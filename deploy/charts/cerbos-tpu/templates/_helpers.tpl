{{- define "cerbos-tpu.fullname" -}}
{{- printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- define "cerbos-tpu.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
{{- end -}}
