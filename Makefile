# Developer / CI entry points. The native module is optional at runtime
# (every caller degrades to the pure-Python path) but CI must prove BOTH
# legs: `test-transport` runs the ticket-queue suites with the module
# built and again with CERBOS_TPU_NO_NATIVE=1 so the uds fallback and the
# stdlib codecs stay honest.
PYTHON ?= python3
PYTEST_FLAGS ?= -q -p no:cacheprovider

TRANSPORT_TESTS := tests/test_shm_transport.py tests/test_ipc.py tests/test_latency_budget.py
OVERLOAD_TESTS := tests/test_overload.py
PLAN_TESTS := tests/test_plan_batch.py
ROLLOUT_TESTS := tests/test_rollout.py
PROVENANCE_TESTS := tests/test_provenance.py
# the native-touching suites: codec round-trips, frame rings, truncation fuzz
ASAN_TESTS := tests/test_native.py tests/test_shm_transport.py

.PHONY: all native native-asan clean test test-transport test-overload \
	test-plan test-rollout test-provenance test-native-asan lint

all: native

native:
	$(MAKE) -C native PYTHON=$(PYTHON)

clean:
	$(MAKE) -C native clean

# tier-1: the full fast suite (slow-marked tests excluded)
test: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ $(PYTEST_FLAGS) -m 'not slow'

# both transport legs: shm granted (native present) and uds fallback
# (native disabled) — the second leg must PASS, not skip-collapse, because
# the suites parametrize/guard on native availability themselves.
test-transport: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(TRANSPORT_TESTS) $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(TRANSPORT_TESTS) $(PYTEST_FLAGS)

# overload suite on both codec legs: admission refusals ride the ERR-frame
# path through the native shm codec when present, and through the uds
# marshal fallback when it is not — both must carry pclass + retry intact.
test-overload: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(OVERLOAD_TESTS) $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(OVERLOAD_TESTS) $(PYTEST_FLAGS)

# batched PlanResources suite (-m plan_batch) on both codec legs: plan
# refusals surface through the same reply codec as check refusals, so the
# chaos leg (plan shed loses zero check requests) must hold with the
# native shm codec present and with the uds marshal fallback.
test-plan: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(PLAN_TESTS) $(PYTEST_FLAGS) -m plan_batch
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(PLAN_TESTS) $(PYTEST_FLAGS) -m plan_batch

# safe-rollout chaos drills on both codec legs: the epoch stamp crosses
# the ticket queue inside STATUS/reply frames, so the mixed-epoch and
# bounded-skew invariants must hold with the native shm codec present and
# with the uds marshal fallback.
test-rollout: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(ROLLOUT_TESTS) $(PYTEST_FLAGS) -m rollout
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(ROLLOUT_TESTS) $(PYTEST_FLAGS) -m rollout

# decision-provenance suite on both codec legs: the winning-rule column
# crosses the ticket queue inside reply frames (native codec v2 and the
# marshal fallback), so rule attribution must survive both encodings.
test-provenance: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(PROVENANCE_TESTS) $(PYTEST_FLAGS) -m provenance
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(PROVENANCE_TESTS) $(PYTEST_FLAGS) -m provenance

# ASan/UBSan leg: rebuild the native module instrumented, run the suites
# that exercise the C++ codec/ring paths (incl. the truncation fuzzers),
# then drop the instrumented .so so ordinary runs don't need the preload.
# python itself isn't ASan-built, so libasan must be preloaded; interpreter-
# level allocations are out of scope, hence detect_leaks=0.
ASAN_LIB := $(shell gcc -print-file-name=libasan.so)

native-asan:
	$(MAKE) -C native asan PYTHON=$(PYTHON)

test-native-asan: native-asan
	JAX_PLATFORMS=cpu LD_PRELOAD=$(ASAN_LIB) \
		ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
		$(PYTHON) -m pytest $(ASAN_TESTS) $(PYTEST_FLAGS)
	$(MAKE) -C native clean

# repo-wide static hygiene (satellite of the analyzer PR): ruff config
# lives in pyproject.toml so editors and CI agree on one rule set.
lint:
	ruff check .
