# Developer / CI entry points. The native module is optional at runtime
# (every caller degrades to the pure-Python path) but CI must prove BOTH
# legs: `test-transport` runs the ticket-queue suites with the module
# built and again with CERBOS_TPU_NO_NATIVE=1 so the uds fallback and the
# stdlib codecs stay honest.
PYTHON ?= python3
PYTEST_FLAGS ?= -q -p no:cacheprovider

TRANSPORT_TESTS := tests/test_shm_transport.py tests/test_ipc.py tests/test_latency_budget.py
OVERLOAD_TESTS := tests/test_overload.py

.PHONY: all native clean test test-transport test-overload

all: native

native:
	$(MAKE) -C native PYTHON=$(PYTHON)

clean:
	$(MAKE) -C native clean

# tier-1: the full fast suite (slow-marked tests excluded)
test: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ $(PYTEST_FLAGS) -m 'not slow'

# both transport legs: shm granted (native present) and uds fallback
# (native disabled) — the second leg must PASS, not skip-collapse, because
# the suites parametrize/guard on native availability themselves.
test-transport: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(TRANSPORT_TESTS) $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(TRANSPORT_TESTS) $(PYTEST_FLAGS)

# overload suite on both codec legs: admission refusals ride the ERR-frame
# path through the native shm codec when present, and through the uds
# marshal fallback when it is not — both must carry pclass + retry intact.
test-overload: native
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest $(OVERLOAD_TESTS) $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu CERBOS_TPU_NO_NATIVE=1 $(PYTHON) -m pytest $(OVERLOAD_TESTS) $(PYTEST_FLAGS)
