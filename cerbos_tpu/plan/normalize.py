"""Filter-AST normalisation and multi-action merging.

Behavioral reference: internal/ruletable/planner/ast.go:594-806
(normaliseFilter / normaliseFilterExprOpExpr / normaliseInExpr) and
merge.go:14-48 (MergeWithAnd). Operates on the Operand/Expr wire tree:

- `in` over a 1-element list/map → `eq`; over an empty one → false; over a
  non-collection value → `eq`
- and/or: literal true/false operands drop out or short-circuit, duplicate
  operands (by canonical JSON) collapse, single-operand and/or unwraps
- not of a literal bool folds
- a filter that normalises to a literal bool becomes
  ALWAYS_ALLOWED/ALWAYS_DENIED
- multiple per-action filters AND together after dedup, sorted by their
  string form; any ALWAYS_DENIED wins, ALWAYS_ALLOWED drops out
"""

from __future__ import annotations

import json
from typing import Optional

from .types import (
    KIND_ALWAYS_ALLOWED,
    KIND_ALWAYS_DENIED,
    KIND_CONDITIONAL,
    Expr,
    Operand,
)


def _as_bool(op: Optional[Operand]) -> Optional[bool]:
    if op is not None and op.expression is None and op.variable is None and isinstance(op.value, bool):
        return op.value
    return None


_TRUE = Operand.val(True)
_FALSE = Operand.val(False)


def _canon(op: Operand) -> str:
    return json.dumps(op.to_json(), sort_keys=True)


_ABBREVS = {
    "P": "request.principal",
    "R": "request.resource",
    "C": "constants",
    "V": "variables",
    "G": "globals",
}


def expand_abbrev(s: str) -> str:
    """conditions/cel.go ExpandAbbrev: P/R/C/V/G prefixes → full idents."""
    prefix, dot, rest = s.partition(".")
    expanded = _ABBREVS.get(prefix, prefix)
    return f"{expanded}.{rest}" if dot else expanded


def normalise_operand(op: Optional[Operand]) -> Optional[Operand]:
    if op is None:
        return op
    if op.expression is None:
        if op.variable is not None:
            return Operand(variable=expand_abbrev(op.variable))
        return op
    expr = op.expression

    if expr.op == "in" and len(expr.operands) == 2:
        simplified, expr = _normalise_in(expr)
        if simplified is not None:
            return simplified

    logical = expr.op if expr.op in ("and", "or", "not") else ""
    seen: set[str] = set()
    operands: list[Operand] = []
    for o in expr.operands:
        n = normalise_operand(o)
        if n is None:
            continue
        if logical:
            b = _as_bool(n)
            if b is not None:
                if logical == "and" and b:
                    continue
                if logical == "or" and not b:
                    continue
                if logical == "and":
                    return _FALSE
                if logical == "or":
                    return _TRUE
            if logical != "not":
                # dedup by the NORMALISED operand: the reference normalises
                # protos in place, so its HashPB(op) sees post-normalisation
                # content (ast.go:694-701)
                key = _canon(n)
                if key in seen:
                    continue
                seen.add(key)
        operands.append(n)

    if logical:
        if not operands:
            if logical == "and":
                return _TRUE
            if logical == "or":
                return _FALSE
            return None
        if len(operands) == 1:
            if logical in ("and", "or"):
                return operands[0]
            b = _as_bool(operands[0])
            if b is not None:
                return Operand.val(not b)

    return Operand(expression=Expr(op=expr.op, operands=operands))


def _normalise_in(expr: Expr) -> tuple[Optional[Operand], Expr]:
    """ast.go:753-795 — → (replacement, possibly-rewritten expr). Builds a
    fresh Expr instead of mutating, keeping normalise_operand pure."""
    rhs = expr.operands[1]
    if rhs.expression is not None or rhs.variable is not None:
        return None, expr
    v = rhs.value
    if isinstance(v, dict):
        if len(v) == 0:
            return _FALSE, expr
        if len(v) == 1:
            expr = Expr(op="eq", operands=[expr.operands[0], Operand.val(next(iter(v)))])
    elif isinstance(v, list):
        if len(v) == 0:
            return _FALSE, expr
        if len(v) == 1:
            expr = Expr(op="eq", operands=[expr.operands[0], Operand.val(v[0])])
    else:
        expr = Expr(op="eq", operands=list(expr.operands))
    return None, expr


def normalise_filter(kind: str, condition: Optional[Operand]) -> tuple[str, Optional[Operand]]:
    """→ (kind, condition), folding literal-bool conditions into the kind."""
    if kind != KIND_CONDITIONAL:
        return kind, None
    condition = normalise_operand(condition)
    if condition is None:
        return KIND_ALWAYS_ALLOWED, None
    b = _as_bool(condition)
    if b is not None:
        return (KIND_ALWAYS_ALLOWED, None) if b else (KIND_ALWAYS_DENIED, None)
    return KIND_CONDITIONAL, condition


def merge_with_and(filters: list[tuple[str, Optional[Operand]]]) -> tuple[str, Optional[Operand]]:
    """merge.go MergeWithAnd: per-action filters → one filter.

    Dedup/sort key is the filter debug string (`Operand.debug_str`), the
    analogue of the reference's FilterToString key, so merged multi-action
    AND operands come out in the same order the reference renders."""
    conds: dict[str, Operand] = {}
    for kind, cond in filters:
        if kind == KIND_ALWAYS_ALLOWED:
            continue
        if kind == KIND_ALWAYS_DENIED:
            return KIND_ALWAYS_DENIED, None
        assert cond is not None
        conds[cond.debug_str()] = cond
    if not conds:
        return KIND_ALWAYS_ALLOWED, None
    if len(conds) == 1:
        return KIND_CONDITIONAL, next(iter(conds.values()))
    operands = [conds[k] for k in sorted(conds)]
    return KIND_CONDITIONAL, Operand(expression=Expr(op="and", operands=operands))


def filter_to_string(kind: str, condition: Optional[Operand]) -> str:
    """planner/ast.go FilterToString: canonical debug rendering of a filter."""
    if kind == KIND_ALWAYS_ALLOWED:
        return "(true)"
    if kind == KIND_ALWAYS_DENIED:
        return "(false)"
    if kind == KIND_CONDITIONAL:
        return _op_to_string(condition)
    return ""


def _op_to_string(op: Optional[Operand]) -> str:
    if op is None:
        return ""
    if op.expression is not None:
        inner = " ".join(_op_to_string(o) for o in op.expression.operands)
        return f"({op.expression.op} {inner})"
    if op.variable is not None:
        return op.variable
    return _compact_value(op.value)


def _compact_value(v) -> str:
    """protojson-compact Value rendering (whole floats print as ints)."""
    import json as _json

    def compact(x):
        if isinstance(x, bool) or x is None or isinstance(x, str):
            return x
        if isinstance(x, float) and x.is_integer():
            return int(x)
        if isinstance(x, list):
            return [compact(i) for i in x]
        if isinstance(x, dict):
            return {k: compact(i) for k, i in x.items()}
        return x

    return _json.dumps(compact(v), separators=(",", ":"), ensure_ascii=False)
