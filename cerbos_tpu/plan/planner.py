"""PlanResources: symbolic policy evaluation → filter AST.

Behavioral reference: internal/ruletable/plan.go (role/scope loops mirroring
check) and internal/ruletable/planner (partial evaluation, ALLOW/DENY filter
combination, multi-action MergeWithAnd — merge.go). Per action and role:
``(OR allow-residuals) AND NOT (OR deny-residuals)``; principal policies
take precedence (a principal DENY blocks regardless of resource policy);
multiple requested actions AND together.
"""

from __future__ import annotations

from typing import Any, Optional

from .. import namer
from ..cel import ast as A
from ..cel.errors import CelError
from ..engine import types as T
from ..ruletable.check import EvalContext, build_request_messages
from ..policy.model import (
    SCOPE_PERMISSIONS_OVERRIDE_PARENT,
    SCOPE_PERMISSIONS_REQUIRE_PARENTAL_CONSENT,
)
from ..ruletable.rows import KIND_PRINCIPAL, KIND_RESOURCE
from ..ruletable.table import RuleTable
from .partial import PartialEvaluator, Residual
from .types import (
    KIND_ALWAYS_ALLOWED,
    KIND_ALWAYS_DENIED,
    KIND_CONDITIONAL,
    Operand,
    PlanInput,
    PlanOutput,
)

TRUE = object()
FALSE = object()
# node results: TRUE | FALSE | A.Node (residual)


def _or(nodes: list[Any]) -> Any:
    """n-ary OR (MkOrLogicalOperation: one LO node with n operands)."""
    out: list[A.Node] = []
    for n in nodes:
        if n is TRUE:
            return TRUE
        if n is FALSE:
            continue
        out.append(n)
    if not out:
        return FALSE
    if len(out) == 1:
        return out[0]
    return A.Call("_||_", tuple(out))


def _and(nodes: list[Any]) -> Any:
    """n-ary AND (MkAndLogicalOperation: one LO node with n operands)."""
    out: list[A.Node] = []
    for n in nodes:
        if n is FALSE:
            return FALSE
        if n is TRUE:
            continue
        out.append(n)
    if not out:
        return TRUE
    if len(out) == 1:
        return out[0]
    return A.Call("_&&_", tuple(out))


def _not(n: Any) -> Any:
    if n is TRUE:
        return FALSE
    if n is FALSE:
        return TRUE
    if isinstance(n, A.Call) and n.fn == "!_":
        return n.args[0]
    return A.Call("!_", (n,))


class Planner:
    def __init__(self, rule_table: RuleTable, schema_mgr: Any = None):
        self.rt = rule_table
        self.schema_mgr = schema_mgr

    def plan(self, input: PlanInput, params: Optional[T.EvalParams] = None) -> PlanOutput:
        from ..observability import start_span

        with start_span("engine.Plan", resource_kind=input.resource_kind):
            return self._plan(input, params)

    def _plan(self, input: PlanInput, params: Optional[T.EvalParams] = None) -> PlanOutput:
        params = params or T.EvalParams()
        rt = self.rt

        principal_scope = T.effective_scope(input.principal.scope, params)
        principal_version = T.effective_version(input.principal.policy_version, params)
        resource_scope = T.effective_scope(input.resource_scope, params)
        resource_version = T.effective_version(input.resource_policy_version, params)

        p_scopes, _, _ = rt.get_all_scopes(
            KIND_PRINCIPAL, principal_scope, input.principal.id, principal_version, params.lenient_scope_search
        )
        r_scopes, _, r_fqn = rt.get_all_scopes(
            KIND_RESOURCE, resource_scope, input.resource_kind, resource_version, params.lenient_scope_search
        )

        output = PlanOutput(
            request_id=input.request_id,
            actions=list(input.actions),
            kind=KIND_ALWAYS_DENIED,
            resource_kind=input.resource_kind,
            # echo the request's version verbatim (engine PlanResourcesOutput
            # does NOT substitute the "default" fallback; an omitted request
            # version stays omitted in the response)
            policy_version=input.resource_policy_version,
            scope=resource_scope,
            include_meta=input.include_meta,
        )
        if not p_scopes and not r_scopes:
            output.policy_match = False
            return output

        # schema validation of the principal (resource attrs are partial)
        if self.schema_mgr is not None:
            check_in = T.CheckInput(
                principal=input.principal,
                resource=T.Resource(kind=input.resource_kind, id="", attr=dict(input.resource_attr)),
                actions=list(input.actions),
                aux_data=input.aux_data,
            )
            errors, reject = self.schema_mgr.validate_check_input(
                rt.get_schema(r_fqn), check_in, resource_ignore_required=True
            )
            output.validation_errors = errors
            if reject:
                return output

        pe = self._partial_evaluator(input, params)
        sanitized = namer.sanitize(input.resource_kind)

        from .normalize import merge_with_and, normalise_filter

        action_filters: list[tuple[str, Optional[Any]]] = []
        dr_lists: dict[str, Any] = {}  # scope → derived-roles list, shared across actions
        effective_policies: dict[str, dict] = {}
        any_match = False
        for action in dict.fromkeys(input.actions):
            node, matched_scope, matched = self._plan_action(
                pe, input, params, action, sanitized, resource_version, resource_scope, p_scopes, r_scopes, dr_lists,
                effective_policies,
            )
            if node is TRUE:
                action_filters.append((KIND_ALWAYS_ALLOWED, None))
            elif node is FALSE:
                action_filters.append((KIND_ALWAYS_DENIED, None))
            else:
                action_filters.append(normalise_filter(KIND_CONDITIONAL, ast_to_operand(node)))
            output.matched_scopes[action] = matched_scope
            any_match = any_match or matched

        output.kind, output.condition = merge_with_and(action_filters)
        output.policy_match = any_match
        output.effective_policies = {
            namer.policy_key_from_fqn(f): attrs for f, attrs in effective_policies.items()
        }
        return output

    def _partial_evaluator(self, input: PlanInput, params: T.EvalParams):
        check_in = T.CheckInput(
            principal=input.principal,
            resource=T.Resource(
                kind=input.resource_kind,
                id="",
                attr=dict(input.resource_attr),
                scope=input.resource_scope,
                policy_version=input.resource_policy_version,
            ),
            actions=list(input.actions),
            aux_data=input.aux_data,
        )
        request, principal, resource = build_request_messages(check_in)
        ec = EvalContext(params, request, principal, resource)
        act = ec.activation({}, {})

        def make(known_attrs: dict[str, Any], var_defs: dict[str, A.Node], constants: dict[str, Any], drl=None):
            consts_act = ec.activation(constants, {})
            return PartialEvaluator(consts_act, known_attrs, var_defs, derived_roles_list=drl)

        return make

    def _plan_action(
        self, pe_factory, input: PlanInput, params, action, sanitized, resource_version, resource_scope, p_scopes, r_scopes, dr_lists,
        effective_policies: Optional[dict] = None,
    ) -> tuple[Any, str]:
        """One action → TRUE/FALSE/residual node.

        Faithful port of the plan.go:100-371 walk: resource policies first,
        then principal; per role: per scope allow/deny nodes with
        role-policy denies tracked separately, child-OVERRIDE_PARENT allows
        gating parent denies, REQUIRE_PARENTAL_CONSENT pending allows, const
        deny collapsing the role to false, role-policy denies ANDed into the
        role allow; across policy types allow ORs into the root and deny
        inverts and ANDs (plan.go:336-359); no policy-type allow at all →
        unconditional deny.
        """
        rt = self.rt
        known = {str(k): v for k, v in input.resource_attr.items()}
        matched_scope = ""
        roles = input.principal.roles or [""]

        def is_true(n) -> bool:
            return n is TRUE or (isinstance(n, A.Lit) and n.value is True)

        def is_false(n) -> bool:
            return n is FALSE or (isinstance(n, A.Lit) and n.value is False)

        def to_node(n) -> A.Node:
            if n is TRUE:
                return A.Lit(True)
            if n is FALSE:
                return A.Lit(False)
            return n

        def or2(a, b):
            return A.Call("_||_", (to_node(a), to_node(b)))

        def and2(a, b):
            return A.Call("_&&_", (to_node(a), to_node(b)))

        def add_node(curr, nxt, combine):
            if nxt is None:
                return curr
            if curr is None:
                return nxt
            return combine(curr, nxt)

        def invert(n):
            """InvertNodeBooleanValue (planner.go:285-304)."""
            if is_true(n):
                return FALSE
            if is_false(n):
                return TRUE
            if isinstance(n, A.Call) and n.fn == "!_":
                if len(n.args) == 1:
                    return n.args[0]
            return A.Call("!_", (to_node(n),))

        def gate_by_child_override(child_allow, deny):
            """gateByChildOverrideAllow (plan.go:405-415)."""
            if deny is None or child_allow is None:
                return deny
            inv = invert(child_allow)
            if is_true(deny):
                return inv
            return and2(inv, deny)

        def derived_roles_list(scope: str):
            """Sorted (name, condition-node) pairs for runtime.effectiveDerivedRoles
            substitution (plan.go:144-183, planner.go:831-851)."""
            if scope in dr_lists:
                return dr_lists[scope]
            out = []
            drs = rt.get_derived_roles(
                namer.resource_policy_fqn(input.resource_kind, resource_version, scope)
            )
            if drs:
                principal_parent_roles = set(
                    rt.idx.add_parent_roles([resource_scope], list(input.principal.roles))
                )
                for name in sorted(drs):
                    dr = drs[name]
                    if "*" not in dr.parent_roles and not (dr.parent_roles & principal_parent_roles):
                        continue
                    node = self._derived_role_node(pe_factory, known, dr)
                    if node is TRUE:
                        node = A.Lit(True)
                    elif node is FALSE:
                        node = A.Lit(False)
                    out.append((name, node))
            dr_lists[scope] = out
            return out

        root = None
        has_pt_allow = False
        for pt in (KIND_RESOURCE, KIND_PRINCIPAL):
            pt_allow = None
            pt_deny = None
            scopes = p_scopes if pt == KIND_PRINCIPAL else r_scopes

            for role_idx, role in enumerate(roles):
                if role_idx > 0 and pt == KIND_PRINCIPAL:
                    break
                role_allow = None
                role_deny = None
                role_deny_rp = None
                pending_allow = False
                child_override_allow = None
                parent_roles = rt.idx.add_parent_roles([resource_scope], [role])

                for scope in scopes:
                    if child_override_allow is not None and is_true(child_override_allow):
                        break
                    scope_allow = None
                    scope_deny = None
                    scope_deny_rp = None
                    drl = derived_roles_list(scope) if pt == KIND_RESOURCE else []
                    pid = input.principal.id if pt == KIND_PRINCIPAL else ""
                    rows = rt.idx.query(resource_version, sanitized, scope, action, parent_roles, pt, pid)
                    for b in rows:
                        if effective_policies is not None:
                            # every QUERIED binding's policy chain lands in the
                            # audit trail, matching plan.go's
                            # maps.Copy(effectivePolicies, GetSourceAttributes())
                            for f, attrs in rt.get_chain_source_attributes(b.origin_fqn).items():
                                effective_policies.setdefault(f, dict(attrs))
                        node = self._binding_node(pe_factory, known, drl, b)
                        if b.effect == "EFFECT_ALLOW":
                            scope_allow = add_node(scope_allow, node, or2)
                        elif b.effect == "EFFECT_DENY":
                            if is_false(node):
                                continue
                            if b.from_role_policy:
                                scope_deny_rp = add_node(scope_deny_rp, node, or2)
                            else:
                                scope_deny = add_node(scope_deny, node, or2)

                    scope_deny = gate_by_child_override(child_override_allow, scope_deny)
                    scope_deny_rp = gate_by_child_override(child_override_allow, scope_deny_rp)
                    role_deny = add_node(role_deny, scope_deny, or2)
                    role_deny_rp = add_node(role_deny_rp, scope_deny_rp, or2)

                    sp = rt.get_scope_scope_permissions(scope)
                    if scope_allow is not None:
                        if role_allow is None:
                            role_allow = scope_allow
                        elif pending_allow:
                            role_allow = and2(role_allow, scope_allow)
                            pending_allow = False
                        else:
                            role_allow = or2(role_allow, scope_allow)
                        if sp == SCOPE_PERMISSIONS_REQUIRE_PARENTAL_CONSENT:
                            pending_allow = True

                    if (
                        (scope_deny is not None or scope_deny_rp is not None or scope_allow is not None)
                        and sp == SCOPE_PERMISSIONS_OVERRIDE_PARENT
                    ):
                        matched_scope = scope
                    if scope_allow is not None and sp == SCOPE_PERMISSIONS_OVERRIDE_PARENT:
                        child_override_allow = add_node(child_override_allow, scope_allow, or2)

                # an ALLOW pending parental consent with no parent match → no allow
                if pending_allow:
                    role_allow = None

                const_deny = (role_deny is not None and is_true(role_deny)) or (
                    role_deny_rp is not None and is_true(role_deny_rp)
                )
                if const_deny:
                    # role independence: fold the const DENY into this role's
                    # allow so other roles can still override (plan.go:302-312)
                    role_allow = FALSE
                    role_deny = None
                    role_deny_rp = None
                elif role_allow is not None and role_deny is None and role_deny_rp is None and is_true(role_allow):
                    pt_allow = role_allow
                    pt_deny = None
                    break

                if role_deny_rp is not None and role_allow is not None:
                    role_allow = and2(role_allow, invert(role_deny_rp))

                pt_allow = add_node(pt_allow, role_allow, or2)
                pt_deny = add_node(pt_deny, role_deny, or2)

            if pt_allow is not None:
                has_pt_allow = True
                root = pt_allow if root is None else or2(pt_allow, root)
            if pt_deny is not None:
                inv = invert(pt_deny)
                root = inv if root is None else and2(inv, root)

        matched = root is not None
        if root is None or not has_pt_allow:
            return FALSE, matched_scope, matched
        if is_true(root):
            return TRUE, matched_scope, matched
        if is_false(root):
            return FALSE, matched_scope, matched
        return to_node(root), matched_scope, matched

    # -- condition evaluation seams ---------------------------------------
    # BatchPlanner (plan/batch.py) overrides these two to try the device
    # ternary verdict before falling back to symbolic partial evaluation;
    # the sequential walk above is byte-identical either way.

    def _binding_node(self, pe_factory, known, drl, b):
        """One rule binding → TRUE/FALSE/residual node."""
        pe = self._pe_for(pe_factory, known, b.params, drl)
        node = self._cond_node(pe, b.condition)
        if b.derived_role_condition is not None:
            dr_pe = self._pe_for(pe_factory, known, b.derived_role_params, drl)
            dr_node = self._cond_node(dr_pe, b.derived_role_condition)
            node = dr_node if b.condition is None else _and([node, dr_node])
        return node

    def _derived_role_node(self, pe_factory, known, dr):
        """One derived-role definition → TRUE/FALSE/residual node."""
        dr_pe = self._pe_for(pe_factory, known, dr.params, None)
        return self._cond_node(dr_pe, dr.condition)

    def _pe_for(self, pe_factory, known, params_obj, drl) -> PartialEvaluator:
        var_defs = {}
        constants = {}
        if params_obj is not None:
            var_defs = {v.name: v.expr.node for v in params_obj.ordered_variables}
            constants = params_obj.constants
        return pe_factory(known, var_defs, constants, drl)

    def _cond_node(self, pe: PartialEvaluator, cond) -> Any:
        """CompiledCondition → TRUE/FALSE/residual node via partial eval."""
        if cond is None:
            return TRUE
        if cond.kind == "expr":
            try:
                r = pe.run(cond.expr.node)
            except CelError:
                return FALSE
            if isinstance(r, Residual):
                return r.node
            return TRUE if r is True else FALSE
        children = [self._cond_node(pe, c) for c in cond.children]
        if cond.kind == "all":
            return _and(children)
        if cond.kind == "any":
            return _or(children)
        if cond.kind == "none":
            # NOT distributes over the children: none{a,b} → !a && !b
            # (planner.go:365-393, InvertNodeBooleanValue per child)
            parts: list[Any] = []
            for c in children:
                if c is TRUE:
                    return FALSE
                if c is FALSE:
                    continue
                parts.append(_not(c))
            return _and(parts)
        raise ValueError(f"unknown condition kind {cond.kind}")


# ---------------------------------------------------------------------------
# residual AST → filter expression tree
#
# Behavioral reference: internal/ruletable/planner/ast.go buildExprImpl
# (operator names via opFromCLE ast.go:62-101; has() → literal true
# ast.go:395-397; `x in <map>` rewrites the RHS to its sorted key list
# ast.go:464-477 + structKeys; struct → set-field entries ast.go:478-497)
# and lambda.go / mkNode (comprehension → op(range, lambda(...)), the
# iteration range of a non-transform op over a map becomes its key list,
# ast.go:538-546).

_OP_NAMES = {
    "_==_": "eq", "_!=_": "ne", "_<_": "lt", "_<=_": "le", "_>_": "gt", "_>=_": "ge",
    "_&&_": "and", "_||_": "or", "!_": "not", "_in_": "in",
    "_+_": "add", "_-_": "sub", "_*_": "mult", "_/_": "div", "_%_": "mod", "-_": "neg",
    "_[_]": "index", "_?_:_": "if",
}

_COMPREHENSION_OPS = {
    "all": "all", "exists": "exists", "exists_one": "exists_one",
    "filter": "filter", "map": "map", "transform_list": "transformList",
    "transform_map": "transformMap", "transform_map_entry": "transformMapEntry",
    "sort_by": "sortBy",
}

_STRUCT_OPS = {"transformList", "transformMap", "transformMapEntry"}


def _map_keys_operand(node: A.Node) -> Optional[Operand]:
    """Map-typed node → list-of-keys operand (structKeys: sorted), or None."""
    if isinstance(node, A.Lit) and isinstance(node.value, dict):
        keys = sorted(node.value.keys(), key=str)
        from ..util import normalize_attr

        return Operand.val([normalize_attr(k) for k in keys])
    if isinstance(node, A.MapLit):
        entries = sorted(node.entries, key=lambda kv: repr(kv[0]))
        keys = [ast_to_operand(k) for k, _ in entries]
        if all(o.expression is None and o.variable is None for o in keys):
            return Operand.val([o.value for o in keys])
        return Operand.expr("list", *keys)
    return None


def ast_to_operand(node: A.Node) -> Operand:
    """Residual CEL AST → PlanResourcesFilter operand tree (the wire format
    list endpoints consume)."""
    if isinstance(node, A.Lit):
        v = node.value
        from ..util import normalize_attr

        if isinstance(v, dict):
            # residual map values surface as struct expressions (ast.go:478)
            ops = [
                Operand.expr("set-field", Operand.val(normalize_attr(k)), Operand.val(normalize_attr(x)))
                for k, x in v.items()
            ]
            return Operand.expr("struct", *ops)
        return Operand.val(normalize_attr(v))
    if isinstance(node, A.Present):
        # has() in a residual converts to literal true (ast.go:395-397)
        return Operand.val(True)
    if isinstance(node, (A.Select, A.Index, A.Ident)):
        var = _variable_name(node)
        if var is not None:
            return Operand.var(var)
        if isinstance(node, A.Index):
            return Operand.expr("index", ast_to_operand(node.operand), ast_to_operand(node.index))
        if isinstance(node, A.Select):
            return Operand.expr("get-field", ast_to_operand(node.operand), Operand.var(node.field))
        raise ValueError(f"cannot convert {node} to filter operand")
    if isinstance(node, A.ListLit):
        items = [ast_to_operand(x) for x in node.items]
        if all(o.expression is None and o.variable is None for o in items):
            return Operand.val([o.value for o in items])
        return Operand.expr("list", *items)
    if isinstance(node, A.MapLit):
        ops = []
        for k, v in node.entries:
            ops.append(Operand.expr("set-field", ast_to_operand(k), ast_to_operand(v)))
        return Operand.expr("struct", *ops)
    if isinstance(node, A.Comprehension):
        return _comprehension_to_operand(node)
    if isinstance(node, A.Bind):
        # cel.bind residual: inline the bound value (shadow-aware, recurses
        # into comprehensions — see partial._substitute_many)
        from .partial import _substitute_many

        return ast_to_operand(_substitute_many(node.body, {node.name: node.init}))
    if isinstance(node, A.Call):
        if node.fn == "_in_" and len(node.args) == 2:
            keys = _map_keys_operand(node.args[1])
            if keys is not None:
                return Operand.expr("in", ast_to_operand(node.args[0]), keys)
        op = _OP_NAMES.get(node.fn, node.fn)
        operands = []
        if node.target is not None:
            operands.append(ast_to_operand(node.target))
        operands.extend(ast_to_operand(a) for a in node.args)
        return Operand.expr(op, *operands)
    raise ValueError(f"cannot convert {type(node).__name__} to filter operand")


def _comprehension_to_operand(node: A.Comprehension) -> Operand:
    """Comprehension → op(iterRange, lambda(expr[, expr2], vars...))."""
    op = _COMPREHENSION_OPS.get(node.kind)
    if op is None:
        raise ValueError(f"cannot convert comprehension kind {node.kind}")
    # 3-arg map (map with predicate) surfaces as transformList (lambda.go:96-104)
    expr, expr2 = node.step, None
    if node.step2 is not None:
        if node.kind == "map":
            op = "transformList"
        expr, expr2 = node.step2, node.step
    lambda_args = [ast_to_operand(expr)]
    if expr2 is not None:
        lambda_args.append(ast_to_operand(expr2))
    lambda_args.append(Operand.var(node.iter_var))
    if node.iter_var2:
        lambda_args.append(Operand.var(node.iter_var2))
    iter_range = node.iter_range
    range_op = None
    if op not in _STRUCT_OPS:
        range_op = _map_keys_operand(iter_range)
    if range_op is None:
        range_op = ast_to_operand(iter_range)
    return Operand.expr(op, range_op, Operand.expr("lambda", *lambda_args))


def _variable_name(node: A.Node) -> Optional[str]:
    segs: list[str] = []
    cur = node
    while True:
        if isinstance(cur, A.Select):
            segs.append(cur.field)
            cur = cur.operand
        elif isinstance(cur, A.Ident):
            root = cur.name
            if root == "R":
                return ".".join(["request", "resource"] + list(reversed(segs)))
            if root == "P":
                return ".".join(["request", "principal"] + list(reversed(segs)))
            if root == "request":
                return ".".join(["request"] + list(reversed(segs)))
            # compound dotted variable (e.g. a comprehension iteration var)
            return ".".join([root] + list(reversed(segs)))
        else:
            return None
