"""PlanResources: symbolic policy evaluation → filter AST.

Behavioral reference: internal/ruletable/plan.go (role/scope loops mirroring
check) and internal/ruletable/planner (partial evaluation, ALLOW/DENY filter
combination, multi-action MergeWithAnd — merge.go). Per action and role:
``(OR allow-residuals) AND NOT (OR deny-residuals)``; principal policies
take precedence (a principal DENY blocks regardless of resource policy);
multiple requested actions AND together.
"""

from __future__ import annotations

from typing import Any, Optional

from .. import namer
from ..cel import ast as A
from ..cel.errors import CelError
from ..engine import types as T
from ..ruletable.check import EvalContext, build_request_messages
from ..ruletable.rows import KIND_PRINCIPAL, KIND_RESOURCE
from ..ruletable.table import RuleTable
from .partial import PartialEvaluator, Residual
from .types import (
    KIND_ALWAYS_ALLOWED,
    KIND_ALWAYS_DENIED,
    KIND_CONDITIONAL,
    Operand,
    PlanInput,
    PlanOutput,
)

TRUE = object()
FALSE = object()
# node results: TRUE | FALSE | A.Node (residual)


def _or(nodes: list[Any]) -> Any:
    out: list[A.Node] = []
    for n in nodes:
        if n is TRUE:
            return TRUE
        if n is FALSE:
            continue
        out.append(n)
    if not out:
        return FALSE
    res = out[0]
    for n in out[1:]:
        res = A.Call("_||_", (res, n))
    return res


def _and(nodes: list[Any]) -> Any:
    out: list[A.Node] = []
    for n in nodes:
        if n is FALSE:
            return FALSE
        if n is TRUE:
            continue
        out.append(n)
    if not out:
        return TRUE
    res = out[0]
    for n in out[1:]:
        res = A.Call("_&&_", (res, n))
    return res


def _not(n: Any) -> Any:
    if n is TRUE:
        return FALSE
    if n is FALSE:
        return TRUE
    if isinstance(n, A.Call) and n.fn == "!_":
        return n.args[0]
    return A.Call("!_", (n,))


class Planner:
    def __init__(self, rule_table: RuleTable, schema_mgr: Any = None):
        self.rt = rule_table
        self.schema_mgr = schema_mgr

    def plan(self, input: PlanInput, params: Optional[T.EvalParams] = None) -> PlanOutput:
        params = params or T.EvalParams()
        rt = self.rt

        principal_scope = T.effective_scope(input.principal.scope, params)
        principal_version = T.effective_version(input.principal.policy_version, params)
        resource_scope = T.effective_scope(input.resource_scope, params)
        resource_version = T.effective_version(input.resource_policy_version, params)

        p_scopes, _, _ = rt.get_all_scopes(
            KIND_PRINCIPAL, principal_scope, input.principal.id, principal_version, params.lenient_scope_search
        )
        r_scopes, _, r_fqn = rt.get_all_scopes(
            KIND_RESOURCE, resource_scope, input.resource_kind, resource_version, params.lenient_scope_search
        )

        output = PlanOutput(
            request_id=input.request_id,
            actions=list(input.actions),
            kind=KIND_ALWAYS_DENIED,
            resource_kind=input.resource_kind,
            policy_version=resource_version,
            scope=resource_scope,
            include_meta=input.include_meta,
        )
        if not p_scopes and not r_scopes:
            return output

        # schema validation of the principal (resource attrs are partial)
        if self.schema_mgr is not None:
            check_in = T.CheckInput(
                principal=input.principal,
                resource=T.Resource(kind=input.resource_kind, id="", attr=dict(input.resource_attr)),
                actions=list(input.actions),
                aux_data=input.aux_data,
            )
            errors, reject = self.schema_mgr.validate_check_input(
                rt.get_schema(r_fqn), check_in, principal_only=True
            )
            output.validation_errors = errors
            if reject:
                return output

        pe = self._partial_evaluator(input, params)
        sanitized = namer.sanitize(input.resource_kind)

        action_filters: list[Any] = []
        for action in dict.fromkeys(input.actions):
            node, matched_scope = self._plan_action(
                pe, input, params, action, sanitized, resource_version, resource_scope, p_scopes, r_scopes
            )
            action_filters.append(node)
            output.matched_scopes[action] = matched_scope

        final = _and(action_filters)  # multi-action: MergeWithAnd semantics
        if final is TRUE:
            output.kind = KIND_ALWAYS_ALLOWED
        elif final is FALSE:
            output.kind = KIND_ALWAYS_DENIED
        else:
            output.kind = KIND_CONDITIONAL
            output.condition = ast_to_operand(final)
        return output

    def _partial_evaluator(self, input: PlanInput, params: T.EvalParams):
        check_in = T.CheckInput(
            principal=input.principal,
            resource=T.Resource(kind=input.resource_kind, id="", attr=dict(input.resource_attr)),
            actions=list(input.actions),
            aux_data=input.aux_data,
        )
        request, principal, resource = build_request_messages(check_in)
        ec = EvalContext(params, request, principal, resource)
        act = ec.activation({}, {})

        def make(known_attrs: dict[str, Any], var_defs: dict[str, A.Node], constants: dict[str, Any]):
            consts_act = ec.activation(constants, {})
            return PartialEvaluator(consts_act, known_attrs, var_defs)

        return make

    def _plan_action(
        self, pe_factory, input: PlanInput, params, action, sanitized, resource_version, resource_scope, p_scopes, r_scopes
    ) -> tuple[Any, str]:
        rt = self.rt
        known = {str(k): v for k, v in input.resource_attr.items()}
        matched_scope = ""

        def eval_rows(pt: str, scopes: list[str], role: str, pid: str) -> tuple[list[Any], list[Any], str]:
            allows: list[Any] = []
            denies: list[Any] = []
            first_scope = ""
            parent_roles = rt.idx.add_parent_roles([resource_scope], [role])
            for scope in scopes:
                rows = rt.idx.query(resource_version, sanitized, scope, action, parent_roles, pt, pid)
                for b in rows:
                    var_defs = {}
                    constants = {}
                    if b.params is not None:
                        var_defs = {v.name: v.expr.node for v in b.params.ordered_variables}
                        constants = b.params.constants
                    pe = pe_factory(known, var_defs, constants)
                    node = self._cond_node(pe, b.derived_role_condition, b.derived_role_params, known, pe_factory)
                    if node is FALSE:
                        continue
                    cond_node = self._cond_node(pe, b.condition, b.params, known, pe_factory)
                    combined = _and([node, cond_node])
                    if combined is FALSE:
                        continue
                    if not first_scope:
                        first_scope = scope
                    if b.effect == "EFFECT_ALLOW":
                        allows.append(combined)
                    elif b.effect == "EFFECT_DENY":
                        denies.append(combined)
            return allows, denies, first_scope

        # principal pass (role-agnostic)
        p_allows, p_denies, p_matched = eval_rows(KIND_PRINCIPAL, p_scopes, input.principal.roles[0] if input.principal.roles else "", input.principal.id)

        # resource pass per role, combined with OR (role independence)
        role_filters: list[Any] = []
        r_matched = ""
        for role in input.principal.roles:
            allows, denies, first_scope = eval_rows(KIND_RESOURCE, r_scopes, role, "")
            if not r_matched:
                r_matched = first_scope
            role_filters.append(_and([_or(allows), _not(_or(denies))]))
        r_combined = _or(role_filters)

        final = _and([_not(_or(p_denies)), _or([_or(p_allows), r_combined])])
        matched_scope = p_matched or r_matched
        return final, matched_scope

    def _cond_node(self, pe: PartialEvaluator, cond, params_obj, known, pe_factory) -> Any:
        """CompiledCondition → TRUE/FALSE/residual node via partial eval."""
        if cond is None:
            return TRUE
        if cond.kind == "expr":
            try:
                r = pe.run(cond.expr.node)
            except CelError:
                return FALSE
            if isinstance(r, Residual):
                return r.node
            return TRUE if r is True else FALSE
        children = [self._cond_node(pe, c, params_obj, known, pe_factory) for c in cond.children]
        if cond.kind == "all":
            return _and(children)
        if cond.kind == "any":
            return _or(children)
        if cond.kind == "none":
            return _not(_or(children))
        raise ValueError(f"unknown condition kind {cond.kind}")


# ---------------------------------------------------------------------------
# residual AST → filter expression tree

_OP_NAMES = {
    "_==_": "eq", "_!=_": "ne", "_<_": "lt", "_<=_": "le", "_>_": "gt", "_>=_": "ge",
    "_&&_": "and", "_||_": "or", "!_": "not", "_in_": "in",
    "_+_": "add", "_-_": "sub", "_*_": "mult", "_/_": "div", "_%_": "mod", "-_": "neg",
    "_[_]": "index",
}


def _flatten(node: A.Node, op: str) -> list[A.Node]:
    if isinstance(node, A.Call) and node.fn == op and node.target is None:
        return _flatten(node.args[0], op) + _flatten(node.args[1], op)
    return [node]


def ast_to_operand(node: A.Node) -> Operand:
    """Residual CEL AST → PlanResourcesFilter operand tree (the wire format
    list endpoints consume)."""
    if isinstance(node, A.Lit):
        v = node.value
        from ..util import normalize_attr

        return Operand.val(normalize_attr(v))
    if isinstance(node, (A.Select, A.Index, A.Ident, A.Present)):
        var = _variable_name(node)
        if var is not None:
            return Operand.var(var)
        if isinstance(node, A.Present):
            return Operand.expr("has", ast_to_operand(A.Select(node.operand, node.field)))
        if isinstance(node, A.Index):
            return Operand.expr("index", ast_to_operand(node.operand), ast_to_operand(node.index))
        raise ValueError(f"cannot convert {node} to filter operand")
    if isinstance(node, A.ListLit):
        return Operand.expr("list", *[ast_to_operand(x) for x in node.items])
    if isinstance(node, A.MapLit):
        ops = []
        for k, v in node.entries:
            ops.append(Operand.expr("map-entry", ast_to_operand(k), ast_to_operand(v)))
        return Operand.expr("map", *ops)
    if isinstance(node, A.Call):
        if node.fn in ("_&&_", "_||_"):
            parts = _flatten(node, node.fn)
            return Operand.expr(_OP_NAMES[node.fn], *[ast_to_operand(p) for p in parts])
        op = _OP_NAMES.get(node.fn, node.fn)
        operands = []
        if node.target is not None:
            operands.append(ast_to_operand(node.target))
        operands.extend(ast_to_operand(a) for a in node.args)
        return Operand.expr(op, *operands)
    raise ValueError(f"cannot convert {type(node).__name__} to filter operand")


def _variable_name(node: A.Node) -> Optional[str]:
    segs: list[str] = []
    cur = node
    while True:
        if isinstance(cur, A.Select):
            segs.append(cur.field)
            cur = cur.operand
        elif isinstance(cur, A.Index) and isinstance(cur.index, A.Lit) and isinstance(cur.index.value, str):
            segs.append(cur.index.value)
            cur = cur.operand
        elif isinstance(cur, A.Ident):
            root = cur.name
            if root == "R":
                return ".".join(["request", "resource"] + list(reversed(segs)))
            if root == "P":
                return ".".join(["request", "principal"] + list(reversed(segs)))
            if root == "request":
                return ".".join(["request"] + list(reversed(segs)))
            return None
        else:
            return None
