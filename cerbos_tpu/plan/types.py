"""PlanResources input/output types.

Behavioral reference: api/public/cerbos/engine/v1/engine.proto
(PlanResourcesInput/Filter/Output) and internal/ruletable/planner.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..engine import types as T

KIND_ALWAYS_ALLOWED = "KIND_ALWAYS_ALLOWED"
KIND_ALWAYS_DENIED = "KIND_ALWAYS_DENIED"
KIND_CONDITIONAL = "KIND_CONDITIONAL"


@dataclass
class PlanInput:
    request_id: str
    actions: list[str]
    principal: T.Principal
    resource_kind: str
    resource_attr: dict[str, Any] = field(default_factory=dict)
    resource_policy_version: str = ""
    resource_scope: str = ""
    aux_data: Optional[T.AuxData] = None
    include_meta: bool = False


@dataclass
class Expr:
    """Filter expression node: operator over operands (value/variable/expr)."""

    op: str
    operands: list["Operand"] = field(default_factory=list)


@dataclass
class Operand:
    value: Any = None
    expression: Optional[Expr] = None
    variable: Optional[str] = None

    @classmethod
    def val(cls, v: Any) -> "Operand":
        return cls(value=v)

    @classmethod
    def var(cls, name: str) -> "Operand":
        return cls(variable=name)

    @classmethod
    def expr(cls, op: str, *operands: "Operand") -> "Operand":
        return cls(expression=Expr(op=op, operands=list(operands)))

    def to_json(self) -> dict:
        if self.expression is not None:
            return {
                "expression": {
                    "operator": self.expression.op,
                    "operands": [o.to_json() for o in self.expression.operands],
                }
            }
        if self.variable is not None:
            return {"variable": self.variable}
        return {"value": self.value}

    def debug_str(self) -> str:
        if self.expression is not None:
            inner = " ".join(o.debug_str() for o in self.expression.operands)
            return f"({self.expression.op} {inner})"
        if self.variable is not None:
            return self.variable
        import json

        return json.dumps(self.value)


@dataclass
class PlanOutput:
    request_id: str
    actions: list[str]
    kind: str
    resource_kind: str
    policy_version: str
    scope: str
    condition: Optional[Operand] = None
    matched_scopes: dict[str, str] = field(default_factory=dict)
    validation_errors: list[T.ValidationError] = field(default_factory=list)
    include_meta: bool = False
    # False when NO policy produced a node for any action (plan.go:380-390:
    # FilterDebug reads NO_MATCH instead of the filter string)
    policy_match: bool = True
    # policy key -> source attributes for every queried binding's chain
    # (plan.go: effectivePolicies in the audit trail)
    effective_policies: dict[str, dict] = field(default_factory=dict)

    def to_json(self, call_id: str = "") -> dict:
        filter_j: dict[str, Any] = {"kind": self.kind}
        if self.kind == KIND_CONDITIONAL and self.condition is not None:
            filter_j["condition"] = self.condition.to_json()
        out: dict[str, Any] = {
            "requestId": self.request_id,
            "actions": self.actions,
            "resourceKind": self.resource_kind,
            "filter": filter_j,
        }
        if self.policy_version:  # proto3 JSON omits empty strings
            out["policyVersion"] = self.policy_version
        if self.include_meta:
            if not self.policy_match:
                debug = "NO_MATCH"  # plan.go noPolicyMatch
            elif self.kind == KIND_ALWAYS_ALLOWED:
                debug = "(true)"  # planner/ast.go FilterToString
            elif self.kind == KIND_ALWAYS_DENIED:
                debug = "(false)"
            else:
                debug = self.condition.debug_str() if self.condition is not None else self.kind
            out["meta"] = {
                "filterDebug": debug,
                "matchedScopes": self.matched_scopes,
            }
        if self.validation_errors:
            out["validationErrors"] = [
                {"path": v.path, "message": v.message, "source": v.source} for v in self.validation_errors
            ]
        if call_id:
            out["cerbosCallId"] = call_id
        return out
