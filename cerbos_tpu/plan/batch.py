"""BatchPlanner: vectorized partial evaluation for PlanResources.

Evaluates a batch of plan queries in one device dispatch. Every condition
kernel in the lowered rule table is evaluated over the whole batch exactly
as the check path does — resource attributes the query supplies in
``known_attrs`` are encoded into the SoA columns, everything else encodes
as missing — and each (query, condition) pair then resolves to a ternary
verdict:

* **TRUE / FALSE** — the kernel is statically residualizable
  (``CondKernel.plan_reason is None``) and every resource-rooted dependency
  is known for this query, so the device sat bit equals what concrete host
  evaluation would produce (missing-principal-attr errors collapse to FALSE
  on both paths).
* **RESIDUAL** — anything else: the walk falls back to the sequential
  planner's symbolic :class:`~cerbos_tpu.plan.partial.PartialEvaluator`,
  which produces the identical filter-AST fragment the sequential planner
  would, byte for byte.

The role/scope walk itself is inherited unchanged from :class:`Planner`;
only the two condition-evaluation seams (``_binding_node`` /
``_derived_role_node``) are overridden, so the combination machinery
(``_or``/``_and``/``_not``, gate-by-child-override, RPC pending allows …)
is shared code, not a reimplementation. Routing is decided statically at
compile time (``condcompile.plan_verdict``) — the runtime never guesses.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..engine import types as T
from .planner import FALSE, TRUE, Planner
from .types import PlanInput, PlanOutput

_RESIDUAL_BUCKETS = [0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0]


@dataclass
class _QueryCtx:
    """Per-query routing context, live only while its walk runs."""

    sat_row: Optional[np.ndarray]
    known: frozenset
    oracle: bool  # fallback tag fired while encoding this query's columns
    device_rules: int = 0
    symbolic_rules: int = 0


@dataclass
class BatchStats:
    """Cumulative routing counters (also exported as metrics)."""

    batches: int = 0
    queries: int = 0
    device_queries: int = 0  # resolved without any symbolic fallback
    symbolic_queries: int = 0
    memo_queries: int = 0  # exact duplicates of an earlier query in the batch
    device_rules: int = 0
    symbolic_rules: int = 0

    def as_dict(self) -> dict[str, int]:
        return {
            "batches": self.batches,
            "queries": self.queries,
            "device_queries": self.device_queries,
            "symbolic_queries": self.symbolic_queries,
            "memo_queries": self.memo_queries,
            "device_rules": self.device_rules,
            "symbolic_rules": self.symbolic_rules,
        }


class BatchPlanner(Planner):
    """Plan many (principal, action) queries against one device dispatch.

    Owns its own :class:`LoweredTable` by default (separate compiler and
    string interner, so concurrent check batches never race the plan path;
    pass ``lowered=`` to share one). ``globals_`` must match the globals the
    serving params carry — a mismatched batch routes every query symbolic
    rather than risk a divergent constant fold.
    """

    def __init__(
        self,
        rule_table,
        schema_mgr: Any = None,
        globals_: Optional[dict[str, Any]] = None,
        lowered: Any = None,
        use_jax: bool = False,
    ):
        super().__init__(rule_table, schema_mgr=schema_mgr)
        self._globals = dict(globals_ or {})
        self._lowered = lowered
        self._packer = None
        self._use_jax = use_jax
        self._need_attrs_cache: dict[int, frozenset] = {}
        self._lock = threading.Lock()  # serializes batch encodes
        self._tls = threading.local()  # per-thread query context
        self.stats = BatchStats()
        self._init_metrics()

    #: max per-bucket candidates compared during batch dedup
    DEDUP_SCAN = 8

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_batch = reg.histogram_vec(
            "cerbos_tpu_plan_batch_seconds",
            "Wall time of one batched PlanResources dispatch, by evaluation mode",
            label="mode",
        )
        self.m_queries = reg.counter_vec(
            "cerbos_tpu_plan_queries_total",
            "Plan queries by resolution path: device = every condition resolved "
            "on the ternary device path, symbolic = at least one sequential "
            "PartialEvaluator fallback",
            label="path",
        )
        self.m_residual = reg.histogram(
            "cerbos_tpu_plan_residual_rules",
            "Per plan query: rules that fell back to symbolic partial evaluation",
            buckets=_RESIDUAL_BUCKETS,
        )

    # -- lowering ----------------------------------------------------------

    def _lt(self):
        lt = self._lowered
        if lt is None:
            from ..tpu.lowering import lower_table

            lt = self._lowered = lower_table(self.rt, self._globals)
        return lt

    def _get_packer(self):
        p = self._packer
        if p is None:
            from ..tpu.packer import Packer

            p = self._packer = Packer(self._lt())
        return p

    def refresh(self, rule_table=None) -> None:
        """Drop lowered state after a policy swap; relowers lazily."""
        with self._lock:
            if rule_table is not None:
                self.rt = rule_table
            self._lowered = None
            self._packer = None
            self._need_attrs_cache.clear()

    # -- batch entry -------------------------------------------------------

    def plan_batch(
        self, inputs: list[PlanInput], params: Optional[T.EvalParams] = None
    ) -> list[PlanOutput]:
        """Evaluate a batch of plan queries; order-preserving.

        Queries that are field-identical except for ``request_id`` provably
        produce the same output (the walk never reads the id), so the batch
        is deduplicated first: only unique queries are encoded, dispatched
        and walked; duplicates clone the representative's output under their
        own request id and are booked as ``path="memo"``. Serving sweeps —
        the same (principal, action, kind) planned once per list request —
        collapse almost entirely.
        """
        from ..observability import start_span

        params = params or T.EvalParams()
        with self._lock, start_span("engine.PlanBatch", batch=len(inputs)):
            t0 = time.perf_counter()
            uniques: list[PlanInput] = []
            order: list[int] = []
            buckets: dict[tuple, list[int]] = {}
            for q in inputs:
                p = q.principal
                key = (
                    q.resource_kind,
                    tuple(q.actions),
                    p.id,
                    q.resource_policy_version,
                    q.resource_scope,
                    len(q.resource_attr),
                    len(p.attr),
                )
                cands = buckets.setdefault(key, [])
                u = -1
                # bounded scan: best-effort dedup stays O(batch) even when an
                # adversarial batch funnels distinct queries into one bucket
                for c in cands[: self.DEDUP_SCAN]:
                    if self._same_query(uniques[c], q):
                        u = c
                        break
                if u < 0:
                    u = len(uniques)
                    uniques.append(q)
                    cands.append(u)
                order.append(u)
            plans, sat = self._device_sat(uniques, params)
            uout: list[PlanOutput] = []
            st = self.stats
            st.batches += 1
            for i, q in enumerate(uniques):
                ctx = _QueryCtx(
                    sat_row=None if sat is None else sat[i],
                    known=frozenset(str(k) for k in q.resource_attr),
                    oracle=plans[i].oracle if plans is not None else True,
                )
                self._tls.ctx = ctx
                try:
                    uout.append(self._plan(q, params))
                finally:
                    self._tls.ctx = None
                st.queries += 1
                st.device_rules += ctx.device_rules
                st.symbolic_rules += ctx.symbolic_rules
                if ctx.symbolic_rules:
                    st.symbolic_queries += 1
                    self.m_queries.inc("symbolic")
                else:
                    st.device_queries += 1
                    self.m_queries.inc("device")
                self.m_residual.observe(float(ctx.symbolic_rules))
            outputs: list[PlanOutput] = []
            memo = 0
            for q, u in zip(inputs, order):
                if uniques[u] is q:
                    outputs.append(uout[u])
                else:
                    outputs.append(self._clone_output(uout[u], q))
                    memo += 1
            if memo:
                st.queries += memo
                st.memo_queries += memo
                self.m_queries.inc("memo", memo)
            self.m_batch.observe(self._mode(), time.perf_counter() - t0)
            return outputs

    @staticmethod
    def _same_query(a: PlanInput, b: PlanInput) -> bool:
        """Field-identity modulo ``request_id`` — everything ``_plan`` reads.
        Deep dict equality runs in C; the bucket key already matched kind,
        actions, principal id, version, scope and both attr-dict sizes."""
        pa, pb = a.principal, b.principal
        try:
            return (
                a.include_meta == b.include_meta
                and pa.roles == pb.roles
                and pa.scope == pb.scope
                and pa.policy_version == pb.policy_version
                and a.resource_attr == b.resource_attr
                and pa.attr == pb.attr
                and (a.aux_data.jwt if a.aux_data is not None else None)
                == (b.aux_data.jwt if b.aux_data is not None else None)
            )
        except (TypeError, ValueError):
            return False  # uncomparable values: evaluate both standalone

    def _clone_output(self, out: PlanOutput, q: PlanInput) -> PlanOutput:
        """Duplicate a representative's output under another request id.
        The condition AST is shared (treated as immutable after the walk);
        container fields are shallow-copied so callers may mutate."""
        return PlanOutput(
            request_id=q.request_id,
            actions=list(out.actions),
            kind=out.kind,
            resource_kind=out.resource_kind,
            policy_version=out.policy_version,
            scope=out.scope,
            condition=out.condition,
            matched_scopes=dict(out.matched_scopes),
            validation_errors=list(out.validation_errors),
            include_meta=out.include_meta,
            policy_match=out.policy_match,
            effective_policies=dict(out.effective_policies),
        )

    def _mode(self) -> str:
        return "jax" if self._use_jax else "numpy"

    def _device_sat(self, inputs: list[PlanInput], params: T.EvalParams):
        """Encode the batch and evaluate every kernel group once.

        Returns (plans, sat[B, C]) — or (None, None) when the device path
        can't be trusted for the whole batch (mismatched globals) and every
        query must go symbolic.
        """
        if dict(params.globals or {}) != self._globals:
            # kernels folded different global constants than this request
            # carries; the static verdict no longer applies
            return None, None
        lt = self._lt()
        packer = self._get_packer()
        from ..tpu.condcompile import Refs
        from ..tpu.evaluator import _sat_groups
        from ..tpu.packer import InputPlan

        plans = []
        for q in inputs:
            check_in = T.CheckInput(
                principal=q.principal,
                resource=T.Resource(
                    kind=q.resource_kind,
                    id="",
                    attr=dict(q.resource_attr),
                    scope=q.resource_scope,
                    policy_version=q.resource_policy_version,
                ),
                actions=list(q.actions),
                aux_data=q.aux_data,
            )
            plans.append(
                InputPlan(
                    input=check_in,
                    principal_scopes=[],
                    resource_scopes=[],
                    principal_policy_key="",
                    resource_policy_key="",
                    resource_policy_fqn="",
                    scoped_principal_exists=False,
                    scoped_resource_exists=False,
                    roles=list(q.principal.roles),
                )
            )
        compiler = lt.compiler
        if not compiler.kernels:
            return plans, None
        cb = packer._encode_columns(plans, params)
        xp: Any = np
        if self._use_jax:
            import jax.numpy as jnp

            xp = jnp
        refs = Refs(
            xp,
            cb.tags,
            cb.his,
            cb.los,
            cb.sids,
            cb.nans,
            cb.pred_vals,
            cb.pred_errs,
            list_sids=cb.list_sids,
            list_states=cb.list_states,
            ts_his=cb.ts_his,
            ts_los=cb.ts_los,
            ts_states=cb.ts_states,
            now_hi=cb.now_hi,
            now_lo=cb.now_lo,
        )
        sat = np.asarray(_sat_groups(xp, compiler, len(plans), refs))
        return plans, sat

    # -- ternary routing (the overridden Planner seams) --------------------

    def _ctx(self) -> Optional[_QueryCtx]:
        return getattr(self._tls, "ctx", None)

    def _need_attrs(self, cid: int) -> frozenset:
        """Resource attr leaves kernel ``cid``'s verdict depends on."""
        need = self._need_attrs_cache.get(cid)
        if need is None:
            k = self._lt().compiler.kernels[cid]
            need = frozenset(
                p[2]
                for p in k.resource_dep_paths()
                if len(p) == 3 and p[1] == "attr"
            )
            self._need_attrs_cache[cid] = need
        return need

    def _device_value(self, ctx: _QueryCtx, cid: int) -> tuple[bool, bool]:
        """(usable, value) of the device ternary for one kernel/query."""
        k = self._lt().compiler.kernels[cid]
        if k.emit is None or k.plan_reason is not None:
            return False, False
        if not self._need_attrs(cid) <= ctx.known:
            return False, False  # RESIDUAL: this query doesn't know enough
        return True, bool(ctx.sat_row[cid])

    def _binding_cond_ids(self, b) -> Optional[tuple[int, ...]]:
        """Kernel ids for a rule binding as returned by ``Index.query``.

        Regular indexed rows carry their own (cond, derived-role cond) pair;
        role-policy conditional allows surface as synthetic DENY bindings
        whose condition is ``none(original)`` — lowered once as
        ``negated_cond_id``. Anything unrecognized returns None and goes
        symbolic (never guess).
        """
        if b.id < 0:
            return None
        lr = self._lt().rows.get(b.id)
        if lr is None:
            return None
        if lr.row is b:
            return (lr.cond_id, lr.drcond_id)
        if (
            b.from_role_policy
            and b.effect == "EFFECT_DENY"
            and b.derived_role_condition is None
            and b.condition is not None
            and b.condition.kind == "none"
            and len(b.condition.children) == 1
            and b.condition.children[0] is lr.row.condition
            and lr.negated_cond_id >= 0
        ):
            return (lr.negated_cond_id,)
        return None

    def _binding_node(self, pe_factory, known, drl, b):
        if b.condition is None and b.derived_role_condition is None:
            return TRUE  # unconditional binding on either path
        ctx = self._ctx()
        if ctx is not None and ctx.sat_row is not None and not ctx.oracle:
            cids = self._binding_cond_ids(b)
            if cids is not None:
                val = True
                usable = True
                for cid in cids:
                    if cid < 0:
                        continue
                    ok, v = self._device_value(ctx, cid)
                    if not ok:
                        usable = False
                        break
                    val = val and v
                if usable:
                    ctx.device_rules += 1
                    return TRUE if val else FALSE
        if ctx is not None:
            ctx.symbolic_rules += 1
        return super()._binding_node(pe_factory, known, drl, b)

    def _derived_role_node(self, pe_factory, known, dr):
        if dr.condition is None:
            return TRUE
        ctx = self._ctx()
        if ctx is not None and ctx.sat_row is not None and not ctx.oracle:
            cid = self._lt().dr_cond_ids.get(id(dr), -1)
            if cid >= 0:
                ok, v = self._device_value(ctx, cid)
                if ok:
                    ctx.device_rules += 1
                    return TRUE if v else FALSE
        if ctx is not None:
            ctx.symbolic_rules += 1
        return super()._derived_role_node(pe_factory, known, dr)

    def _partial_evaluator(self, input: PlanInput, params: T.EvalParams):
        """Lazy PE factory: request messages and the activation are only
        built the first time a binding actually goes symbolic — a query
        fully resolved on the device path never constructs any of it."""
        real: list[Any] = [None]

        def make(known_attrs, var_defs, constants, drl=None):
            if real[0] is None:
                real[0] = Planner._partial_evaluator(self, input, params)
            return real[0](known_attrs, var_defs, constants, drl)

        return make
