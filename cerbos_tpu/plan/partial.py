"""Partial CEL evaluation with resource attributes as unknowns.

Behavioral reference: internal/ruletable/planner/planner.go:467-524
(partialEvaluator: CEL eval with unknowns, residual extraction). Here the
partial evaluator works directly on the AST: known subtrees (principal,
provided resource attrs, constants/variables/globals, pure functions)
collapse to literal values; unknown subtrees (absent resource attrs) stay
residual. Logic operators short-circuit on known operands.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..cel import ast as A
from ..cel.errors import CelError
from ..cel.interp import Activation, evaluate


@dataclass
class Residual:
    node: A.Node


class _Unknown(Exception):
    """Internal: subtree references an unknown."""


class PartialEvaluator:
    def __init__(self, act: Activation, known_attrs: dict[str, Any], var_defs: dict[str, A.Node]):
        self.act = act
        self.known_attrs = known_attrs
        self.var_defs = var_defs  # variable name -> definition AST (inlined on use)

    def run(self, node: A.Node):
        """→ concrete value, Residual, or raises CelError."""
        node = self._inline_vars(node, 0)
        try:
            return self._eval(node)
        except _Unknown:
            return Residual(self._residualize(node))

    # -- variable inlining (variables may reference resource attrs) --------

    def _inline_vars(self, node: A.Node, depth: int) -> A.Node:
        if depth > 32:
            raise CelError("variable inlining too deep")
        if isinstance(node, A.Select) and isinstance(node.operand, A.Ident) and node.operand.name in ("V", "variables"):
            if node.field in self.var_defs:
                return self._inline_vars(self.var_defs[node.field], depth + 1)
            raise CelError(f"undefined variable {node.field}")
        if isinstance(node, A.Select):
            return A.Select(self._inline_vars(node.operand, depth), node.field)
        if isinstance(node, A.Present):
            return A.Present(self._inline_vars(node.operand, depth), node.field)
        if isinstance(node, A.Index):
            return A.Index(self._inline_vars(node.operand, depth), self._inline_vars(node.index, depth))
        if isinstance(node, A.Call):
            return A.Call(
                node.fn,
                tuple(self._inline_vars(a, depth) for a in node.args),
                target=self._inline_vars(node.target, depth) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self._inline_vars(x, depth) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(tuple((self._inline_vars(k, depth), self._inline_vars(v, depth)) for k, v in node.entries))
        if isinstance(node, A.Bind):
            return A.Bind(node.name, self._inline_vars(node.init, depth), self._inline_vars(node.body, depth))
        if isinstance(node, A.Comprehension):
            return A.Comprehension(
                kind=node.kind,
                iter_range=self._inline_vars(node.iter_range, depth),
                iter_var=node.iter_var,
                step=self._inline_vars(node.step, depth),
                iter_var2=node.iter_var2,
                step2=self._inline_vars(node.step2, depth) if node.step2 is not None else None,
            )
        return node

    # -- unknown detection --------------------------------------------------

    def _attr_key(self, node: A.Node) -> Optional[str]:
        """R.attr.<k> / request.resource.attr.<k> (or [k]) → k."""
        field = None
        if isinstance(node, A.Select):
            field = node.field
            operand = node.operand
        elif isinstance(node, A.Index) and isinstance(node.index, A.Lit) and isinstance(node.index.value, str):
            field = node.index.value
            operand = node.operand
        else:
            return None
        if isinstance(operand, A.Select) and operand.field == "attr":
            root = operand.operand
            if isinstance(root, A.Ident) and root.name == "R":
                return field
            if (
                isinstance(root, A.Select)
                and root.field == "resource"
                and isinstance(root.operand, A.Ident)
                and root.operand.name == "request"
            ):
                return field
        return None

    def _is_unknown(self, node: A.Node) -> bool:
        k = self._attr_key(node)
        return k is not None and k not in self.known_attrs

    def _eval(self, node: A.Node) -> Any:
        """Evaluate if fully known, else raise _Unknown."""
        if self._has_unknown(node):
            # short-circuitable operators get special treatment
            if isinstance(node, A.Call) and node.target is None and node.fn in ("_&&_", "_||_"):
                short = node.fn == "_||_"
                results = []
                for arg in node.args:
                    try:
                        v = self._eval(arg)
                        if v is short:
                            return short
                        results.append(v)
                    except _Unknown:
                        results.append(None)
                if all(r is not None for r in results):
                    return not short
                raise _Unknown
            if isinstance(node, A.Call) and node.target is None and node.fn == "_?_:_":
                cond = self._eval(node.args[0])  # may raise _Unknown
                if not isinstance(cond, bool):
                    raise CelError("ternary condition is not a bool")
                return self._eval(node.args[1] if cond else node.args[2])
            raise _Unknown
        return evaluate(node, self.act)

    _unknown_cache: dict

    def _has_unknown(self, node: A.Node) -> bool:
        if self._is_unknown(node):
            return True
        if isinstance(node, (A.Select, A.Present)):
            return self._has_unknown(node.operand)
        if isinstance(node, A.Index):
            return self._has_unknown(node.operand) or self._has_unknown(node.index)
        if isinstance(node, A.Call):
            if node.target is not None and self._has_unknown(node.target):
                return True
            return any(self._has_unknown(a) for a in node.args)
        if isinstance(node, A.ListLit):
            return any(self._has_unknown(a) for a in node.items)
        if isinstance(node, A.MapLit):
            return any(self._has_unknown(k) or self._has_unknown(v) for k, v in node.entries)
        if isinstance(node, A.Bind):
            return self._has_unknown(node.init) or self._has_unknown(node.body)
        if isinstance(node, A.Comprehension):
            return (
                self._has_unknown(node.iter_range)
                or self._has_unknown(node.step)
                or (node.step2 is not None and self._has_unknown(node.step2))
            )
        return False

    # -- residualization ----------------------------------------------------

    def _residualize(self, node: A.Node) -> A.Node:
        """Replace fully-known subtrees with literals; keep unknowns."""
        if not self._has_unknown(node):
            try:
                return A.Lit(self._eval(node))
            except (_Unknown, CelError):
                return node
        if isinstance(node, A.Call):
            if node.fn in ("_&&_", "_||_") and node.target is None:
                short = node.fn == "_||_"
                parts: list[A.Node] = []
                for arg in node.args:
                    r = self._residualize(arg)
                    if isinstance(r, A.Lit) and isinstance(r.value, bool):
                        if r.value is short:
                            return A.Lit(short)
                        continue  # neutral element drops out
                    parts.append(r)
                if not parts:
                    return A.Lit(not short)
                if len(parts) == 1:
                    return parts[0]
                out = parts[0]
                for p in parts[1:]:
                    out = A.Call(node.fn, (out, p))
                return out
            if node.fn == "_?_:_" and node.target is None:
                cond = self._residualize(node.args[0])
                if isinstance(cond, A.Lit) and isinstance(cond.value, bool):
                    return self._residualize(node.args[1] if cond.value else node.args[2])
                return A.Call(node.fn, (cond, self._residualize(node.args[1]), self._residualize(node.args[2])))
            if node.fn == "!_" and node.target is None:
                inner = self._residualize(node.args[0])
                if isinstance(inner, A.Lit) and isinstance(inner.value, bool):
                    return A.Lit(not inner.value)
                return A.Call("!_", (inner,))
            return A.Call(
                node.fn,
                tuple(self._residualize(a) for a in node.args),
                target=self._residualize(node.target) if node.target is not None else None,
            )
        if isinstance(node, (A.Select, A.Present, A.Index, A.ListLit, A.MapLit)):
            return node  # unknown leaf chains stay as-is
        return node
