"""Partial CEL evaluation with resource attributes as unknowns.

Behavioral reference: internal/ruletable/planner/planner.go:467-524
(partialEvaluator: CEL eval with unknowns, residual extraction). Here the
partial evaluator works directly on the AST: known subtrees (principal,
provided resource attrs, constants/variables/globals, pure functions)
collapse to literal values; unknown subtrees (absent resource attrs) stay
residual. Logic operators short-circuit on known operands.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..cel import ast as A
from ..cel.errors import CelError
from ..cel.interp import Activation, evaluate


@dataclass
class Residual:
    node: A.Node


def _has_non_literal_value(v: Any) -> bool:
    """True if v contains a CEL value with no constant form in the filter
    AST (duration, timestamp, hierarchy, SPIFFE ids, ...) — cel prune keeps
    the originating call for these instead of a value."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return False
    if isinstance(v, (list, tuple)):
        return any(_has_non_literal_value(x) for x in v)
    if isinstance(v, dict):
        return any(_has_non_literal_value(x) for x in v.values())
    return True


def _substitute_many(node: A.Node, mapping: dict[str, A.Node]) -> A.Node:
    """Replace free identifiers per mapping (comprehension unrolling)."""
    if isinstance(node, A.Ident):
        return mapping.get(node.name, node)
    if isinstance(node, A.Select):
        return A.Select(_substitute_many(node.operand, mapping), node.field)
    if isinstance(node, A.Present):
        return A.Present(_substitute_many(node.operand, mapping), node.field)
    if isinstance(node, A.Index):
        return A.Index(_substitute_many(node.operand, mapping), _substitute_many(node.index, mapping))
    if isinstance(node, A.Call):
        return A.Call(
            node.fn,
            tuple(_substitute_many(a, mapping) for a in node.args),
            target=_substitute_many(node.target, mapping) if node.target is not None else None,
        )
    if isinstance(node, A.ListLit):
        return A.ListLit(tuple(_substitute_many(x, mapping) for x in node.items))
    if isinstance(node, A.MapLit):
        return A.MapLit(
            tuple((_substitute_many(k, mapping), _substitute_many(v, mapping)) for k, v in node.entries)
        )
    if isinstance(node, A.Bind):
        inner = {k: v for k, v in mapping.items() if k != node.name}
        return A.Bind(node.name, _substitute_many(node.init, mapping), _substitute_many(node.body, inner))
    if isinstance(node, A.Comprehension):
        inner = {k: v for k, v in mapping.items() if k not in (node.iter_var, node.iter_var2)}
        return A.Comprehension(
            kind=node.kind,
            iter_range=_substitute_many(node.iter_range, mapping),
            iter_var=node.iter_var,
            step=_substitute_many(node.step, inner),
            iter_var2=node.iter_var2,
            step2=_substitute_many(node.step2, inner) if node.step2 is not None else None,
        )
    return node


class _Unknown(Exception):
    """Internal: subtree references an unknown."""


class PartialEvaluator:
    def __init__(
        self,
        act: Activation,
        known_attrs: dict[str, Any],
        var_defs: dict[str, A.Node],
        derived_roles_list=None,
        known_fields: frozenset = frozenset({"kind", "scope"}),
    ):
        self.act = act
        self.known_attrs = known_attrs
        # resource head fields resolvable from the activation; the planner
        # keeps id/policyVersion symbolic (planner.go), the REPL's :exec
        # evaluates them concretely against the loaded fixtures
        self.known_fields = known_fields
        self.var_defs = var_defs  # variable name -> definition AST (inlined on use)
        # (name, condition-node) pairs for runtime.effectiveDerivedRoles
        # substitution (planner.go:795-851): the select is replaced by
        # (cond1 ? [name1] : []) + (cond2 ? [name2] : []) + ...
        self.derived_roles_list = derived_roles_list
        self._opaque_idents: set[str] = set()

    def run(self, node: A.Node):
        """→ concrete value, Residual, or raises CelError."""
        node = self._inline_vars(node, 0)
        try:
            return self._eval(node)
        except _Unknown:
            residual = self._residualize(node)
            rewritten = self._struct_match(residual)
            if rewritten is not None:
                residual = self._residualize(rewritten)
            if isinstance(residual, A.Lit) and isinstance(residual.value, bool):
                return residual.value
            return Residual(residual)

    # -- struct matcher ------------------------------------------------------
    #
    # Behavioral reference: internal/ruletable/planner/struct_matcher.go.
    # A root-level residual of the form `<known-map>[<unknown-select>](.f)?
    # <op> <const>` (s1) or `<const> in <known-map>[<unknown-select>](.f)?`
    # (s2) expands to an OR over the map's entries:
    # `(indexer == key) && (const <op> value(.f))` — constant arms then fold
    # away in the follow-up partial evaluation.

    _STRUCT_OPS = ("_==_", "_!=_", "_<_", "_<=_", "_>_", "_>=_")

    def _struct_match(self, node: A.Node) -> Optional[A.Node]:
        if isinstance(node, A.Comprehension):
            return self._lambda_match(node)
        if not isinstance(node, A.Call) or node.target is not None or len(node.args) != 2:
            return None
        if node.fn in self._STRUCT_OPS:
            indexed = self._match_struct_indexer(node.args[0])
            if indexed is None or not isinstance(node.args[1], A.Lit):
                return None
            entries, indexer, field = indexed
            const = node.args[1]
        elif node.fn == "_in_":
            indexed = self._match_struct_indexer(node.args[1])
            if indexed is None or not isinstance(node.args[0], A.Lit):
                return None
            entries, indexer, field = indexed
            const = node.args[0]
        else:
            return None
        if not entries:
            return None
        opts: list[A.Node] = []
        for key, value in entries:
            val_node: A.Node = A.Lit(value)
            if field is not None:
                val_node = A.Select(val_node, field)
            # mkOption emits op(const, value); that order is correct for the
            # symmetric ==/!=/in cases the reference tests, but inverts the
            # ordered comparisons (m[x] < c must become value < c, not
            # c < value) — deliberate fix over struct_matcher.go:258-264
            if node.fn in ("_<_", "_<=_", "_>_", "_>=_"):
                cmp_node = A.Call(node.fn, (val_node, const))
            else:
                cmp_node = A.Call(node.fn, (const, val_node))
            opts.append(
                A.Call(
                    "_&&_",
                    (A.Call("_==_", (indexer, A.Lit(key))), cmp_node),
                )
            )
        # right-nested OR chain (struct_matcher.go mkLogicalOr)
        out = opts[-1]
        for o in reversed(opts[:-1]):
            out = A.Call("_||_", (o, out))
        return out

    _LAMBDA_MAX_ITEMS = 10  # struct_matcher.go:352 maxItems

    def _lambda_match(self, node: A.Comprehension) -> Optional[A.Node]:
        """Root-level exists/all over a known list/map of ≤10 items unrolls
        to an or/and chain (struct_matcher.go lambdaMatcher.Process)."""
        if node.kind not in ("all", "exists"):
            return None
        rng = node.iter_range
        if not (isinstance(rng, A.Lit) and isinstance(rng.value, (list, dict))):
            return None
        if len(rng.value) > self._LAMBDA_MAX_ITEMS or len(rng.value) == 0:
            return None
        if isinstance(rng.value, list):
            items = list(enumerate(rng.value)) if node.iter_var2 else [(None, v) for v in rng.value]
        else:
            if not node.iter_var2:
                items = [(None, k) for k in rng.value.keys()]
            else:
                items = list(rng.value.items())
        opts: list[A.Node] = []
        for k, v in items:
            mapping = {node.iter_var: A.Lit(v)} if k is None else {
                node.iter_var: A.Lit(k), node.iter_var2: A.Lit(v)
            }
            opts.append(self._residualize(_substitute_many(node.step, mapping)))
        fn = "_&&_" if node.kind == "all" else "_||_"
        out = opts[-1]
        for o in reversed(opts[:-1]):
            out = A.Call(fn, (o, out))
        return out

    def _match_struct_indexer(self, node: A.Node):
        """→ (sorted entries, indexer expr, optional field) or None."""
        field = None
        if isinstance(node, A.Select):
            field = node.field
            node = node.operand
        if not isinstance(node, A.Index):
            return None
        if not (isinstance(node.operand, A.Lit) and isinstance(node.operand.value, dict)):
            return None
        if not isinstance(node.index, (A.Select, A.Index)):
            return None
        entries = sorted(node.operand.value.items(), key=lambda kv: str(kv[0]))
        return entries, node.index, field

    def _edr_list_expr(self) -> A.Node:
        parts: list[A.Node] = []
        for name, cond in self.derived_roles_list or []:
            if isinstance(cond, A.Lit) and cond.value is False:
                continue
            if isinstance(cond, A.Lit) and cond.value is True:
                parts.append(A.ListLit((A.Lit(name),)))
            else:
                parts.append(A.Call("_?_:_", (cond, A.ListLit((A.Lit(name),)), A.ListLit(()))))
        if not parts:
            return A.ListLit(())
        # mkBinaryOperatorExpr: right-nested adds (planner.go:853-860)
        out = parts[-1]
        for p in reversed(parts[:-1]):
            out = A.Call("_+_", (p, out))
        return out

    # -- variable inlining (variables may reference resource attrs) --------

    def _inline_vars(self, node: A.Node, depth: int) -> A.Node:
        if depth > 32:
            raise CelError("variable inlining too deep")
        if isinstance(node, A.Select) and isinstance(node.operand, A.Ident) and node.operand.name in ("V", "variables"):
            if node.field in self.var_defs:
                return self._inline_vars(self.var_defs[node.field], depth + 1)
            raise CelError(f"undefined variable {node.field}")
        if (
            isinstance(node, A.Select)
            and isinstance(node.operand, A.Ident)
            and node.operand.name == "runtime"
            and node.field in ("effectiveDerivedRoles", "effective_derived_roles")
            and self.derived_roles_list is not None
        ):
            return self._edr_list_expr()
        if isinstance(node, A.Select):
            return A.Select(self._inline_vars(node.operand, depth), node.field)
        if isinstance(node, A.Present):
            return A.Present(self._inline_vars(node.operand, depth), node.field)
        if isinstance(node, A.Index):
            return A.Index(self._inline_vars(node.operand, depth), self._inline_vars(node.index, depth))
        if isinstance(node, A.Call):
            return A.Call(
                node.fn,
                tuple(self._inline_vars(a, depth) for a in node.args),
                target=self._inline_vars(node.target, depth) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self._inline_vars(x, depth) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(tuple((self._inline_vars(k, depth), self._inline_vars(v, depth)) for k, v in node.entries))
        if isinstance(node, A.Bind):
            return A.Bind(node.name, self._inline_vars(node.init, depth), self._inline_vars(node.body, depth))
        if isinstance(node, A.Comprehension):
            return A.Comprehension(
                kind=node.kind,
                iter_range=self._inline_vars(node.iter_range, depth),
                iter_var=node.iter_var,
                step=self._inline_vars(node.step, depth),
                iter_var2=node.iter_var2,
                step2=self._inline_vars(node.step2, depth) if node.step2 is not None else None,
            )
        return node

    # -- unknown detection --------------------------------------------------
    #
    # The reference declares the ENTIRE resource as unknown
    # (cel.AttributePattern("R") / request.resource, planner.go:510-516) and
    # then re-declares specific qualified names as known variables:
    # R.attr.<name> for every provided attribute, R.kind, R.scope and
    # P.scope (planner.go:525-570). So R.id and absent attrs are unknown;
    # provided attrs, kind and scope are concrete.

    _DYNAMIC = object()

    def _resource_chain(self, node: A.Node) -> Optional[list]:
        """Accessor steps (outermost-last) for a chain rooted at R or
        request.resource; None if not resource-rooted. A step is a field /
        literal string index, or _DYNAMIC for a computed index."""
        steps: list = []
        cur = node
        while True:
            if isinstance(cur, (A.Select, A.Present)):
                steps.append(cur.field)
                cur = cur.operand
            elif isinstance(cur, A.Index):
                if isinstance(cur.index, A.Lit) and isinstance(cur.index.value, str):
                    steps.append(cur.index.value)
                else:
                    steps.append(self._DYNAMIC)
                cur = cur.operand
            elif isinstance(cur, A.Ident):
                steps.reverse()
                if cur.name == "R":
                    return steps
                if cur.name == "request" and steps[:1] == ["resource"]:
                    return steps[1:]
                return None
            else:
                return None

    def _classify_resource(self, node: A.Node) -> Optional[bool]:
        """True = unknown, False = known concrete, None = not resource-rooted."""
        steps = self._resource_chain(node)
        if steps is None:
            return None
        if not steps:
            return True  # bare R / request.resource
        head = steps[0]
        if head in self.known_fields:
            return False
        if head == "attr" and len(steps) >= 2 and isinstance(steps[1], str) and steps[1] in self.known_attrs:
            return False
        return True  # id, policyVersion, absent attrs, dynamic indexes: unknown

    def _eval(self, node: A.Node) -> Any:
        """Evaluate if fully known, else raise _Unknown."""
        if self._has_unknown(node):
            # short-circuitable operators get special treatment
            if isinstance(node, A.Call) and node.target is None and node.fn in ("_&&_", "_||_"):
                short = node.fn == "_||_"
                results = []
                for arg in node.args:
                    try:
                        v = self._eval(arg)
                        if v is short:
                            return short
                        results.append(v)
                    except _Unknown:
                        results.append(None)
                if all(r is not None for r in results):
                    return not short
                raise _Unknown
            if isinstance(node, A.Call) and node.target is None and node.fn == "_?_:_":
                cond = self._eval(node.args[0])  # may raise _Unknown
                if not isinstance(cond, bool):
                    raise CelError("ternary condition is not a bool")
                return self._eval(node.args[1] if cond else node.args[2])
            raise _Unknown
        return evaluate(node, self.act)

    def _has_unknown(self, node: A.Node) -> bool:
        if isinstance(node, A.Ident) and node.name in self._opaque_idents:
            return True
        cls = self._classify_resource(node)
        if cls is not None:
            # a resource-rooted chain is classified atomically: a KNOWN chain
            # (provided attr / kind / scope) must not be re-examined through
            # its R-rooted operand, and dynamic index exprs inside an unknown
            # chain don't change the verdict
            return cls
        if isinstance(node, (A.Select, A.Present)):
            return self._has_unknown(node.operand)
        if isinstance(node, A.Index):
            return self._has_unknown(node.operand) or self._has_unknown(node.index)
        if isinstance(node, A.Call):
            if node.target is not None and self._has_unknown(node.target):
                return True
            return any(self._has_unknown(a) for a in node.args)
        if isinstance(node, A.ListLit):
            return any(self._has_unknown(a) for a in node.items)
        if isinstance(node, A.MapLit):
            return any(self._has_unknown(k) or self._has_unknown(v) for k, v in node.entries)
        if isinstance(node, A.Bind):
            return self._has_unknown(node.init) or self._has_unknown(node.body)
        if isinstance(node, A.Comprehension):
            return (
                self._has_unknown(node.iter_range)
                or self._has_unknown(node.step)
                or (node.step2 is not None and self._has_unknown(node.step2))
            )
        return False

    # -- residualization ----------------------------------------------------

    def _residualize(self, node: A.Node) -> A.Node:
        """Replace fully-known subtrees with literals; keep unknowns."""
        if not self._has_unknown(node):
            try:
                v = self._eval(node)
            except _Unknown:
                pass
            except CelError:
                # evaluation failed (e.g. select of a missing key on a known
                # map): keep the node's structure but materialize its known
                # children, the way cel prune does — P.attr.missing becomes
                # get-field(<attr literal>, missing), not a bare chain
                return self._residualize_children(node)
            else:
                if _has_non_literal_value(v):
                    # durations/timestamps re-materialize in canonical call
                    # form (duration("1h") → duration("3600s")); other
                    # non-constant values (hierarchy, ...) keep their call
                    # with known args pruned to constants
                    from ..cel.values import Duration, Timestamp

                    if isinstance(v, Duration):
                        from ..cel.stdlib import _to_string

                        return A.Call("duration", (A.Lit(_to_string(v)),))
                    if isinstance(v, Timestamp):
                        from ..cel.stdlib import _to_string

                        return A.Call("timestamp", (A.Lit(_to_string(v)),))
                    return self._residualize_children(node)
                return A.Lit(v)
        if isinstance(node, A.Call):
            if node.fn in ("_&&_", "_||_") and node.target is None:
                short = node.fn == "_||_"
                parts: list[A.Node] = []
                for arg in node.args:
                    r = self._residualize(arg)
                    if isinstance(r, A.Lit) and isinstance(r.value, bool):
                        if r.value is short:
                            return A.Lit(short)
                        continue  # neutral element drops out
                    parts.append(r)
                if not parts:
                    return A.Lit(not short)
                if len(parts) == 1:
                    return parts[0]
                out = parts[0]
                for p in parts[1:]:
                    out = A.Call(node.fn, (out, p))
                return out
            if node.fn == "_?_:_" and node.target is None:
                cond = self._residualize(node.args[0])
                if isinstance(cond, A.Lit) and isinstance(cond.value, bool):
                    return self._residualize(node.args[1] if cond.value else node.args[2])
                return A.Call(node.fn, (cond, self._residualize(node.args[1]), self._residualize(node.args[2])))
            if node.fn == "!_" and node.target is None:
                inner = self._residualize(node.args[0])
                if isinstance(inner, A.Lit) and isinstance(inner.value, bool):
                    return A.Lit(not inner.value)
                return A.Call("!_", (inner,))
            return A.Call(
                node.fn,
                tuple(self._residualize(a) for a in node.args),
                target=self._residualize(node.target) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self._residualize(x) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(
                tuple((self._residualize(k), self._residualize(v)) for k, v in node.entries)
            )
        if isinstance(node, A.Comprehension):
            return self._residualize_comprehension(node)
        if isinstance(node, A.Index):
            if self._classify_resource(node) is True:
                return node  # unknown resource chains stay as-is
            return A.Index(self._residualize(node.operand), self._residualize(node.index))
        if isinstance(node, A.Select):
            if self._classify_resource(node) is True:
                return node
            return A.Select(self._residualize(node.operand), node.field)
        if isinstance(node, A.Present):
            if self._classify_resource(node) is True:
                return node
            return A.Present(self._residualize(node.operand), node.field)
        return node

    def _residualize_children(self, node: A.Node) -> A.Node:
        if isinstance(node, A.Select):
            return A.Select(self._residualize(node.operand), node.field)
        if isinstance(node, A.Present):
            return A.Present(self._residualize(node.operand), node.field)
        if isinstance(node, A.Index):
            return A.Index(self._residualize(node.operand), self._residualize(node.index))
        if isinstance(node, A.Call):
            return A.Call(
                node.fn,
                tuple(self._residualize(a) for a in node.args),
                target=self._residualize(node.target) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self._residualize(x) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(tuple((self._residualize(k), self._residualize(v)) for k, v in node.entries))
        return node

    def _residualize_comprehension(self, node: A.Comprehension) -> A.Node:
        """The iter range residualizes; the body is partially evaluated with
        the iteration vars left opaque (planner.go evalComprehensionBody).
        Unrolling over known ranges happens only at the residual root, via
        the lambda matcher (struct_matcher.go:316-411) in run()."""
        range_r = self._residualize(node.iter_range)
        added = {node.iter_var} | ({node.iter_var2} if node.iter_var2 else set())
        added -= self._opaque_idents
        self._opaque_idents |= added
        try:
            step_r = self._residualize(node.step)
            step2_r = self._residualize(node.step2) if node.step2 is not None else None
        finally:
            self._opaque_idents -= added
        return A.Comprehension(
            kind=node.kind,
            iter_range=range_r,
            iter_var=node.iter_var,
            step=step_r,
            iter_var2=node.iter_var2,
            step2=step2_r,
        )
