from .types import PlanInput, PlanOutput  # noqa: F401
from .planner import Planner  # noqa: F401
from .batch import BatchPlanner  # noqa: F401
