"""Bootstrap warmup: pre-compile the dominant device layouts.

A fresh replica's first device batch in each shape bucket pays the full XLA
compile (seconds to tens of seconds when the persistent cache is cold) — a
latency cliff the reference's ~1 s cold start never shows. The warmup
driver runs synthetic batches through the evaluator's normal ``check()``
path before readiness opens the gates, so the compile happens on nobody's
request. Configured under ``engine.tpu.warmup``:

- ``batchSizes``: batch sizes to pre-compile, one per pow2 shape bucket the
  traffic mix is expected to hit (sizes below ``minDeviceBatch`` are
  clamped up — the oracle path compiles nothing);
- ``synthetic``: optional explicit corpus, a list of
  ``{kind, actions, roles}`` entries. When empty, the corpus is DERIVED
  from the loaded rule table (its resource kinds, actions, and roles) so
  the warmed layouts match the policies actually being served;
- ``maxKinds``, ``timeoutSeconds``, ``background``.

The driver talks to :mod:`..engine.readiness`: one ``layout_compiled()``
per finished batch size, ``mark_ready()`` at the end — also on failure or
timeout, because a replica that never becomes ready is a worse outcome
than one that cold-compiles a straggler layout under traffic.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

from ..engine import types as T

_log = logging.getLogger("cerbos_tpu.warmup")

_FALLBACK_SPEC = {"kind": "warmup", "actions": ["view"], "roles": ["user"]}


def derive_corpus(rule_table: Any, max_kinds: int = 8) -> list[dict]:
    """Synthesize ``{kind, actions, roles}`` specs from the rule table so
    warmup batches exercise real candidate rows (unknown kinds pack to
    empty candidate sets and compile nothing useful)."""
    by_kind: dict[str, dict[str, set]] = {}
    try:
        rows = rule_table.idx.get_all_rows()
    except Exception:
        rows = []
    for row in rows:
        kind = getattr(row, "resource", "") or ""
        if not kind or "*" in kind:
            continue
        spec = by_kind.setdefault(kind, {"actions": set(), "roles": set()})
        if row.action and "*" not in row.action:
            spec["actions"].add(row.action)
        if row.allow_actions:
            spec["actions"].update(a for a in row.allow_actions if "*" not in a)
        role = getattr(row, "role", "") or ""
        if role and role != "*":
            spec["roles"].add(role)
    out = []
    for kind in sorted(by_kind)[: max(1, int(max_kinds))]:
        spec = by_kind[kind]
        out.append(
            {
                "kind": kind,
                "actions": sorted(spec["actions"])[:4] or ["view"],
                "roles": sorted(spec["roles"])[:4] or ["user"],
            }
        )
    return out or [dict(_FALLBACK_SPEC)]


def synthetic_inputs(specs: list[dict], n: int) -> list[T.CheckInput]:
    """``n`` CheckInputs cycling over the corpus specs. Attribute payloads
    stay empty: layout keys depend on shapes and referenced columns, not on
    attribute values, and empty attrs keep packing cheap."""
    inputs = []
    for i in range(n):
        spec = specs[i % len(specs)]
        actions = list(spec.get("actions") or ["view"])[:4]
        roles = list(spec.get("roles") or ["user"])[:4]
        inputs.append(
            T.CheckInput(
                request_id=f"warmup-{i}",
                principal=T.Principal(id=f"warmup-principal-{i % 7}", roles=roles),
                resource=T.Resource(kind=str(spec.get("kind", "warmup")), id=f"warmup-res-{i}"),
                actions=actions,
            )
        )
    return inputs


class WarmupDriver:
    """Pre-compiles one device layout per configured batch size."""

    def __init__(
        self,
        evaluator: Any,
        batch_sizes: Optional[list[int]] = None,
        corpus: Optional[list[dict]] = None,
        max_kinds: int = 8,
        timeout_s: float = 120.0,
        readiness: Any = None,
        evaluators: Optional[list[Any]] = None,
    ):
        # ``evaluators`` warms a sharded pool: every lane's clone owns its
        # own jit cache, so readiness must wait for sizes × shards compiles
        # (the persistent XLA cache makes shards 2..N cheap on real metal)
        self.evaluators = list(evaluators) if evaluators else [evaluator]
        self.evaluator = evaluator if evaluator is not None else self.evaluators[0]
        min_batch = max(1, int(getattr(self.evaluator, "min_device_batch", 16)))
        sizes = sorted({max(int(s), min_batch) for s in (batch_sizes or [16, 64]) if int(s) > 0})
        self.batch_sizes = sizes or [min_batch]
        self.corpus = [dict(s) for s in corpus] if corpus else None
        self.max_kinds = int(max_kinds)
        self.timeout_s = float(timeout_s)
        self.readiness = readiness
        self.expected = len(self.batch_sizes) * len(self.evaluators)
        self._thread: Optional[threading.Thread] = None

    def run(self) -> dict:
        """Synchronously warm every batch size, then mark ready."""
        specs = self.corpus or derive_corpus(self.evaluator.rule_table, self.max_kinds)
        deadline = time.monotonic() + self.timeout_s
        summary: dict = {"layouts": 0, "inputs": 0, "errors": []}
        t_start = time.monotonic()
        error: Optional[str] = None
        timed_out = False
        for ei, ev in enumerate(self.evaluators):
            if timed_out:
                break
            shard = getattr(ev, "shard_id", None)
            tag = f" shard {shard}" if shard is not None else ""
            for size in self.batch_sizes:
                if time.monotonic() > deadline:
                    error = f"warmup timeout after {self.timeout_s:.0f}s ({summary['layouts']}/{self.expected} layouts)"
                    _log.warning("%s — opening readiness anyway", error)
                    timed_out = True
                    break
                try:
                    t0 = time.monotonic()
                    ev.check(synthetic_inputs(specs, size))
                    _log.info(
                        "warmup: batch size %d%s compiled in %.2fs (%d/%d layouts)",
                        size, tag, time.monotonic() - t0, summary["layouts"] + 1, self.expected,
                    )
                except Exception as e:  # noqa: BLE001 - warmup must not kill boot
                    summary["errors"].append(f"size {size}{tag}: {e}")
                    _log.warning("warmup batch size %d%s failed: %s", size, tag, e)
                    continue
                summary["layouts"] += 1
                summary["inputs"] += size
                if self.readiness is not None:
                    self.readiness.layout_compiled()
        summary["seconds"] = round(time.monotonic() - t_start, 3)
        if error is None and summary["errors"]:
            error = "; ".join(summary["errors"])
        if self.readiness is not None:
            self.readiness.mark_ready(error=error)
        return summary

    def start(self) -> threading.Thread:
        """Run warmup on a daemon thread so the listeners bind immediately;
        readiness keeps traffic out until the thread reports in."""

        def _bg():
            try:
                self.run()
            except Exception as e:  # noqa: BLE001 - never wedge readiness shut
                _log.warning("warmup driver crashed: %s — opening readiness anyway", e)
                if self.readiness is not None:
                    self.readiness.mark_ready(error=str(e))

        t = threading.Thread(target=_bg, name="cerbos-tpu-warmup", daemon=True)
        self._thread = t
        t.start()
        return t
