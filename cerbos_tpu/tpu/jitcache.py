"""Persistent XLA compilation cache (VERDICT r4 item 2).

The lowered table's sat/lattice graph takes ~35 s of XLA compilation on a
TPU, which the reference's stateless-replica restart model cannot absorb
(its cold start is ~1 s: load = deserialize, `index/marshal.go:20,240`).
JAX ships a persistent compilation cache keyed by (HLO, compile options,
jaxlib version, device topology); enabling it makes every process after
the first load the compiled binary from disk instead of re-running XLA.

Cache location, first writable wins:
  1. ``$CERBOS_TPU_XLA_CACHE_DIR``
  2. ``<repo root>/.xla_cache`` (so a checked-out tree warms itself)
  3. ``~/.cache/cerbos_tpu/xla``
"""

from __future__ import annotations

import os
import pathlib

_enabled = False


def _candidate_dirs():
    env = os.environ.get("CERBOS_TPU_XLA_CACHE_DIR")
    if env:
        yield pathlib.Path(env)
    # cerbos_tpu/tpu/jitcache.py -> repo root two levels up, but only when
    # running from a checkout — an installed package must not write into
    # site-packages' parent
    root = pathlib.Path(__file__).resolve().parents[2]
    if (root / "pyproject.toml").exists() or (root / ".git").exists():
        yield root / ".xla_cache"
    yield pathlib.Path.home() / ".cache" / "cerbos_tpu" / "xla"


def enable() -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.

    Returns the directory used, or None if configuration failed (old jax,
    read-only filesystem everywhere). Safe to call before or after jax
    backends initialize — the cache config is read at compile time.
    """
    global _enabled
    if _enabled:
        return _enabled if isinstance(_enabled, str) else None
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return None
    # respect an embedding application's own cache configuration: only
    # install ours when nothing is configured yet
    try:
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            _enabled = True
            return None
    except Exception:
        pass
    for cand in _candidate_dirs():
        try:
            cand.mkdir(parents=True, exist_ok=True)
            probe = cand / ".probe"
            probe.write_bytes(b"")
            probe.unlink()
        except OSError:
            continue
        try:
            jax.config.update("jax_compilation_cache_dir", str(cand))
        except Exception:
            return None
        _enabled = str(cand)
        # cache every entry: the default thresholds skip "fast" compiles,
        # but on this serving path even a 2 s compile is worth persisting.
        # These knobs don't exist on older jax — the cache dir alone must
        # survive, so they get their own guard instead of unwinding it.
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass
        return _enabled
    return None
