"""Persistent XLA compilation cache (VERDICT r4 item 2).

The lowered table's sat/lattice graph takes ~35 s of XLA compilation on a
TPU, which the reference's stateless-replica restart model cannot absorb
(its cold start is ~1 s: load = deserialize, `index/marshal.go:20,240`).
JAX ships a persistent compilation cache keyed by (HLO, compile options,
jaxlib version, device topology); enabling it makes every process after
the first load the compiled binary from disk instead of re-running XLA.

Cache location, first writable wins:
  1. ``$CERBOS_TPU_XLA_CACHE_DIR``
  2. ``<repo root>/.xla_cache`` (so a checked-out tree warms itself)
  3. ``~/.cache/cerbos_tpu/xla``
"""

from __future__ import annotations

import os
import pathlib

# False until enable() runs; afterwards the cache directory string (ours or
# an embedding application's own) — enable()/status() report it either way
_enabled: "str | bool" = False
_external = False  # directory was configured by the embedding app, not us
_entries_at_enable: "int | None" = None


def _candidate_dirs():
    env = os.environ.get("CERBOS_TPU_XLA_CACHE_DIR")
    if env:
        yield pathlib.Path(env)
    # cerbos_tpu/tpu/jitcache.py -> repo root two levels up, but only when
    # running from a checkout — an installed package must not write into
    # site-packages' parent
    root = pathlib.Path(__file__).resolve().parents[2]
    if (root / "pyproject.toml").exists() or (root / ".git").exists():
        yield root / ".xla_cache"
    yield pathlib.Path.home() / ".cache" / "cerbos_tpu" / "xla"


def enable() -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.

    Returns the directory in use — ours, or an embedding application's own
    preconfigured one — or None if configuration failed (old jax, read-only
    filesystem everywhere). Repeat calls return the same directory. Safe to
    call before or after jax backends initialize — the cache config is read
    at compile time.
    """
    global _enabled, _external, _entries_at_enable
    if _enabled:
        return _enabled if isinstance(_enabled, str) else None
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return None
    # respect an embedding application's own cache configuration: only
    # install ours when nothing is configured yet (but still report theirs,
    # so repeat calls and status() see the directory actually in use)
    try:
        existing = getattr(jax.config, "jax_compilation_cache_dir", None)
        if existing:
            _enabled = str(existing)
            _external = True
            _entries_at_enable = entry_count()
            return _enabled
    except Exception:
        pass
    for cand in _candidate_dirs():
        try:
            cand.mkdir(parents=True, exist_ok=True)
            probe = cand / ".probe"
            probe.write_bytes(b"")
            probe.unlink()
        except OSError:
            continue
        try:
            jax.config.update("jax_compilation_cache_dir", str(cand))
        except Exception:
            return None
        _enabled = str(cand)
        _external = False
        _entries_at_enable = entry_count()
        # cache every entry: the default thresholds skip "fast" compiles,
        # but on this serving path even a 2 s compile is worth persisting.
        # These knobs don't exist on older jax — the cache dir alone must
        # survive, so they get their own guard instead of unwinding it.
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass
        return _enabled
    return None


def directory() -> str | None:
    """The persistent cache directory in use, or None when not enabled."""
    return _enabled if isinstance(_enabled, str) else None


def entry_count() -> int | None:
    """Files currently in the cache directory (None when disabled or
    unreadable). Cheap relative to any compile, and the before/after delta
    is what classifies a compile as fresh vs persistent-loaded."""
    d = directory()
    if not d:
        return None
    try:
        return sum(1 for p in pathlib.Path(d).iterdir() if p.is_file())
    except OSError:
        return None


def status() -> dict:
    """Cache evidence for the bootstrap log line, ``/_cerbos/debug/flight``,
    and operators asking "did the restart actually skip the compile?":
    the directory, whether it held entries when we enabled it (a warm
    restart), and how many compiles this process loaded from it."""
    entries = entry_count()
    persistent_loads = 0
    try:
        from .compilestats import stats as _compile_stats

        persistent_loads = _compile_stats().snapshot()["persistent_loads"]
    except Exception:  # pragma: no cover - circular-import belt and braces
        pass
    return {
        "enabled": bool(_enabled),
        "dir": directory(),
        "external": _external,
        "entries": entries,
        "entries_at_enable": _entries_at_enable,
        # hit evidence: pre-existing entries mean this process can load
        # instead of compile; persistent_loads counts the times it did
        "warm_at_enable": bool(_entries_at_enable),
        "persistent_loads": persistent_loads,
    }
