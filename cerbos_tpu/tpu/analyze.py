"""Static policy analyzer: compile-time device-eligibility and divergence audit.

Walks every rule in a built :class:`RuleTable` and the lowered CEL kernels
(reusing the condition compiler in audit mode — nothing here traces or
executes device code) and produces a structured report answering, before any
request arrives, the questions the runtime otherwise answers the hard way:

* **Device eligibility** per rule: ``device`` (fully batchable), ``tagged-
  fallback`` (batchable, but specific attribute paths carry runtime type
  tags that divert matching requests to the CPU oracle), or ``oracle-only``
  (the condition references runtime values the device cannot see and every
  evaluation goes to the oracle). Reasons are the stable codes from
  :data:`condcompile.REASONS` / :data:`condcompile.FALLBACK_REASONS`, not
  free-text strings.
* **Divergence-risk lints**: construct classes the parity sentinel (PR 8)
  catches only after a batch has diverged — float equality, NaN constants,
  mixed timestamp comparisons, string-ordering constants, deep variable
  inlining chains.
* **Policy-graph findings**: dead rules shadowed by unconditional DENYs in
  the same match cell, derived roles imported but never referenced, and
  undefined variable/constant/global references.

The report is surfaced three ways: ``cerbos-tpuctl analyze`` (CI gating),
``cerbos_tpu_policy_analysis_total{class,reason}`` gauges republished on
every bundle build/swap, and the ``/_cerbos/debug/analysis`` endpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from .. import namer
from ..cel import ast as A
from ..cel.parser import token_offset
from ..compile import CompiledCondition, PolicyParams
from ..ruletable.rows import RuleRow
from ..ruletable.table import PolicyMeta, RuleTable
from .columns import TAG_BOOL, TAG_MISSING, TAG_NULL, TAG_NUM, TAG_OTHER, TAG_STR
from .condcompile import REASONS, CondKernel
from .lowering import LoweredTable, lower_table

CLASS_DEVICE = "device"
CLASS_TAGGED = "tagged-fallback"
CLASS_ORACLE = "oracle-only"

# plan-mode (PlanResources) eligibility: can BatchPlanner trust the device
# ternary verdict for this rule, or must it always take the sequential
# symbolic fallback? Decided statically by condcompile.plan_verdict.
PLAN_RESIDUALIZABLE = "residualizable"
PLAN_SYMBOLIC = "symbolic-only"

KIND_ELIGIBILITY = "eligibility"
KIND_DIVERGENCE = "divergence-risk"
KIND_GRAPH = "policy-graph"

# divergence-risk lint codes -> description (the analyzer's own vocabulary,
# disjoint from condcompile.REASONS which describes compiler rejections)
LINTS: dict[str, str] = {
    "float_equality": "equality against a non-integral float constant",
    "nan_constant": "NaN literal in a comparison",
    "mixed_timestamp_comparison": "timestamp compared against a non-timestamp operand",
    "string_ordering": "lexicographic ordering against a string constant",
    "deep_inlining": "variable inlining chain near the compiler depth bound",
}

GRAPH_FINDINGS: dict[str, str] = {
    "dead_rule": "ALLOW shadowed by an unconditional DENY in the same match cell",
    "unreachable_derived_role": "derived role imported but referenced by no rule",
    "undefined_reference": "condition references an undefined variable/constant/global",
}

# a variable chain this deep is legal (hard bound is 32) but every extra
# level multiplies re-inlined subtrees and the odds of float re-association
DEEP_INLINE_WARN = 8

_TAG_NAMES = {
    TAG_MISSING: "missing",
    TAG_NULL: "null",
    TAG_BOOL: "bool",
    TAG_NUM: "num",
    TAG_STR: "str",
    TAG_OTHER: "other",
}

_OP_TOKENS = {
    "_&&_": "&&",
    "_||_": "||",
    "!_": "!",
    "_==_": "==",
    "_!=_": "!=",
    "_<_": "<",
    "_<=_": "<=",
    "_>_": ">",
    "_>=_": ">=",
    "_in_": "in",
    "_?_:_": "?",
    "_[_]": "[",
}

_EQ_OPS = ("_==_", "_!=_")
_ORD_OPS = ("_<_", "_<=_", "_>_", "_>=_")


def _node_anchor(node: A.Node) -> tuple[Optional[str], Optional[tuple[str, ...]]]:
    """Map an AST node to (token text, token-kind filter) for offset lookup."""
    if isinstance(node, A.Call):
        return _OP_TOKENS.get(node.fn, node.fn), None
    if isinstance(node, (A.Select, A.Present)):
        return node.field, None
    if isinstance(node, A.Ident):
        return node.name, None
    if isinstance(node, A.Lit):
        v = node.value
        if isinstance(v, str):
            return v, ("STRING",)
        if isinstance(v, bool):
            return ("true" if v else "false"), None
        if v is None:
            return "null", None
        return str(v), None
    return None, None


def expr_offset(src: str, node: Optional[A.Node]) -> int:
    """Character offset of ``node``'s anchor token in ``src``; -1 if unknown."""
    if node is None:
        return -1
    anchor, kinds = _node_anchor(node)
    if not anchor:
        return -1
    return token_offset(src, anchor, kinds=kinds)


@dataclass
class Finding:
    """One analyzer diagnostic, addressable down to the expression token."""

    kind: str  # eligibility | divergence-risk | policy-graph
    code: str
    severity: str  # info | warning | error
    message: str
    policy: str = ""  # origin fqn
    file: str = ""  # source file (disk-store relpath) when known
    rule_index: int = -1  # row ordinal within the policy
    rule_name: str = ""
    expr: str = ""  # offending CEL source
    offset: int = -1  # char offset of the anchor token in expr
    path: str = ""  # dotted attribute path (fallback findings)
    tags: tuple[str, ...] = ()

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "kind": self.kind,
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "policy": self.policy,
        }
        if self.file:
            d["file"] = self.file
        if self.rule_index >= 0:
            d["rule_index"] = self.rule_index
        if self.rule_name:
            d["rule_name"] = self.rule_name
        if self.expr:
            d["expr"] = self.expr
            d["offset"] = self.offset
        if self.path:
            d["path"] = self.path
        if self.tags:
            d["tags"] = list(self.tags)
        return d

    def dedupe_key(self) -> tuple:
        return (self.kind, self.code, self.policy, self.rule_index, self.expr, self.offset, self.path)


@dataclass
class RuleReport:
    """Per-rule device-eligibility verdict with machine-readable reasons."""

    policy: str
    file: str
    rule_index: int
    rule_name: str
    evaluation_key: str
    row_id: int
    eligibility: str = CLASS_DEVICE
    # oracle-only reasons: [{code, reason, message, expr, offset}]
    reasons: list[dict[str, Any]] = field(default_factory=list)
    # tagged-fallback triggers: [{path, tags, reasons}]
    fallbacks: list[dict[str, Any]] = field(default_factory=list)
    # host-predicate columns (still device-classed): [{code, message, expr, offset}]
    predicates: list[dict[str, Any]] = field(default_factory=list)
    # plan-mode verdict + reasons when symbolic-only: [{code, reason, message, expr, offset}]
    plan: str = PLAN_RESIDUALIZABLE
    plan_reasons: list[dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "policy": self.policy,
            "file": self.file,
            "rule_index": self.rule_index,
            "rule_name": self.rule_name,
            "evaluation_key": self.evaluation_key,
            "eligibility": self.eligibility,
            "reasons": self.reasons,
            "fallbacks": self.fallbacks,
            "predicates": self.predicates,
            "plan": self.plan,
            "plan_reasons": self.plan_reasons,
        }

    def primary_reason(self) -> str:
        if self.eligibility == CLASS_ORACLE and self.reasons:
            return self.reasons[0]["code"]
        if self.eligibility == CLASS_TAGGED and self.fallbacks:
            for fb in self.fallbacks:
                if fb["reasons"]:
                    return fb["reasons"][0]
            return "tagged"
        return "ok"


@dataclass
class AnalysisReport:
    rules: list[RuleReport] = field(default_factory=list)
    findings: list[Finding] = field(default_factory=list)

    def class_counts(self) -> dict[str, int]:
        out = {CLASS_DEVICE: 0, CLASS_TAGGED: 0, CLASS_ORACLE: 0}
        for r in self.rules:
            out[r.eligibility] = out.get(r.eligibility, 0) + 1
        return out

    def plan_counts(self) -> dict[str, int]:
        """Plan-class histogram (the /_cerbos/debug/analysis 'Plan' block)."""
        out = {PLAN_RESIDUALIZABLE: 0, PLAN_SYMBOLIC: 0}
        for r in self.rules:
            out[r.plan] = out.get(r.plan, 0) + 1
        return out

    def finding_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def metric_counts(self) -> dict[tuple[str, str], int]:
        """(class, reason) -> count for the policy_analysis gauge family."""
        out: dict[tuple[str, str], int] = {}
        for r in self.rules:
            key = (r.eligibility, r.primary_reason())
            out[key] = out.get(key, 0) + 1
        for f in self.findings:
            if f.kind == KIND_ELIGIBILITY:
                continue  # already counted through the rule classes
            key = (f.kind, f.code)
            out[key] = out.get(key, 0) + 1
        for r in self.rules:
            if r.plan == PLAN_SYMBOLIC:
                code = r.plan_reasons[0]["code"] if r.plan_reasons else "unknown"
                key = ("plan-" + PLAN_SYMBOLIC, code)
            else:
                key = ("plan-" + PLAN_RESIDUALIZABLE, "ok")
            out[key] = out.get(key, 0) + 1
        return out

    def summary(self) -> dict[str, Any]:
        return {
            "rules": len(self.rules),
            "classes": self.class_counts(),
            "plan": self.plan_counts(),
            "findings": self.finding_counts(),
        }

    def to_dict(self) -> dict[str, Any]:
        return {
            "summary": self.summary(),
            "rules": [r.to_dict() for r in self.rules],
            "findings": [f.to_dict() for f in self.findings],
        }

    def summary_line(self) -> str:
        c = self.class_counts()
        pc = self.plan_counts()
        fc = self.finding_counts()
        return (
            f"policy analysis: {len(self.rules)} rules "
            f"({c[CLASS_DEVICE]} device, {c[CLASS_TAGGED]} tagged-fallback, "
            f"{c[CLASS_ORACLE]} oracle-only; "
            f"plan: {pc[PLAN_RESIDUALIZABLE]} residualizable, "
            f"{pc[PLAN_SYMBOLIC]} symbolic-only), "
            f"{fc.get(KIND_DIVERGENCE, 0)} divergence-risk, "
            f"{fc.get(KIND_GRAPH, 0)} policy-graph findings"
        )

    def failed(self, fail_on: str) -> bool:
        if fail_on == "oracle-only":
            return self.class_counts()[CLASS_ORACLE] > 0
        if fail_on == "divergence-risk":
            return self.finding_counts().get(KIND_DIVERGENCE, 0) > 0
        raise ValueError(f"unknown --fail-on criterion {fail_on!r}")


# ---------------------------------------------------------------------------
# condition-tree helpers


def _iter_exprs(cond: Optional[CompiledCondition]):
    if cond is None:
        return
    if cond.kind == "expr" and cond.expr is not None:
        yield cond.expr
    for ch in cond.children:
        yield from _iter_exprs(ch)


def _locate(
    node: Optional[A.Node],
    conds: Iterable[Optional[CompiledCondition]],
    params: Iterable[Optional[PolicyParams]],
) -> tuple[str, int]:
    """Best-effort (source, offset) for a node that may have been inlined.

    The compiler hands back AST nodes from *inlined* trees, so the node may
    originate in the rule's own expression or in a variable definition it
    pulled in. Try the rule expressions first, then variable defs.
    """
    if node is None:
        return "", -1
    srcs: list[str] = []
    for c in conds:
        for e in _iter_exprs(c):
            srcs.append(e.original)
    for p in params:
        if p is None:
            continue
        for v in p.ordered_variables:
            srcs.append(v.expr.original)
    first = srcs[0] if srcs else ""
    for src in srcs:
        off = expr_offset(src, node)
        if off >= 0:
            return src, off
    return first, -1


def _path_str(path: tuple[str, ...]) -> str:
    return ".".join(path)


def _tag_names(tags: frozenset[int]) -> tuple[str, ...]:
    return tuple(sorted(_TAG_NAMES.get(t, str(t)) for t in tags))


# ---------------------------------------------------------------------------
# eligibility


def _rule_kernels(lt: LoweredTable, row_id: int) -> list[CondKernel]:
    lr = lt.rows[row_id]
    ids = [lr.cond_id, lr.drcond_id, lr.negated_cond_id]
    return [lt.compiler.kernels[c] for c in ids if c >= 0]


def _classify_rule(rep: RuleReport, row: RuleRow, kernels: list[CondKernel]) -> None:
    conds = (row.condition, row.derived_role_condition)
    params = (row.params, row.derived_role_params)
    seen_fb: set[tuple[str, ...]] = set()
    for k in kernels:
        if k.oracle_reason is not None:
            code, msg, node = k.oracle_reason
            src, off = _locate(node, conds, params)
            rep.reasons.append(
                {
                    "code": code,
                    "reason": REASONS.get(code, code),
                    "message": msg,
                    "expr": src,
                    "offset": off,
                }
            )
        for path, tags in k.fallback_tags.items():
            if path in seen_fb:
                continue
            seen_fb.add(path)
            rcodes = sorted(k.fallback_reasons.get(path, frozenset()))
            rep.fallbacks.append(
                {
                    "path": _path_str(path),
                    "tags": list(_tag_names(tags)),
                    "reasons": rcodes,
                }
            )
        for code, msg, node in k.pred_reasons:
            src, off = _locate(node, conds, params)
            rep.predicates.append(
                {"code": code, "message": msg, "expr": src, "offset": off}
            )
    if any(k.emit is None for k in kernels):
        rep.eligibility = CLASS_ORACLE
    elif any(k.fallback_tags for k in kernels):
        rep.eligibility = CLASS_TAGGED
    else:
        rep.eligibility = CLASS_DEVICE

    # plan-mode verdict: symbolic-only as soon as ANY kernel of the rule
    # carries a plan_reason (BatchPlanner routes per kernel, but the rule-
    # level report answers "can this rule ever ride the device path")
    seen_plan: set[str] = set()
    for k in kernels:
        if k.plan_reason is None:
            continue
        code, msg, node = k.plan_reason
        if code in seen_plan:
            continue
        seen_plan.add(code)
        src, off = _locate(node, conds, params)
        rep.plan_reasons.append(
            {
                "code": code,
                "reason": REASONS.get(code, code),
                "message": msg,
                "expr": src,
                "offset": off,
            }
        )
    rep.plan = PLAN_SYMBOLIC if rep.plan_reasons else PLAN_RESIDUALIZABLE


# ---------------------------------------------------------------------------
# divergence-risk lints


def _is_timestamp_node(n: A.Node) -> bool:
    return isinstance(n, A.Call) and n.target is None and (
        (n.fn == "now" and not n.args) or (n.fn == "timestamp" and len(n.args) == 1)
    )


def _lint_expr(src: str, node: A.Node, add) -> None:
    for n in A.walk(node):
        if isinstance(n, A.Lit) and isinstance(n.value, float) and n.value != n.value:
            add("nan_constant", "NaN literal in expression", src, n)
        if not (isinstance(n, A.Call) and n.target is None):
            continue
        if n.fn in _EQ_OPS + _ORD_OPS and len(n.args) == 2:
            lhs, rhs = n.args
            if _is_timestamp_node(lhs) != _is_timestamp_node(rhs):
                add(
                    "mixed_timestamp_comparison",
                    "timestamp compared against a non-timestamp operand; host and "
                    "device coerce differently",
                    src,
                    n,
                )
            if n.fn in _EQ_OPS:
                for side in (lhs, rhs):
                    if (
                        isinstance(side, A.Lit)
                        and isinstance(side.value, float)
                        and not isinstance(side.value, bool)
                        and side.value == side.value
                        and not float(side.value).is_integer()
                    ):
                        add(
                            "float_equality",
                            f"equality against float constant {side.value!r}; "
                            "bit-inexact attribute encodings diverge here",
                            src,
                            n,
                        )
                        break
            else:
                for side in (lhs, rhs):
                    if isinstance(side, A.Lit) and isinstance(side.value, str):
                        add(
                            "string_ordering",
                            f"lexicographic ordering against {side.value!r}; device "
                            "string ordering uses interned ranks, not full collation",
                            src,
                            n,
                        )
                        break


def _var_refs(node: A.Node) -> list[str]:
    out = []
    for n in A.walk(node):
        if (
            isinstance(n, A.Select)
            and isinstance(n.operand, A.Ident)
            and n.operand.name in ("V", "variables")
        ):
            out.append(n.field)
    return out


def _var_depths(params: Optional[PolicyParams]) -> dict[str, int]:
    """Inlining depth of each variable (1 = no nested variable references)."""
    if params is None:
        return {}
    defs = {v.name: v.expr.node for v in params.ordered_variables}
    depths: dict[str, int] = {}

    def depth_of(name: str, stack: tuple[str, ...]) -> int:
        if name in depths:
            return depths[name]
        if name in stack:
            return 99  # cycle: the compiler's depth bound will reject it
        n = defs.get(name)
        if n is None:
            return 0
        d = 1 + max([depth_of(r, stack + (name,)) for r in _var_refs(n)] or [0])
        depths[name] = d
        return d

    for name in defs:
        depth_of(name, ())
    return depths


# ---------------------------------------------------------------------------
# policy-graph audit


def _covers(pattern: str, value: Optional[str]) -> bool:
    return pattern == "*" or pattern == (value or "")


def _graph_audit(
    rt: RuleTable,
    rows_by_policy: dict[str, list[RuleRow]],
    file_of: dict[str, str],
    add_finding,
) -> None:
    # dead rules: an ALLOW whose whole match cell is covered by an
    # unconditional DENY of the same policy (DENY always wins within a cell,
    # so the ALLOW can never change an outcome). Conservative on purpose:
    # exact scope/version/resource/principal, glob-or-equal role+action,
    # plain DENY rows only (no derived-role origin, no role-policy rows).
    for fqn, rows in rows_by_policy.items():
        denies = [
            r
            for r in rows
            if r.effect == "EFFECT_DENY"
            and r.condition is None
            and r.derived_role_condition is None
            and not r.origin_derived_role
            and not r.from_role_policy
            and not r.no_match_for_scope_permissions
            and r.action is not None
        ]
        if not denies:
            continue
        for idx, r in enumerate(rows):
            if r.effect != "EFFECT_ALLOW" or r.action is None:
                continue
            for d in denies:
                if (
                    d.scope == r.scope
                    and d.version == r.version
                    and d.resource == r.resource
                    and d.principal == r.principal
                    and _covers(d.role, r.role)
                    and _covers(d.action, r.action)
                ):
                    add_finding(
                        Finding(
                            kind=KIND_GRAPH,
                            code="dead_rule",
                            severity="warning",
                            message=(
                                f"ALLOW rule for action {r.action!r} role "
                                f"{r.role or '*'!r} is dead: unconditional DENY "
                                f"{d.name or d.evaluation_key!r} shadows the same cell"
                            ),
                            policy=fqn,
                            file=file_of.get(fqn, ""),
                            rule_index=idx,
                            rule_name=r.name,
                        )
                    )
                    break

    # unreachable derived roles: imported by a policy but referenced by none
    # of its rows (origin_derived_role is set per expanded parent-role row)
    used: dict[int, set[str]] = {}
    for rows in rows_by_policy.values():
        for r in rows:
            if r.origin_derived_role:
                used.setdefault(namer.module_id(r.origin_fqn), set()).add(
                    r.origin_derived_role
                )
    for mod_id, drs in rt.policy_derived_roles.items():
        meta: Optional[PolicyMeta] = rt.meta.get(mod_id)
        pol_fqn = meta.fqn if meta else ""
        for name, dr in drs.items():
            if name not in used.get(mod_id, set()):
                add_finding(
                    Finding(
                        kind=KIND_GRAPH,
                        code="unreachable_derived_role",
                        severity="info",
                        message=(
                            f"derived role {name!r} (from {dr.origin_fqn}) is "
                            "imported but referenced by no rule"
                        ),
                        policy=pol_fqn,
                        file=file_of.get(pol_fqn, ""),
                    )
                )


def _undefined_refs(
    node: A.Node,
    params: Optional[PolicyParams],
    globals_: dict[str, Any],
) -> list[tuple[str, str]]:
    """(root-kind, name) for V/C/G selects that resolve to nothing."""
    var_names = (
        {v.name for v in params.ordered_variables} if params is not None else set()
    )
    consts = params.constants if params is not None else {}
    out: list[tuple[str, str]] = []
    for n in A.walk(node):
        if not (isinstance(n, A.Select) and isinstance(n.operand, A.Ident)):
            continue
        root = n.operand.name
        if root in ("V", "variables") and n.field not in var_names:
            out.append(("variable", n.field))
        elif root in ("C", "constants") and n.field not in consts:
            out.append(("constant", n.field))
        elif root in ("G", "globals") and n.field not in globals_:
            out.append(("global", n.field))
    return out


# ---------------------------------------------------------------------------
# entry points


def analyze_table(
    rt: RuleTable,
    globals_: Optional[dict[str, Any]] = None,
    lowered: Optional[LoweredTable] = None,
) -> AnalysisReport:
    """Analyze a built rule table. Pass ``lowered`` to reuse an existing
    lowering (swap-time hook) instead of compiling a fresh audit copy."""
    globals_ = globals_ or {}
    lt = lowered if lowered is not None else lower_table(rt, globals_)
    report = AnalysisReport()
    seen_findings: set[tuple] = set()

    def add_finding(f: Finding) -> None:
        key = f.dedupe_key()
        if key not in seen_findings:
            seen_findings.add(key)
            report.findings.append(f)

    file_of: dict[str, str] = {}
    for meta in rt.meta.values():
        src = meta.source_attributes.get("source")
        if isinstance(src, str):
            file_of[meta.fqn] = src

    rows_by_policy: dict[str, list[RuleRow]] = {}
    for row in rt.idx.get_all_rows():
        rows_by_policy.setdefault(row.origin_fqn, []).append(row)

    for fqn, rows in sorted(rows_by_policy.items()):
        fname = file_of.get(fqn, "")
        linted_params: set[int] = set()
        for idx, row in enumerate(rows):
            rep = RuleReport(
                policy=fqn,
                file=fname,
                rule_index=idx,
                rule_name=row.name,
                evaluation_key=row.evaluation_key,
                row_id=row.id,
            )
            _classify_rule(rep, row, _rule_kernels(lt, row.id))
            report.rules.append(rep)
            if rep.eligibility == CLASS_ORACLE:
                for r in rep.reasons:
                    add_finding(
                        Finding(
                            kind=KIND_ELIGIBILITY,
                            code=r["code"],
                            severity="warning",
                            message=r["message"],
                            policy=fqn,
                            file=fname,
                            rule_index=idx,
                            rule_name=row.name,
                            expr=r["expr"],
                            offset=r["offset"],
                        )
                    )
            elif rep.eligibility == CLASS_TAGGED:
                for fb in rep.fallbacks:
                    add_finding(
                        Finding(
                            kind=KIND_ELIGIBILITY,
                            code=(fb["reasons"][0] if fb["reasons"] else "tagged"),
                            severity="info",
                            message=(
                                f"requests where {fb['path']} carries a "
                                f"{'/'.join(fb['tags'])} tag fall back to the oracle"
                            ),
                            policy=fqn,
                            file=fname,
                            rule_index=idx,
                            rule_name=row.name,
                            path=fb["path"],
                            tags=tuple(fb["tags"]),
                        )
                    )

            # lints + undefined references over the rule's own expressions
            # and (once per params object) its variable definitions
            def lint_add(code, msg, src, n, idx=idx, row=row, fname=fname, fqn=fqn):
                add_finding(
                    Finding(
                        kind=KIND_DIVERGENCE,
                        code=code,
                        severity="warning",
                        message=msg,
                        policy=fqn,
                        file=fname,
                        rule_index=idx,
                        rule_name=row.name,
                        expr=src,
                        offset=expr_offset(src, n),
                    )
                )

            for cond, params in (
                (row.condition, row.params),
                (row.derived_role_condition, row.derived_role_params),
            ):
                if cond is None:
                    continue
                for e in _iter_exprs(cond):
                    _lint_expr(e.original, e.node, lint_add)
                    for kind_, name_ in _undefined_refs(e.node, params, globals_):
                        add_finding(
                            Finding(
                                kind=KIND_GRAPH,
                                code="undefined_reference",
                                severity="error",
                                message=f"condition references undefined {kind_} {name_!r}",
                                policy=fqn,
                                file=fname,
                                rule_index=idx,
                                rule_name=row.name,
                                expr=e.original,
                                offset=token_offset(e.original, name_),
                            )
                        )
                if params is not None and id(params) not in linted_params:
                    linted_params.add(id(params))
                    for v in params.ordered_variables:
                        _lint_expr(v.expr.original, v.expr.node, lint_add)
                        for kind_, name_ in _undefined_refs(v.expr.node, params, globals_):
                            add_finding(
                                Finding(
                                    kind=KIND_GRAPH,
                                    code="undefined_reference",
                                    severity="error",
                                    message=(
                                        f"variable {v.name!r} references undefined "
                                        f"{kind_} {name_!r}"
                                    ),
                                    policy=fqn,
                                    file=fname,
                                    expr=v.expr.original,
                                    offset=token_offset(v.expr.original, name_),
                                )
                            )
                    depths = _var_depths(params)
                    for vname, d in depths.items():
                        if d >= DEEP_INLINE_WARN:
                            vdef = next(
                                ve for ve in params.ordered_variables if ve.name == vname
                            )
                            add_finding(
                                Finding(
                                    kind=KIND_DIVERGENCE,
                                    code="deep_inlining",
                                    severity="warning",
                                    message=(
                                        f"variable {vname!r} inlines {d} levels deep "
                                        "(compiler bound is 32); deep chains amplify "
                                        "float re-association divergence"
                                    ),
                                    policy=fqn,
                                    file=fname,
                                    expr=vdef.expr.original,
                                )
                            )

    _graph_audit(rt, rows_by_policy, file_of, add_finding)
    report.findings.sort(
        key=lambda f: ({"error": 0, "warning": 1, "info": 2}.get(f.severity, 3), f.policy, f.rule_index)
    )
    return report


def analyze_policies(
    policies: Iterable[Any], globals_: Optional[dict[str, Any]] = None
) -> AnalysisReport:
    """Compile a raw policy set (storage Policy objects) and analyze it."""
    from ..compile import compile_policy_set
    from ..ruletable.table import build_rule_table

    policies = list(policies)
    cps = compile_policy_set(policies)
    rt = build_rule_table(cps)
    report = analyze_table(rt, globals_)
    _audit_unused_derived_roles(policies, report)
    return report


def _audit_unused_derived_roles(policies: list[Any], report: AnalysisReport) -> None:
    """Flag derived-role definitions no importing rule ever references.

    The compiler prunes unreferenced definitions before they reach the rule
    table, so this is only detectable while the raw policy objects are in
    hand — table-level analysis (swap-time hook) cannot see them."""
    defs: dict[str, list[tuple[str, str]]] = {}  # set name -> [(role, file)]
    referenced: set[str] = set()
    imported: set[str] = set()
    for p in policies:
        dr = getattr(p, "derived_roles", None)
        if dr is not None:
            meta = getattr(p, "metadata", None)
            src = (meta.source_attributes.get("source", "") if meta else "") or ""
            defs[dr.name] = [(d.name, src) for d in dr.definitions]
        rp = getattr(p, "resource_policy", None)
        if rp is not None:
            imported.update(rp.import_derived_roles)
            for r in rp.rules:
                referenced.update(r.derived_roles)
    existing = {f.dedupe_key() for f in report.findings}
    for set_name, roles in sorted(defs.items()):
        if set_name not in imported:
            continue  # never imported: dangling set, not a per-role finding
        for role, src in roles:
            if role in referenced:
                continue
            f = Finding(
                kind=KIND_GRAPH,
                code="unreachable_derived_role",
                severity="info",
                message=(
                    f"derived role {role!r} (derived-roles set {set_name!r}) "
                    "is defined but referenced by no rule"
                ),
                policy=namer.derived_roles_fqn(set_name),
                file=src,
            )
            if f.dedupe_key() not in existing:
                report.findings.append(f)


# ---------------------------------------------------------------------------
# publication (gauges + latest-report singleton for the debug endpoint)

_latest: Optional[AnalysisReport] = None
_published_keys: set[tuple[str, str]] = set()


def publish(report: AnalysisReport) -> AnalysisReport:
    """Export ``cerbos_tpu_policy_analysis_total{class,reason}`` gauges and
    retain the report for ``/_cerbos/debug/analysis``. Keys published by a
    previous bundle that vanished in this one are zeroed, not dropped, so
    scrapes never see a stale non-zero sample."""
    global _latest, _published_keys
    from ..observability import metrics

    vec = metrics().gauge_vec(
        "cerbos_tpu_policy_analysis_total",
        "Static policy-analysis verdicts by eligibility class / finding kind and stable reason code",
        label=("class", "reason"),
    )
    counts = report.metric_counts()
    for key in _published_keys - set(counts):
        vec.set(key, 0.0)
    for key, n in counts.items():
        vec.set(key, float(n))
    _published_keys = set(counts)
    _latest = report
    return report


def latest() -> Optional[AnalysisReport]:
    return _latest


# ---------------------------------------------------------------------------
# CLI rendering


def render_text(report: AnalysisReport) -> str:
    lines = [report.summary_line()]
    nondevice = [r for r in report.rules if r.eligibility != CLASS_DEVICE]
    if nondevice:
        lines.append("")
        lines.append("non-device rules:")
        for r in nondevice:
            loc = r.file or r.policy
            lines.append(
                f"  [{r.eligibility}] [plan: {r.plan}] {loc} rule#{r.rule_index} {r.evaluation_key}"
            )
            for reason in r.reasons:
                lines.append(
                    f"      {reason['code']}: {reason['message']}"
                    + (f"  ({reason['expr']!r} @{reason['offset']})" if reason["expr"] else "")
                )
            for fb in r.fallbacks:
                rs = f" [{', '.join(fb['reasons'])}]" if fb["reasons"] else ""
                lines.append(f"      fallback {fb['path']} tags={'/'.join(fb['tags'])}{rs}")
    plan_symbolic = [r for r in report.rules if r.plan != PLAN_RESIDUALIZABLE]
    if plan_symbolic:
        lines.append("")
        lines.append("plan symbolic-only rules:")
        for r in plan_symbolic:
            loc = r.file or r.policy
            lines.append(f"  [plan: {r.plan}] {loc} rule#{r.rule_index} {r.evaluation_key}")
            for reason in r.plan_reasons:
                lines.append(
                    f"      {reason['code']}: {reason['message']}"
                    + (f"  ({reason['expr']!r} @{reason['offset']})" if reason["expr"] else "")
                )
    shown = [f for f in report.findings if f.kind != KIND_ELIGIBILITY]
    if shown:
        lines.append("")
        lines.append("findings:")
        for f in shown:
            loc = f.file or f.policy
            at = f" rule#{f.rule_index}" if f.rule_index >= 0 else ""
            lines.append(f"  {f.severity}: [{f.code}] {loc}{at}: {f.message}")
            if f.expr:
                lines.append(f"      {f.expr!r} @{f.offset}")
    return "\n".join(lines)
