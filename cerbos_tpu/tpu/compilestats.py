"""Compile-economy telemetry: the other half of the device serving cost.

The request path is lit end to end (spans, stage histograms, the flight
recorder), but XLA compilation — ~35 s cold per distinct trace, the single
largest latency event a replica can produce — was dark. This module wraps
every ``jit_cache`` population site in :mod:`evaluator` plus the persistent
cache in :mod:`jitcache` and answers, per process:

- how many compiles happened, how long each took, and whether the
  persistent cache absorbed them (``cerbos_tpu_xla_compiles_total{source}``,
  ``cerbos_tpu_xla_compile_seconds``);
- how often the live jit cache hit vs missed
  (``cerbos_tpu_jit_cache_{hits,misses}_total``);
- how many distinct compiled layouts exist
  (``cerbos_tpu_xla_layout_cardinality``) — the figure that bounds both
  device program memory and worst-case warmup time;
- device memory from ``device.memory_stats()`` when a backend exposes it;
- whether the layout keyspace is CHURNING: the recompile-storm detector
  fires when >= N distinct layouts compile within W seconds, meaning the
  shape-bucket ladder or variant budget no longer amortizes and the replica
  is spending its time in XLA instead of serving.

Everything is process-global (like the metrics registry it feeds) so the
serving batcher, the pipelined path, and bench all account into one place.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..engine.flight import recorder as flight_recorder
from ..observability import metrics

_log = logging.getLogger("cerbos_tpu.compilestats")

# compile latencies span four orders of magnitude: sub-second persistent
# cache loads up to multi-minute cold TPU compiles
_COMPILE_BUCKETS = [0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 80.0, 160.0]

STORM_THRESHOLD = 8
STORM_WINDOW_S = 120.0


class RecompileStormDetector:
    """Sliding-window detector over compile events.

    A healthy replica compiles each dominant layout once and then serves
    from cache; a storm (>= ``threshold`` DISTINCT layout keys compiled
    within ``window_s`` seconds) means traffic shapes are defeating the
    pow2 bucket ladder / variant budget. Fires once per excursion: after
    tripping, it stays quiet until the distinct count falls back below the
    threshold, so a sustained storm is one event, not one per compile.

    ``clock`` is injectable for deterministic tests (same pattern as
    ``engine.health.DeviceHealth``).
    """

    def __init__(
        self,
        threshold: int = STORM_THRESHOLD,
        window_s: float = STORM_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self._clock = clock
        self._events: deque[tuple[float, Any]] = deque()
        self._lock = threading.Lock()
        self._in_storm = False
        self.storms = 0

    def observe(self, layout_key: Any) -> Optional[int]:
        """Record one compile; returns the distinct-layout count when this
        observation trips a NEW storm, else None."""
        now = self._clock()
        with self._lock:
            self._events.append((now, layout_key))
            cutoff = now - self.window_s
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()
            distinct = len({k for _, k in self._events})
            if distinct < self.threshold:
                self._in_storm = False
                return None
            if self._in_storm:
                return None
            self._in_storm = True
            self.storms += 1
            return distinct


class CompileStats:
    """Process-wide compile accounting feeding the shared metrics registry."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        storm_threshold: int = STORM_THRESHOLD,
        storm_window_s: float = STORM_WINDOW_S,
    ):
        reg = metrics()
        self.m_compiles = reg.counter_vec(
            "cerbos_tpu_xla_compiles_total",
            "XLA compilations by source: fresh (XLA ran) or persistent (loaded from the on-disk cache)",
            label="source",
        )
        self.m_compile_seconds = reg.histogram(
            "cerbos_tpu_xla_compile_seconds",
            "Wall time of each XLA compile (first invocation of a new jit trace)",
            buckets=_COMPILE_BUCKETS,
        )
        self.m_hits = reg.counter(
            "cerbos_tpu_jit_cache_hits_total",
            "Device dispatches served by an already-compiled jit trace",
        )
        self.m_misses = reg.counter(
            "cerbos_tpu_jit_cache_misses_total",
            "Device dispatches that had to build (and compile) a new jit trace",
        )
        self.m_cardinality = reg.gauge(
            "cerbos_tpu_xla_layout_cardinality",
            "Distinct compiled device layouts (shape bucket x variant x column layout) this process",
        )
        self.m_storms = reg.counter(
            "cerbos_tpu_recompile_storms_total",
            "Recompile storms: sliding-window excursions of distinct-layout compiles",
        )
        self.m_variant_fallbacks = reg.counter(
            "cerbos_tpu_variant_budget_fallbacks_total",
            "Batches forced onto the full variant because the distinct-variant budget was exhausted",
        )
        self.m_mem_in_use = reg.gauge(
            "cerbos_tpu_device_memory_bytes_in_use",
            "Device memory in use (device.memory_stats, 0 when the backend reports none)",
        )
        self.m_mem_limit = reg.gauge(
            "cerbos_tpu_device_memory_bytes_limit",
            "Device memory capacity (device.memory_stats, 0 when the backend reports none)",
        )
        self.m_mem_peak = reg.gauge(
            "cerbos_tpu_device_memory_peak_bytes_in_use",
            "Peak device memory in use (device.memory_stats, 0 when the backend reports none)",
        )
        self.detector = RecompileStormDetector(
            threshold=storm_threshold, window_s=storm_window_s, clock=clock
        )
        self._lock = threading.Lock()
        self._layouts: set[Any] = set()
        self._per_layout: dict[str, int] = {}
        self._compiles = 0
        self._compile_seconds = 0.0
        self._persistent = 0
        self._hits = 0
        self._misses = 0

    # -- recording ---------------------------------------------------------

    def record_compile(
        self, layout_key: str, seconds: float, source: str = "fresh", trace_key: Any = None
    ) -> None:
        """One compile completed. ``layout_key`` is the display shape
        signature (``B64xBA128``-style); ``trace_key`` is the exact jit-cache
        key, so cardinality/storm detection see variant and column-layout
        churn that shares a shape bucket."""
        tk = trace_key if trace_key is not None else layout_key
        self.m_compiles.inc(source)
        self.m_compile_seconds.observe(seconds)
        with self._lock:
            self._compiles += 1
            self._compile_seconds += seconds
            if source == "persistent":
                self._persistent += 1
            self._layouts.add(tk)
            self._per_layout[layout_key] = self._per_layout.get(layout_key, 0) + 1
            card = len(self._layouts)
        self.m_cardinality.set(card)
        distinct = self.detector.observe(tk)
        if distinct is not None:
            self.m_storms.inc()
            _log.warning(
                "recompile storm: %d distinct device layouts compiled within %.0fs "
                "(threshold %d, last layout %s) — shape buckets or variant budget "
                "are churning faster than the cache amortizes",
                distinct,
                self.detector.window_s,
                self.detector.threshold,
                layout_key,
            )
            flight_recorder().record_event(
                "recompile_storm",
                distinct=distinct,
                window_s=self.detector.window_s,
                threshold=self.detector.threshold,
                layout_key=layout_key,
            )
        self.refresh_device_memory()

    def record_hit(self) -> None:
        self.m_hits.inc()
        with self._lock:
            self._hits += 1

    def record_miss(self) -> None:
        self.m_misses.inc()
        with self._lock:
            self._misses += 1

    def record_variant_fallback(self) -> None:
        self.m_variant_fallbacks.inc()

    def refresh_device_memory(self) -> None:
        """Update the device memory gauges when a backend reports them.

        Reads ``sys.modules`` instead of importing: telemetry must never be
        the thing that initializes a jax backend."""
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            devs = jax.devices()
        except Exception:
            return
        if not devs:
            return
        try:
            stats = devs[0].memory_stats()
        except Exception:
            stats = None
        if not stats:
            return
        if "bytes_in_use" in stats:
            self.m_mem_in_use.set(float(stats["bytes_in_use"]))
        if "bytes_limit" in stats:
            self.m_mem_limit.set(float(stats["bytes_limit"]))
        if "peak_bytes_in_use" in stats:
            self.m_mem_peak.set(float(stats["peak_bytes_in_use"]))

    # -- reading -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Machine-readable compile economics (bench artifact, jitcache
        status, debug surfaces)."""
        with self._lock:
            return {
                "compiles": self._compiles,
                "compile_seconds_total": round(self._compile_seconds, 6),
                "persistent_loads": self._persistent,
                "cache_hits": self._hits,
                "cache_misses": self._misses,
                "layout_cardinality": len(self._layouts),
                "storms": self.detector.storms,
                "per_layout_compiles": dict(self._per_layout),
            }


_stats = CompileStats()


def stats() -> CompileStats:
    return _stats


def configure(storm_threshold: Optional[int] = None, storm_window_s: Optional[float] = None) -> CompileStats:
    """Re-bound the global detector in place (bootstrap), preserving the
    instance every instrumented module already holds."""
    det = _stats.detector
    if storm_threshold is not None:
        det.threshold = int(storm_threshold)
    if storm_window_s is not None:
        det.window_s = float(storm_window_s)
    return _stats


def timed_first_call(layout_key: str, fn: Callable[..., Any], kwargs: dict, trace_key: Any = None):
    """Invoke a FRESHLY BUILT jit function, timing its first call.

    ``jax.jit`` defers trace+compile to the first invocation (dispatch of
    the compiled program stays async, so the measured wall time is the
    compile, not the device execution). The persistent-cache entry count
    before/after classifies the source: a compile that writes no new entry
    while the cache is enabled was loaded from disk."""
    from . import jitcache

    before = jitcache.entry_count()
    t0 = time.perf_counter()
    out = fn(**kwargs)
    dt = time.perf_counter() - t0
    source = "fresh"
    if before is not None:
        after = jitcache.entry_count()
        if after is not None and after <= before:
            source = "persistent"
    _stats.record_compile(layout_key, dt, source=source, trace_key=trace_key)
    return out
