"""Operator-gated on-demand device profiling.

``GET /_cerbos/debug/profile?seconds=N`` captures a ``jax.profiler.trace``
for N seconds of whatever the serving path is doing and returns the
artifact directory — the tool for "the batch stage histogram says device
time doubled, WHAT is the device doing". Gated off by default
(``engine.tpu.profiler.enabled``): a trace capture perturbs the device and
writes files, so it must be an explicit operator decision.

Artifacts land under a bounded directory: each capture gets its own
timestamped subdirectory and the oldest captures beyond ``maxArtifacts``
are pruned, so a flapping operator cannot fill the disk.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time

_log = logging.getLogger("cerbos_tpu.profiler")


class ProfilerDisabled(RuntimeError):
    """Profiling is not enabled in the configuration."""


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (one at a time: overlapping device
    traces corrupt each other)."""


class ProfilerUnavailable(RuntimeError):
    """The jax profiler cannot run in this process (no jax, old jax)."""


_lock = threading.Lock()
_enabled = False
_dir = ""
_max_artifacts = 4
_max_seconds = 30.0
_active = False
_seq = 0


def configure(
    enabled: bool = False,
    dir: str = "",
    max_artifacts: int = 4,
    max_seconds: float = 30.0,
) -> None:
    global _enabled, _dir, _max_artifacts, _max_seconds
    with _lock:
        _enabled = bool(enabled)
        _dir = str(dir or "")
        _max_artifacts = max(1, int(max_artifacts))
        _max_seconds = float(max_seconds)


def enabled() -> bool:
    return _enabled


def base_dir() -> str:
    return _dir or os.path.join(tempfile.gettempdir(), "cerbos_tpu_profiles")


def _prune(base: str, keep: int) -> None:
    try:
        entries = sorted(
            (e for e in os.scandir(base) if e.is_dir()), key=lambda e: e.name
        )
    except OSError:
        return
    for e in entries[:-keep] if keep < len(entries) else []:
        shutil.rmtree(e.path, ignore_errors=True)


def _run_trace(path: str, seconds: float) -> None:
    """Separated for testability: the actual jax capture."""
    try:
        import jax  # noqa: F401  (availability probe: surface ImportError here)
        from jax import profiler as jprof
    except Exception as e:  # pragma: no cover - jax is a hard dep in practice
        raise ProfilerUnavailable(f"jax profiler unavailable: {e}") from e
    if not hasattr(jprof, "trace"):
        raise ProfilerUnavailable("this jax has no profiler.trace")
    with jprof.trace(path):
        time.sleep(seconds)


def capture(seconds: float) -> dict:
    """Blocking capture; returns ``{path, seconds}`` for the response body.

    Raises ProfilerDisabled / ProfilerBusy / ProfilerUnavailable /
    ValueError (bad duration) — the HTTP handler maps each to a status.
    """
    global _active, _seq
    if not _enabled:
        raise ProfilerDisabled("profiling disabled (engine.tpu.profiler.enabled)")
    seconds = float(seconds)
    if seconds <= 0:
        raise ValueError("seconds must be > 0")
    seconds = min(seconds, _max_seconds)
    with _lock:
        if _active:
            raise ProfilerBusy("a profile capture is already running")
        _active = True
    try:
        base = base_dir()
        os.makedirs(base, exist_ok=True)
        _seq += 1
        name = time.strftime("%Y%m%dT%H%M%S") + f"-p{os.getpid()}-{_seq:03d}"
        path = os.path.join(base, name)
        _log.info("profile capture: %.1fs -> %s", seconds, path)
        _run_trace(path, seconds)
        _prune(base, _max_artifacts)
        return {"path": path, "seconds": seconds}
    finally:
        with _lock:
            _active = False
