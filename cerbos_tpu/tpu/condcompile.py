"""Condition compiler: CEL AST → vectorized, TEMPLATED JAX kernels.

Each distinct (condition, params) pair becomes one kernel computing a
satisfied bit per batch element over SoA attribute columns, reproducing
cel-go semantics: missing keys are errors, ``&&``/``||`` absorb errors
commutatively, mismatched-type equality is false, mismatched ordering is an
error. Variables/constants/globals are inlined at compile time (sound:
conditions are pure and variables are topologically ordered).

**Templating** (the scale property): policy fleets repeat condition
*structures* with different literals (``R.attr.amount < 100`` vs ``< 250``).
Kernels are compiled against constant SLOTS instead of baked scalars; all
kernels sharing a template signature (identical AST shape, paths and
operators — literals abstracted) form one group whose emit evaluates every
member at once: columns enter as ``[B, 1]``, slot constants as ``[1, G]``,
and the whole group resolves with one broadcast compare per leaf. The jit
graph is therefore O(distinct templates), not O(distinct conditions) — at
2,000 distinct conditions sharing 2 shapes, XLA compiles 2 subgraphs
instead of 2,000 (which took 126 s on CPU).

Fragments outside the native device op set — regex, arithmetic, function
calls — compile to *predicate columns*: host-evaluated (value, error) bits
per input, cached per unique referenced-attribute tuple; the pred id is
itself a slot, so pred-bearing kernels still template. Timestamp
comparisons (``timestamp(path) op timestamp(lit)/now()``) ride parsed
key columns on device. Paths whose runtime values the device cannot
compare (lists/dicts under ``==``, strings under path-vs-path ``<``)
register fallback trigger tags; the packer routes affected inputs to the
CPU oracle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from ..cel import ast as A
from ..cel.errors import CelError
from ..compile import CompiledCondition, PolicyParams
from .columns import (
    TAG_BOOL,
    TAG_MISSING,
    TAG_NULL,
    TAG_NUM,
    TAG_OTHER,
    TAG_STR,
    StringInterner,
    double_key,
    split_key,
)

TAG_ERR = 6

_ROOT_ALIASES = {
    "R": ("resource",),
    "P": ("principal",),
    "request": (),
}


# Stable machine-readable reason codes for every Unsupported raise site.
# These are the analyzer's (tpu/analyze.py) and the runtime counter's
# (cond_compile_unsupported_total{reason}) shared vocabulary: messages may
# be reworded freely, codes may not. tests/test_condcompile_analysis.py
# exercises each site and fails the suite on a raise without a code.
REASONS: dict[str, str] = {
    "inline_too_deep": "variable inlining exceeded the depth bound",
    "undefined_variable": "condition references an undefined variable",
    "undefined_constant": "condition references an undefined constant",
    "undefined_global": "condition references an undefined global",
    "non_literal_list_element": "list literal with a non-literal element",
    "operand_unsupported": "operand is neither a literal nor an attribute path",
    "unsupported_function": "function outside the native device op set",
    "non_bool_literal": "non-boolean literal in boolean position",
    "unsupported_bool_expr": "boolean expression shape outside the device op set",
    "has_on_non_path": "has() over a non-attribute-path operand",
    "bad_timestamp_constant": "timestamp() constant failed to convert",
    "mixed_timestamp_equality": "equality between a timestamp and an untyped operand",
    "const_const_equality": "constant == constant (host constant folding)",
    "list_equality": "equality against a list constant",
    "unsupported_equality_constant": "equality against an unsupported constant type",
    "mixed_timestamp_ordering": "ordering between a timestamp and an untyped operand",
    "const_const_ordering": "constant-vs-constant ordering (host constant folding)",
    "string_ordering_constant": "string ordering against a constant",
    "non_numeric_ordering_constant": "ordering against a non-numeric constant",
    "nan_ordering_constant": "ordering against a NaN constant",
    "unsupported_membership": "membership test shape outside the device op set",
    # plan-mode (PlanResources) eligibility verdicts: a kernel carrying one
    # of these can still run in check mode, but BatchPlanner must route the
    # rule to the sequential symbolic fallback instead of the device
    # ternary path (see plan/batch.py and docs/PLAN.md)
    "plan_time_dependent": "condition depends on now(); plan has no single evaluation instant",
    "plan_unknown_resource_field": "references a resource field PlanResources never knows",
}


class Unsupported(Exception):
    """Raised during compilation when a fragment needs a predicate column.

    Carries a stable reason ``code`` (a key of :data:`REASONS`) and the
    offending AST ``node`` so the static analyzer and the runtime fallback
    counter speak the same vocabulary as this free-text message.
    """

    def __init__(self, msg: str, code: str = "unsupported", node: Optional[A.Node] = None):
        super().__init__(msg)
        self.code = code
        self.node = node


@dataclass
class PredSpec:
    """A host-evaluated boolean subexpression."""

    pred_id: int
    node: A.Node
    params: PolicyParams
    ref_paths: tuple[tuple[str, ...], ...]
    time_dependent: bool


@dataclass
class CondKernel:
    cond_id: int
    paths: set[tuple[str, ...]] = field(default_factory=set)
    preds: list[PredSpec] = field(default_factory=list)
    # non-None marks the kernel device-evaluable (callers only None-check).
    # The stored value is the SHARED template emit — signature (refs, gc) —
    # which is only ever invoked through KernelGroup with the group's
    # constant vectors; do not call it with this kernel alone
    emit: Optional[Callable[..., Any]] = None
    # tags that force CPU fallback when seen at a path in a batch
    fallback_tags: dict[tuple[str, ...], frozenset[int]] = field(default_factory=dict)
    # paths needing string-list membership columns
    list_paths: set[tuple[str, ...]] = field(default_factory=set)
    # paths compared as timestamps (timestamp(path) op ...)
    ts_paths: set[tuple[str, ...]] = field(default_factory=set)
    # kernel reads the batch-constant now() key
    uses_now: bool = False
    references_runtime: bool = False
    # templating artifacts
    template_sig: Optional[tuple] = None
    slot_kinds: tuple[str, ...] = ()
    slot_values: tuple[Any, ...] = ()
    # compile audit trail (tpu/analyze.py): expr-level Unsupported codes
    # that became predicate columns, the tree-level rejection that nulled
    # emit, and the per-path reason behind each fallback tag registration
    pred_reasons: list[tuple[str, str, Optional[A.Node]]] = field(default_factory=list)
    oracle_reason: Optional[tuple[str, str, Optional[A.Node]]] = None
    fallback_reasons: dict[tuple[str, ...], frozenset[str]] = field(default_factory=dict)
    # plan-mode verdict, decided statically at compile time: None means the
    # kernel is residualizable (device ternary evaluation is sound when the
    # per-query resource deps are known); a (code, msg, node) triple means
    # BatchPlanner must always take the symbolic fallback for this kernel
    plan_reason: Optional[tuple[str, str, Optional[A.Node]]] = None

    def resource_dep_paths(self) -> set[tuple[str, ...]]:
        """Every resource-rooted path the kernel's verdict can depend on.

        The union of device column paths, host predicate references, list
        membership columns and timestamp columns — plan.batch uses this to
        decide, per query, whether a device TRUE/FALSE is trustworthy."""
        deps: set[tuple[str, ...]] = set()
        for p in self.paths:
            deps.add(p)
        for spec in self.preds:
            deps.update(spec.ref_paths)
        deps.update(self.list_paths)
        deps.update(self.ts_paths)
        return {p for p in deps if p and p[0] == "resource"}


@dataclass
class KernelGroup:
    """All kernels sharing one template: one traced subgraph, G members."""

    emit: Callable[["Refs", "GroupConsts"], Any]  # -> sat [B, G]
    gc: "GroupConsts"
    cond_ids: list[int]
    # ndarray form for per-batch active-mask lookups (hot path); derived —
    # a mis-wired empty array would silently disable the whole group
    cond_id_arr: np.ndarray = field(init=False)

    def __post_init__(self) -> None:
        self.cond_id_arr = np.asarray(self.cond_ids, dtype=np.int64)


class GroupConsts:
    """Per-slot constant vectors for one kernel group."""

    __slots__ = ("size", "slots")

    def __init__(self, size: int, slots: list[Any]):
        self.size = size
        self.slots = slots

    @classmethod
    def build(cls, kinds: tuple[str, ...], member_values: list[tuple[Any, ...]]) -> "GroupConsts":
        g = len(member_values)
        slots: list[Any] = []
        for i, kind in enumerate(kinds):
            vals = [mv[i] for mv in member_values]
            if kind == "key":  # (hi, lo) int pairs → two i32 vectors
                slots.append((
                    np.asarray([v[0] for v in vals], dtype=np.int32),
                    np.asarray([v[1] for v in vals], dtype=np.int32),
                ))
            elif kind in ("sid", "bool"):
                slots.append(np.asarray(vals, dtype=np.int32))
            elif kind == "pred":  # static python ids: traced-graph gather is static
                slots.append(tuple(int(v) for v in vals))
            elif kind == "none":
                slots.append(None)
            else:  # pragma: no cover - sig construction guarantees known kinds
                raise ValueError(f"unknown slot kind {kind}")
        return cls(g, slots)


def subset_group_consts(gc: "GroupConsts", sel: tuple[int, ...]) -> "GroupConsts":
    """A GroupConsts view holding only the members at positions ``sel``.

    Used by the evaluator's jit variant graphs: a batch that references only
    a few members of a template group traces the group's kernel over just
    those members' constant vectors, so the compiled graph (and the device
    work) is O(active conditions) instead of O(all conditions)."""
    idx = np.asarray(sel, dtype=np.int64)
    slots: list[Any] = []
    for s in gc.slots:
        if s is None:
            slots.append(None)
        elif isinstance(s, tuple) and len(s) == 2 and isinstance(s[0], np.ndarray):
            slots.append((s[0][idx], s[1][idx]))  # key slot: (hi, lo)
        elif isinstance(s, np.ndarray):
            slots.append(s[idx])  # sid / bool slot
        elif isinstance(s, tuple):
            slots.append(tuple(s[i] for i in sel))  # pred-id slot (static)
        else:  # pragma: no cover - GroupConsts.build guarantees known shapes
            raise ValueError(f"unknown slot shape {type(s)}")
    return GroupConsts(len(sel), slots)


class Refs:
    """Accessors handed to kernel emit functions (jnp or np arrays)."""

    def __init__(self, xp, tags, his, los, sids, nans, pred_vals, pred_errs,
                 list_sids=None, list_states=None,
                 ts_his=None, ts_los=None, ts_states=None,
                 now_hi=None, now_lo=None):
        self.xp = xp
        self._tags = tags
        self._his = his
        self._los = los
        self._sids = sids
        self._nans = nans
        self._pred_vals = pred_vals
        self._pred_errs = pred_errs
        self._list_sids = list_sids or {}
        self._list_states = list_states or {}
        self._ts_his = ts_his or {}
        self._ts_los = ts_los or {}
        self._ts_states = ts_states or {}
        self._now_hi = now_hi
        self._now_lo = now_lo

    def tag(self, path):
        return self._tags[path]

    def hi(self, path):
        return self._his[path]

    def lo(self, path):
        return self._los[path]

    def sid(self, path):
        return self._sids[path]

    def nan(self, path):
        return self._nans[path]

    def pred(self, pred_id):
        return self._pred_vals[pred_id], self._pred_errs[pred_id]

    def list_col(self, path):
        """(sids [B, L], state [B]) for a string-list membership column;
        state: 0=missing, 1=ok list, 2=error (non-list / bad element)."""
        return self._list_sids[path], self._list_states[path]

    def ts_col(self, path):
        """(hi [B], lo [B], state [B]) parsed-timestamp key column;
        state: 0=missing attr, 1=ok, 2=unconvertible value."""
        return self._ts_his[path], self._ts_los[path], self._ts_states[path]

    def now_key(self):
        """Batch-constant (hi, lo) key of the request-stable now()."""
        return self._now_hi, self._now_lo

    def batch_size(self) -> int:
        for d in (self._tags, self._pred_vals, self._ts_states, self._list_states):
            for v in d.values():
                return v.shape[0]
        return 1


@dataclass
class BoolExpr:
    """emit(refs, gc) -> (val, err) boolean arrays broadcastable to [B, G]."""

    emit: Callable[[Refs, GroupConsts], tuple[Any, Any]]


def _col(a):
    """[B] column → [B, 1] for broadcasting against [1, G] slot vectors."""
    return a[:, None]


class _Compiler:
    def __init__(self, kernel: CondKernel, params: PolicyParams, globals_: dict[str, Any], pred_alloc):
        self.k = kernel
        self.params = params
        self.globals = globals_
        self.pred_alloc = pred_alloc  # (node, params) -> PredSpec
        self.var_defs = {v.name: v.expr.node for v in params.ordered_variables}
        # template accumulation: sig tokens fully determine the emit graph;
        # slots carry this kernel's literal payloads in allocation order
        self.sig: list[Any] = []
        self.slot_kinds: list[str] = []
        self.slot_values: list[Any] = []

    def tok(self, *t: Any) -> None:
        self.sig.append(t)

    def slot(self, kind: str, value: Any) -> int:
        idx = len(self.slot_kinds)
        self.slot_kinds.append(kind)
        self.slot_values.append(value)
        self.tok("slot", kind)
        return idx

    # -- variable / constant inlining -------------------------------------

    def inline(self, node: A.Node, depth: int = 0) -> A.Node:
        if depth > 32:
            raise Unsupported("variable inlining too deep", code="inline_too_deep", node=node)
        if isinstance(node, A.Select) and isinstance(node.operand, A.Ident):
            root = node.operand.name
            if root in ("V", "variables"):
                if node.field in self.var_defs:
                    return self.inline(self.var_defs[node.field], depth + 1)
                raise Unsupported(f"undefined variable {node.field}", code="undefined_variable", node=node)
            if root in ("C", "constants"):
                if node.field in self.params.constants:
                    return A.Lit(self.params.constants[node.field])
                raise Unsupported(f"undefined constant {node.field}", code="undefined_constant", node=node)
            if root in ("G", "globals"):
                if node.field in self.globals:
                    return A.Lit(self.globals[node.field])
                raise Unsupported(f"undefined global {node.field}", code="undefined_global", node=node)
        # recurse
        if isinstance(node, A.Select):
            return A.Select(self.inline(node.operand, depth), node.field)
        if isinstance(node, A.Present):
            return A.Present(self.inline(node.operand, depth), node.field)
        if isinstance(node, A.Index):
            return A.Index(self.inline(node.operand, depth), self.inline(node.index, depth))
        if isinstance(node, A.Call):
            return A.Call(
                node.fn,
                tuple(self.inline(a, depth) for a in node.args),
                target=self.inline(node.target, depth) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self.inline(x, depth) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(tuple((self.inline(k, depth), self.inline(v, depth)) for k, v in node.entries))
        if isinstance(node, A.Bind):
            return A.Bind(node.name, self.inline(node.init, depth), self.inline(node.body, depth))
        if isinstance(node, A.Comprehension):
            return A.Comprehension(
                kind=node.kind,
                iter_range=self.inline(node.iter_range, depth),
                iter_var=node.iter_var,
                step=self.inline(node.step, depth),
                iter_var2=node.iter_var2,
                step2=self.inline(node.step2, depth) if node.step2 is not None else None,
            )
        return node

    # -- operand classification -------------------------------------------

    def as_operand(self, node: A.Node):
        if isinstance(node, A.Lit):
            return ConstOp(node.value)
        if isinstance(node, A.ListLit):
            vals = []
            for item in node.items:
                if not isinstance(item, A.Lit):
                    raise Unsupported("non-literal list element", code="non_literal_list_element", node=item)
                vals.append(item.value)
            return ConstOp(vals)
        path = self.path_of(node)
        if path is not None:
            self.k.paths.add(path)
            return PathOp(path)
        raise Unsupported("operand is not a literal or attribute path", code="operand_unsupported", node=node)

    def path_of(self, node: A.Node) -> Optional[tuple[str, ...]]:
        """Select/Index chain rooted at request/R/P → canonical path."""
        split = _split_chain(node)
        if split is None:
            return None
        root, segs = split
        if root == "runtime":
            self.k.references_runtime = True
            return None
        if root in _ROOT_ALIASES:
            return _ROOT_ALIASES[root] + segs
        return None

    # -- boolean compilation ----------------------------------------------

    def compile_bool(self, node: A.Node) -> BoolExpr:
        if isinstance(node, A.Call) and node.target is None:
            fn = node.fn
            if fn == "_&&_":
                self.tok("and", len(node.args))
                return self._logic(node.args, is_and=True)
            if fn == "_||_":
                self.tok("or", len(node.args))
                return self._logic(node.args, is_and=False)
            if fn == "!_":
                self.tok("not")
                inner = self.compile_bool(node.args[0])

                def emit_not(refs, gc, inner=inner):
                    v, e = inner.emit(refs, gc)
                    return ~v & ~e, e

                return BoolExpr(emit_not)
            if fn == "_?_:_":
                self.tok("ternary")
                c = self.compile_bool(node.args[0])
                t = self.compile_bool(node.args[1])
                f = self.compile_bool(node.args[2])

                def emit_ternary(refs, gc, c=c, t=t, f=f):
                    cv, ce = c.emit(refs, gc)
                    tv, te = t.emit(refs, gc)
                    fv, fe = f.emit(refs, gc)
                    pick_t = cv & ~ce
                    pick_f = ~cv & ~ce
                    err = ce | (pick_t & te) | (pick_f & fe)
                    val = ((pick_t & tv) | (pick_f & fv)) & ~err
                    return val, err

                return BoolExpr(emit_ternary)
            if fn in ("_==_", "_!=_"):
                return self._equality(node.args[0], node.args[1], negate=(fn == "_!=_"))
            if fn in ("_<_", "_<=_", "_>_", "_>=_"):
                return self._ordering(fn, node.args[0], node.args[1])
            if fn == "_in_":
                return self._in(node.args[0], node.args[1])
            raise Unsupported(f"function {fn}", code="unsupported_function", node=node)
        if isinstance(node, A.Present):
            return self._has(node)
        if isinstance(node, A.Lit):
            if isinstance(node.value, bool):
                s = self.slot("bool", 1 if node.value else 0)
                self.tok("litbool")

                def emit_lit(refs, gc, s=s):
                    xp = refs.xp
                    B = refs.batch_size()
                    val = xp.broadcast_to(gc.slots[s][None, :] == 1, (B, gc.size))
                    return val, xp.zeros((B, gc.size), dtype=bool)

                return BoolExpr(emit_lit)
            raise Unsupported("non-bool literal in boolean position", code="non_bool_literal", node=node)
        # bare attribute path in boolean position: true iff value is bool true
        path = self.path_of(node)
        if path is not None:
            self.k.paths.add(path)
            self.tok("boolpath", path)

            def emit_path(refs, gc, path=path):
                tag = _col(refs.tag(path))
                val = (tag == TAG_BOOL) & (_col(refs.hi(path)) == 1)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                return val & ~err, err

            return BoolExpr(emit_path)
        raise Unsupported("unsupported boolean expression", code="unsupported_bool_expr", node=node)

    def _logic(self, args, is_and: bool) -> BoolExpr:
        parts = [self.compile_bool(a) for a in args]

        def emit(refs, gc):
            vals_errs = [p.emit(refs, gc) for p in parts]
            if is_and:
                # false if any (false & !err); err if no false and any err
                any_false = None
                any_err = None
                all_true = None
                for v, e in vals_errs:
                    f = ~v & ~e
                    any_false = f if any_false is None else (any_false | f)
                    any_err = e if any_err is None else (any_err | e)
                    t = v & ~e
                    all_true = t if all_true is None else (all_true & t)
                err = ~any_false & any_err
                val = all_true & ~err
                return val, err
            any_true = None
            any_err = None
            for v, e in vals_errs:
                t = v & ~e
                any_true = t if any_true is None else (any_true | t)
                any_err = e if any_err is None else (any_err | e)
            err = ~any_true & any_err
            val = any_true
            return val, err

        return BoolExpr(emit)

    def _has(self, node: A.Present) -> BoolExpr:
        path = self.path_of(A.Select(node.operand, node.field))
        if path is None:
            raise Unsupported("has() on non-path", code="has_on_non_path", node=node)
        self.k.paths.add(path)
        self.tok("has", path)

        def emit(refs, gc, path=path):
            tag = _col(refs.tag(path))
            err = tag == TAG_ERR
            val = ~err & (tag != TAG_MISSING)
            return val, err

        return BoolExpr(emit)

    # -- timestamp operands -------------------------------------------------

    def _ts_side(self, node: A.Node):
        """PROBE a timestamp-typed operand: timestamp(path),
        timestamp(literal), or now(). Returns a descriptor tuple or None.
        Mutation-free — both sides are probed before either commits, so a
        mixed comparison (one ts side, one untyped) leaves no orphaned ts
        column or slot behind when it falls back to a predicate."""
        if not (isinstance(node, A.Call) and node.target is None):
            return None
        if node.fn == "now" and not node.args:
            return ("now",)
        if node.fn == "timestamp" and len(node.args) == 1:
            arg = self.inline(node.args[0])
            if isinstance(arg, A.Lit):
                from .columns import timestamp_key

                try:
                    hi, lo = timestamp_key(arg.value)
                except Exception:  # noqa: BLE001 — invalid constant: host evaluates (errors)
                    raise Unsupported("unconvertible timestamp constant", code="bad_timestamp_constant", node=node) from None
                return ("rawconst", (hi, lo))
            path = self.path_of(arg)
            if path is not None:
                return ("rawpath", path)
        return None

    def _ts_commit(self, side):
        """Materialize a probed side: allocate slots / register columns.
        Called lhs-first so slot order matches the sig token order."""
        if side[0] == "rawconst":
            return ("const", self.slot("key", side[1]))
        if side[0] == "rawpath":
            self.k.ts_paths.add(side[1])
            return ("path", side[1])
        self.k.uses_now = True
        return side

    def _ts_key_of(self, refs: Refs, gc: GroupConsts, side):
        """side descriptor → (hi, lo, err) broadcastable arrays."""
        xp = refs.xp
        if side[0] == "path":
            hi, lo, state = refs.ts_col(side[1])
            return _col(hi), _col(lo), _col(state != 1)
        if side[0] == "now":
            hi, lo = refs.now_key()
            zero = xp.zeros((1, 1), dtype=bool)
            return hi, lo, zero
        shi, slo = gc.slots[side[1]]
        zero = xp.zeros((1, 1), dtype=bool)
        return shi[None, :], slo[None, :], zero

    def _ts_compare(self, fn: str, ls, rs) -> BoolExpr:
        self.tok("ts", fn, ls[0], ls[1] if ls[0] == "path" else None,
                 rs[0], rs[1] if rs[0] == "path" else None)

        def emit(refs, gc, ls=ls, rs=rs, fn=fn):
            ahi, alo, aerr = self._ts_key_of(refs, gc, ls)
            bhi, blo, berr = self._ts_key_of(refs, gc, rs)
            err = aerr | berr
            lt = (ahi < bhi) | ((ahi == bhi) & (alo < blo))
            eq = (ahi == bhi) & (alo == blo)
            if fn == "_<_":
                val = lt
            elif fn == "_<=_":
                val = lt | eq
            elif fn == "_>_":
                val = ~lt & ~eq
            elif fn == "_>=_":
                val = ~lt
            elif fn == "_==_":
                val = eq
            else:  # _!=_
                val = ~eq
            xp = refs.xp
            shape = (refs.batch_size(), gc.size)
            val = xp.broadcast_to(val, shape)
            err = xp.broadcast_to(err, shape)
            return val & ~err, err

        return BoolExpr(emit)

    # value-compare helpers; `a` is PathOp, b is ConstOp/PathOp

    def _equality(self, lhs_n: A.Node, rhs_n: A.Node, negate: bool) -> BoolExpr:
        ls, rs = self._ts_side(lhs_n), self._ts_side(rhs_n)
        if ls is not None or rs is not None:
            if ls is None or rs is None:
                raise Unsupported("mixed timestamp equality", code="mixed_timestamp_equality", node=lhs_n if ls is None else rhs_n)
            ls, rs = self._ts_commit(ls), self._ts_commit(rs)
            return self._ts_compare("_!=_" if negate else "_==_", ls, rs)
        lhs, rhs = self.as_operand(lhs_n), self.as_operand(rhs_n)
        if isinstance(lhs, ConstOp) and isinstance(rhs, PathOp):
            lhs, rhs = rhs, lhs
        if isinstance(lhs, ConstOp):
            raise Unsupported("constant == constant", code="const_const_equality", node=lhs_n)  # let constant folding live on host
        assert isinstance(lhs, PathOp)
        # lists/dicts at an eq path can't be compared on device
        self._add_fallback(lhs.path, {TAG_OTHER}, "eq_collection_operand")
        if isinstance(rhs, PathOp):
            self._add_fallback(rhs.path, {TAG_OTHER}, "eq_collection_operand")
            self.tok("eqpp", lhs.path, rhs.path, negate)

            def emit_pp(refs, gc, a=lhs.path, b=rhs.path, negate=negate):
                ta, tb = _col(refs.tag(a)), _col(refs.tag(b))
                err = (ta == TAG_MISSING) | (ta == TAG_ERR) | (tb == TAG_MISSING) | (tb == TAG_ERR)
                same_num = (
                    (ta == TAG_NUM) & (tb == TAG_NUM)
                    & _col(~refs.nan(a)) & _col(~refs.nan(b))
                    & (_col(refs.hi(a)) == _col(refs.hi(b)))
                    & (_col(refs.lo(a)) == _col(refs.lo(b)))
                )
                same_str = (ta == TAG_STR) & (tb == TAG_STR) & (_col(refs.sid(a)) == _col(refs.sid(b)))
                same_bool = (ta == TAG_BOOL) & (tb == TAG_BOOL) & (_col(refs.hi(a)) == _col(refs.hi(b)))
                same_null = (ta == TAG_NULL) & (tb == TAG_NULL)
                val = same_num | same_str | same_bool | same_null
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pp)

        cval = rhs.value
        if isinstance(cval, list):
            raise Unsupported("list equality", code="list_equality", node=rhs_n)
        if isinstance(cval, bool):
            s = self.slot("bool", 1 if cval else 0)
            self.tok("eqpb", lhs.path, negate)

            def emit_pb(refs, gc, p=lhs.path, s=s, negate=negate):
                tag = _col(refs.tag(p))
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = (tag == TAG_BOOL) & (_col(refs.hi(p)) == gc.slots[s][None, :])
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pb)
        if cval is None:
            self.tok("eqpn", lhs.path, negate)

            def emit_pn(refs, gc, p=lhs.path, negate=negate):
                tag = _col(refs.tag(p))
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = tag == TAG_NULL
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pn)
        if isinstance(cval, (int, float)):
            f = float(cval)
            if f != f:
                self.tok("eqpnan", lhs.path, negate)

                def emit_pnan(refs, gc, p=lhs.path, negate=negate):
                    tag = _col(refs.tag(p))
                    err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                    xp = refs.xp
                    val = xp.zeros_like(err)
                    if negate:
                        val = ~val
                    return val & ~err, err

                return BoolExpr(emit_pnan)
            s = self.slot("key", split_key(double_key(f)))
            self.tok("eqpf", lhs.path, negate)

            def emit_pf(refs, gc, p=lhs.path, s=s, negate=negate):
                tag = _col(refs.tag(p))
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                chi, clo = gc.slots[s]
                val = (
                    (tag == TAG_NUM) & _col(~refs.nan(p))
                    & (_col(refs.hi(p)) == chi[None, :])
                    & (_col(refs.lo(p)) == clo[None, :])
                )
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pf)
        if isinstance(cval, str):
            s = self.slot("sid", self.interner.intern(cval))
            self.tok("eqps", lhs.path, negate)

            def emit_ps(refs, gc, p=lhs.path, s=s, negate=negate):
                tag = _col(refs.tag(p))
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = (tag == TAG_STR) & (_col(refs.sid(p)) == gc.slots[s][None, :])
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_ps)
        raise Unsupported(f"equality against {type(cval).__name__} constant", code="unsupported_equality_constant", node=rhs_n)

    def _ordering(self, fn: str, lhs_n: A.Node, rhs_n: A.Node) -> BoolExpr:
        ls, rs = self._ts_side(lhs_n), self._ts_side(rhs_n)
        if ls is not None or rs is not None:
            if ls is None or rs is None:
                # mixed timestamp vs untyped operand: host evaluates
                raise Unsupported("mixed timestamp ordering", code="mixed_timestamp_ordering", node=lhs_n if ls is None else rhs_n)
            ls, rs = self._ts_commit(ls), self._ts_commit(rs)
            return self._ts_compare(fn, ls, rs)
        lhs, rhs = self.as_operand(lhs_n), self.as_operand(rhs_n)
        flip = {"_<_": "_>_", "_<=_": "_>=_", "_>_": "_<_", "_>=_": "_<=_"}
        if isinstance(lhs, ConstOp) and isinstance(rhs, PathOp):
            lhs, rhs = rhs, lhs
            fn = flip[fn]
        if isinstance(lhs, ConstOp):
            raise Unsupported("constant ordering", code="const_const_ordering", node=lhs_n)
        assert isinstance(lhs, PathOp)

        def cmp(ahi, alo, bhi, blo, fn):
            lt = (ahi < bhi) | ((ahi == bhi) & (alo < blo))
            eq = (ahi == bhi) & (alo == blo)
            if fn == "_<_":
                return lt
            if fn == "_<=_":
                return lt | eq
            if fn == "_>_":
                return ~lt & ~eq
            return ~lt

        if isinstance(rhs, PathOp):
            # path-vs-path ordering between two STRINGS (or two timestamps
            # under TAG_OTHER) is satisfiable in CEL but not computable on
            # device → route those inputs to the oracle. Every other
            # non-numeric pairing is a CEL type error, which the device err
            # bit reproduces.
            self._add_fallback(lhs.path, {TAG_STR, TAG_OTHER}, "ord_string_pair")
            self._add_fallback(rhs.path, {TAG_STR, TAG_OTHER}, "ord_string_pair")
            self.tok("ordpp", lhs.path, rhs.path, fn)

            def emit_pp(refs, gc, a=lhs.path, b=rhs.path, fn=fn):
                numeric = (
                    (_col(refs.tag(a)) == TAG_NUM) & (_col(refs.tag(b)) == TAG_NUM)
                    & _col(~refs.nan(a)) & _col(~refs.nan(b))
                )
                err = ~numeric
                val = numeric & cmp(
                    _col(refs.hi(a)), _col(refs.lo(a)), _col(refs.hi(b)), _col(refs.lo(b)), fn
                )
                return val, err

            return BoolExpr(emit_pp)
        cval = rhs.value
        if isinstance(cval, str):
            # string ordering against a constant: a predicate column (host
            # CEL, value-cached) — NOT an oracle fallback; strings at the
            # path stay device-served
            raise Unsupported("string ordering constant", code="string_ordering_constant", node=rhs_n)
        if isinstance(cval, bool) or not isinstance(cval, (int, float)):
            raise Unsupported("non-numeric ordering constant", code="non_numeric_ordering_constant", node=rhs_n)
        f = float(cval)
        if f != f:
            raise Unsupported("NaN ordering constant", code="nan_ordering_constant", node=rhs_n)
        s = self.slot("key", split_key(double_key(f)))
        self.tok("ordpc", lhs.path, fn)

        # vs a numeric constant no fallback tags are needed: any non-numeric
        # value at the path (string, list, timestamp) is a CEL type error,
        # exactly what the device err bit produces
        def emit_pc(refs, gc, p=lhs.path, s=s, fn=fn):
            tag = _col(refs.tag(p))
            numeric = (tag == TAG_NUM) & _col(~refs.nan(p))
            err = ~numeric
            chi, clo = gc.slots[s]
            val = numeric & cmp(_col(refs.hi(p)), _col(refs.lo(p)), chi[None, :], clo[None, :], fn)
            return val, err

        return BoolExpr(emit_pc)

    def _in(self, lhs_n: A.Node, rhs_n: A.Node) -> BoolExpr:
        lhs = self.as_operand(lhs_n)
        rhs = self.as_operand(rhs_n)
        if isinstance(lhs, PathOp) and isinstance(rhs, ConstOp) and isinstance(rhs.value, list):
            # OR of equalities against each element
            self.tok("inlist", lhs.path, len(rhs.value))
            parts = []
            for el in rhs.value:
                parts.append(self._equality(lhs_n, A.Lit(el), negate=False))

            def emit(refs, gc, parts=parts, p=lhs.path):
                tag = _col(refs.tag(p))
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = None
                for part in parts:
                    v, _ = part.emit(refs, gc)
                    val = v if val is None else (val | v)
                if val is None:
                    xp = refs.xp
                    val = xp.zeros_like(err)
                return val & ~err, err

            return BoolExpr(emit)
        if isinstance(rhs, PathOp) and isinstance(lhs, ConstOp) and isinstance(lhs.value, str):
            # `"x" in R.attr.list`: membership over a string-list column
            # (sid comparison per padded slot; non-list values error, which
            # collapses to false at the condition boundary like the oracle)
            self.k.list_paths.add(rhs.path)
            s = self.slot("sid", self.interner.intern(lhs.value))
            self.tok("instr", rhs.path)

            def emit_in_list(refs, gc, p=rhs.path, s=s):
                sids, state = refs.list_col(p)
                # anything but a well-formed list (missing attr, wrong type)
                # is a CEL error, which matters under ! / && / || absorption
                err = _col(state != 1)
                needle = gc.slots[s][None, :]  # [1, G]
                # accumulate over the (static, small) list axis instead of
                # materializing a [B, L, G] intermediate — at fleet scale
                # (G in the thousands) that tensor is gigabytes on numpy
                L = sids.shape[1]
                val = None
                for j in range(L):
                    m = sids[:, j : j + 1] == needle  # [B, G]
                    val = m if val is None else (val | m)
                if val is None:
                    val = refs.xp.zeros_like(err)
                return val & ~err, err

            return BoolExpr(emit_in_list)
        raise Unsupported("in over attribute lists", code="unsupported_membership", node=rhs_n)

    def _add_fallback(self, path: tuple[str, ...], tags: set[int], reason: str) -> None:
        cur = self.k.fallback_tags.get(path, frozenset())
        self.k.fallback_tags[path] = cur | frozenset(tags)
        cur_r = self.k.fallback_reasons.get(path, frozenset())
        self.k.fallback_reasons[path] = cur_r | frozenset((reason,))

    interner: StringInterner  # set by compile_condition


# typed mini-IR for operands


@dataclass(frozen=True)
class ConstOp:
    value: Any


@dataclass(frozen=True)
class PathOp:
    path: tuple[str, ...]


def _split_chain(node: A.Node) -> Optional[tuple[str, tuple[str, ...]]]:
    """Maximal select/literal-index chain → (root ident, segments)."""
    segs: list[str] = []
    cur = node
    while True:
        if isinstance(cur, A.Select):
            segs.append(cur.field)
            cur = cur.operand
        elif isinstance(cur, A.Index) and isinstance(cur.index, A.Lit) and isinstance(cur.index.value, str):
            segs.append(cur.index.value)
            cur = cur.operand
        elif isinstance(cur, A.Ident):
            return cur.name, tuple(reversed(segs))
        else:
            return None


def _chain_of(node: A.Node) -> Optional[tuple[str, ...]]:
    """Maximal chain rooted at request/R/P → canonical path."""
    split = _split_chain(node)
    if split is None or split[0] not in _ROOT_ALIASES:
        return None
    return _ROOT_ALIASES[split[0]] + split[1]


def _pred_refs(node: A.Node) -> tuple[set[tuple[str, ...]], bool, bool]:
    """(referenced request paths, references_runtime, time_dependent).

    Paths are MAXIMAL chains (e.g. ("aux_data", "jwt", "aud"), not
    ("aux_data",)) so the packer's predicate cache keys freeze only the leaf
    values actually read, not whole subtrees."""
    paths: set[tuple[str, ...]] = set()
    refs_runtime = False
    time_dep = False

    def visit(n: A.Node) -> None:
        if isinstance(n, A.Ident):
            if n.name == "runtime":
                nonlocal refs_runtime
                refs_runtime = True
            return
        if isinstance(n, A.Call):
            nonlocal time_dep
            if n.fn in ("now", "timeSince"):
                time_dep = True
            if n.target is not None:
                visit(n.target)
            for a in n.args:
                visit(a)
            return
        if isinstance(n, (A.Select, A.Present, A.Index)):
            chain = _chain_of(n if not isinstance(n, A.Present) else A.Select(n.operand, n.field))
            if chain is not None:
                paths.add(chain)
                # still visit a computed index expression
                if isinstance(n, A.Index):
                    visit(n.index)
                return
            if isinstance(n, (A.Select, A.Present)):
                visit(n.operand)
            else:
                visit(n.operand)
                visit(n.index)
            return
        if isinstance(n, A.ListLit):
            for x in n.items:
                visit(x)
            return
        if isinstance(n, A.MapLit):
            for k, v in n.entries:
                visit(k)
                visit(v)
            return
        if isinstance(n, A.Bind):
            visit(n.init)
            visit(n.body)
            return
        if isinstance(n, A.Comprehension):
            visit(n.iter_range)
            visit(n.step)
            if n.step2 is not None:
                visit(n.step2)
            return

    visit(node)
    return paths, refs_runtime, time_dep


# Reason codes for fallback-tag registrations: unlike :data:`REASONS` these
# fragments DO compile to device kernels, but specific runtime value shapes
# at the tagged path (lists/dicts under ==, strings under path-vs-path <)
# route the affected inputs to the CPU oracle. The analyzer reports them as
# the `tagged-fallback` eligibility class.
FALLBACK_REASONS: dict[str, str] = {
    "eq_collection_operand": "equality over a path that may hold a list/dict at runtime",
    "ord_string_pair": "path-vs-path ordering that is string-comparable at runtime",
}


def _unsupported_counter():
    from ..observability import metrics

    return metrics().counter_vec(
        "cerbos_tpu_cond_compile_unsupported_total",
        "Condition fragments rejected by the device compiler, by stable reason code",
    )


def _count_unsupported(code: str) -> None:
    """Runtime condition-compile rejection accounting, by stable reason
    code — the live counterpart of the static analyzer's predictions
    (docs/ANALYSIS.md). Incremented wherever lowering runs: process boot,
    bundle swap, and admin-API policy reloads."""
    _unsupported_counter().inc(code)


class ConditionSetCompiler:
    """Compiles the distinct (condition, params) pairs of a rule table."""

    def __init__(self, globals_: dict[str, Any], interner: StringInterner):
        self.globals = globals_
        self.interner = interner
        # register the rejection counter eagerly so the family scrapes as 0
        # (and passes the registry lint) even on a fully device-clean table
        _unsupported_counter()
        self.kernels: list[CondKernel] = []
        self._by_key: dict[tuple[int, int], int] = {}
        self.preds: list[PredSpec] = []
        self._template_emits: dict[int, Callable] = {}  # cond_id -> slot-mode emit
        self.groups: list[KernelGroup] = []
        self.perm: Optional[np.ndarray] = None
        self._groups_dirty = True

    def cond_id(self, cond: Optional[CompiledCondition], params: Optional[PolicyParams]) -> int:
        """Intern a (condition, params) pair; -1 for condition-less.

        Interning is *structural* (condition text + params content), the
        analogue of the reference's FunctionalCore dedup by behavioral hash
        (index.go:26-32,119-148): policy corpora replicate identical
        conditions across many policies, and one kernel serves them all.
        """
        if cond is None:
            return -1
        id_key = (id(cond), id(params))
        hit = self._by_key.get(id_key)
        if hit is not None:
            return hit
        struct_key = (_cond_struct_key(cond), _params_struct_key(params))
        hit = self._by_key.get(struct_key)
        if hit is not None:
            self._by_key[id_key] = hit
            return hit
        cid = len(self.kernels)
        kernel = self._compile(cond, params or PolicyParams(), cid)
        self.kernels.append(kernel)
        self._by_key[id_key] = cid
        self._by_key[struct_key] = cid
        self._groups_dirty = True
        return cid

    def _alloc_pred(self, node: A.Node, params: PolicyParams) -> PredSpec:
        paths, refs_runtime, time_dep = _pred_refs(node)
        spec = PredSpec(
            pred_id=len(self.preds),
            node=node,
            params=params,
            ref_paths=tuple(sorted(paths)),
            time_dependent=time_dep,
        )
        self.preds.append(spec)
        return spec

    def _compile(self, cond: CompiledCondition, params: PolicyParams, cid: int) -> CondKernel:
        kernel = CondKernel(cond_id=cid)
        comp = _Compiler(kernel, params, self.globals, self._alloc_pred)
        comp.interner = self.interner

        def compile_tree(c: CompiledCondition) -> Callable[[Refs, GroupConsts], Any]:
            """Condition-tree node → emit(refs, gc) -> sat [B, G].

            all/any/none combine *satisfied* child results (each child's
            errors collapse to false at its own boundary — check.go:650-702),
            which is not the same as CEL && / ||.
            """
            if c.kind == "expr":
                node = comp.inline(c.expr.node)
                try:
                    be = comp.compile_bool(node)

                    def emit_expr(refs, gc, be=be):
                        v, e = be.emit(refs, gc)
                        return v & ~e

                    return emit_expr
                except Unsupported as u:
                    if kernel.references_runtime:
                        raise
                    _count_unsupported(u.code)
                    kernel.pred_reasons.append((u.code, str(u), u.node))
                    spec = self._alloc_pred(node, params)
                    kernel.preds.append(spec)
                    s = comp.slot("pred", spec.pred_id)
                    comp.tok("predexpr")

                    def emit_pred(refs, gc, s=s):
                        xp = refs.xp
                        vs = [refs.pred(pid) for pid in gc.slots[s]]
                        v = xp.stack([x[0] for x in vs], axis=1)
                        e = xp.stack([x[1] for x in vs], axis=1)
                        return v & ~e

                    return emit_pred
            comp.tok("tree", c.kind, len(c.children))
            subs = [compile_tree(ch) for ch in c.children]
            if c.kind == "all":
                def emit_all(refs, gc, subs=subs):
                    out = None
                    for sfn in subs:
                        v = sfn(refs, gc)
                        out = v if out is None else (out & v)
                    return out
                return emit_all
            if c.kind == "any":
                def emit_any(refs, gc, subs=subs):
                    out = None
                    for sfn in subs:
                        v = sfn(refs, gc)
                        out = v if out is None else (out | v)
                    return out
                return emit_any
            if c.kind == "none":
                def emit_none(refs, gc, subs=subs):
                    out = None
                    for sfn in subs:
                        v = sfn(refs, gc)
                        out = v if out is None else (out | v)
                    return ~out
                return emit_none
            raise ValueError(f"unknown condition kind {c.kind}")

        try:
            template = compile_tree(cond)
        except Unsupported as u:
            # runtime-referencing conditions can't be batched at all
            _count_unsupported(u.code)
            kernel.oracle_reason = (u.code, str(u), u.node)
            kernel.emit = None
            # no device path at all ⇒ no device ternary either
            kernel.plan_reason = kernel.oracle_reason
            return kernel

        kernel.template_sig = tuple(comp.sig)
        kernel.slot_kinds = tuple(comp.slot_kinds)
        kernel.slot_values = tuple(comp.slot_values)
        self._template_emits[cid] = template
        # contract: non-None emit marks the kernel device-evaluable (callers
        # only None-check it); evaluation happens through the group path,
        # emit(refs, gc) being the shared template
        kernel.emit = template
        kernel.plan_reason = plan_verdict(kernel)
        return kernel

    def build_groups(self) -> None:
        """Group kernels by template signature; one traced subgraph per
        group evaluates all members against slot constant vectors."""
        if not self._groups_dirty:
            return
        by_sig: dict[tuple, list[int]] = {}
        for k in self.kernels:
            if k.emit is None or k.template_sig is None:
                continue
            by_sig.setdefault(k.template_sig, []).append(k.cond_id)
        self.groups = []
        order: list[int] = []
        for sig, cids in by_sig.items():
            gc = GroupConsts.build(
                self.kernels[cids[0]].slot_kinds,
                [self.kernels[c].slot_values for c in cids],
            )
            self.groups.append(KernelGroup(emit=self._template_emits[cids[0]], gc=gc, cond_ids=cids))
            order.extend(cids)
        # column permutation: concatenated group output order -> cond_id order
        C = len(self.kernels)
        self.perm = np.zeros(C, dtype=np.int64)
        self.dead = np.ones(C, dtype=bool)  # kernels with no device emit
        for pos, cid in enumerate(order):
            self.perm[cid] = pos
            self.dead[cid] = False
        self._groups_dirty = False


def _cond_struct_key(c: CompiledCondition):
    if c.kind == "expr":
        return ("e", c.expr.original)
    return (c.kind[0], tuple(_cond_struct_key(ch) for ch in c.children))


def _freeze_val(v):
    if isinstance(v, list):
        return tuple(_freeze_val(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze_val(x)) for k, x in v.items()))
    return v


def _params_struct_key(params: Optional[PolicyParams]):
    if params is None:
        return None
    return (
        tuple(sorted((k, _freeze_val(v)) for k, v in params.constants.items())),
        tuple((v.name, v.expr.original) for v in params.ordered_variables),
    )


def plan_path_always_unknown(path: tuple[str, ...]) -> bool:
    """True for resource fields PlanResources can never supply.

    Mirrors the sequential planner's knowledge model (plan/partial.py):
    ``resource.kind`` and ``resource.scope`` come from the query itself and a
    specific ``resource.attr.X`` leaf may be listed in known_attrs, but
    ``resource.id`` (always empty in plan mode), ``policyVersion``, the bare
    attr map and whole-resource references are unknowable by construction.
    """
    if not path or path[0] != "resource":
        return False
    if len(path) == 1:
        return True
    if path[1] in ("kind", "scope"):
        return False
    if path[1] == "attr":
        return len(path) < 3  # bare attr-map reference
    return True


def plan_verdict(kernel: CondKernel) -> Optional[tuple[str, str, Optional[A.Node]]]:
    """Static plan-mode eligibility for one device-evaluable kernel.

    Returns None when the kernel is residualizable — its device TRUE/FALSE
    is trustworthy for any plan query whose known attrs cover the kernel's
    resource deps — or a (code, msg, node) triple naming why BatchPlanner
    must always take the symbolic fallback. Decided here, at compile time,
    so the runtime router never guesses; the raise sites below keep the
    codes in the REASONS registry honest (the source-scan test walks them).
    """
    try:
        if kernel.uses_now:
            raise Unsupported(
                "condition compares against now(); a plan filter has no "
                "single evaluation instant",
                code="plan_time_dependent",
                node=None,
            )
        for spec in kernel.preds:
            if spec.time_dependent:
                raise Unsupported(
                    "host predicate column is time-dependent",
                    code="plan_time_dependent",
                    node=spec.node,
                )
        for p in sorted(kernel.resource_dep_paths()):
            if plan_path_always_unknown(p):
                raise Unsupported(
                    "condition references resource field "
                    f"{'.'.join(p)} that PlanResources never knows",
                    code="plan_unknown_resource_field",
                    node=None,
                )
    except Unsupported as u:
        return (u.code, str(u), u.node)
    return None


def evaluate_pred_host(spec: PredSpec, input_obj, eval_ctx_factory) -> tuple[bool, bool]:
    """Evaluate a predicate column entry on the host → (value, error)."""
    from ..cel.interp import evaluate

    act = eval_ctx_factory(spec.params)
    try:
        v = evaluate(spec.node, act)
    except CelError:
        return False, True
    return v is True, False
