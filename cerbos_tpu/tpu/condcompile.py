"""Condition compiler: CEL AST → vectorized JAX kernel.

Each distinct (condition, params) pair becomes one kernel computing
``(value, error)`` per batch element over SoA attribute columns, reproducing
cel-go semantics: missing keys are errors, ``&&``/``||`` absorb errors
commutatively, mismatched-type equality is false, mismatched ordering is an
error. Variables/constants/globals are inlined at compile time (sound:
conditions are pure and variables are topologically ordered).

Fragments outside the native device op set — regex, timestamps, arithmetic,
list membership in attribute lists, function calls — compile to *predicate
columns*: host-evaluated (value, error) bits per input, cached per unique
referenced-attribute tuple. Paths whose runtime values the device cannot
compare (lists/dicts under ``==``, strings under ``<``) register fallback
trigger tags; the packer routes affected inputs to the CPU oracle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from ..cel import ast as A
from ..cel.errors import CelError
from ..compile import CompiledCondition, PolicyParams
from .columns import (
    TAG_BOOL,
    TAG_MISSING,
    TAG_NULL,
    TAG_NUM,
    TAG_OTHER,
    TAG_STR,
    StringInterner,
    double_key,
    split_key,
)

TAG_ERR = 6

_ROOT_ALIASES = {
    "R": ("resource",),
    "P": ("principal",),
    "request": (),
}


class Unsupported(Exception):
    """Raised during compilation when a fragment needs a predicate column."""


@dataclass
class PredSpec:
    """A host-evaluated boolean subexpression."""

    pred_id: int
    node: A.Node
    params: PolicyParams
    ref_paths: tuple[tuple[str, ...], ...]
    time_dependent: bool


@dataclass
class CondKernel:
    cond_id: int
    paths: set[tuple[str, ...]] = field(default_factory=set)
    preds: list[PredSpec] = field(default_factory=list)
    # emit(refs) -> bool ndarray [B]; refs provides col/pred accessors
    emit: Optional[Callable[["Refs"], Any]] = None
    # tags that force CPU fallback when seen at a path in a batch
    fallback_tags: dict[tuple[str, ...], frozenset[int]] = field(default_factory=dict)
    # paths needing string-list membership columns
    list_paths: set[tuple[str, ...]] = field(default_factory=set)
    references_runtime: bool = False


class Refs:
    """Accessors handed to kernel emit functions (jnp or np arrays)."""

    def __init__(self, xp, tags, his, los, sids, nans, pred_vals, pred_errs,
                 list_sids=None, list_states=None):
        self.xp = xp
        self._tags = tags
        self._his = his
        self._los = los
        self._sids = sids
        self._nans = nans
        self._pred_vals = pred_vals
        self._pred_errs = pred_errs
        self._list_sids = list_sids or {}
        self._list_states = list_states or {}

    def tag(self, path):
        return self._tags[path]

    def hi(self, path):
        return self._his[path]

    def lo(self, path):
        return self._los[path]

    def sid(self, path):
        return self._sids[path]

    def nan(self, path):
        return self._nans[path]

    def pred(self, pred_id):
        return self._pred_vals[pred_id], self._pred_errs[pred_id]

    def list_col(self, path):
        """(sids [B, L], state [B]) for a string-list membership column;
        state: 0=missing, 1=ok list, 2=error (non-list / bad element)."""
        return self._list_sids[path], self._list_states[path]


# ---------------------------------------------------------------------------
# typed mini-IR for operands


@dataclass(frozen=True)
class ConstOp:
    value: Any


@dataclass(frozen=True)
class PathOp:
    path: tuple[str, ...]


@dataclass
class BoolExpr:
    """emit(refs) -> (val, err) boolean arrays."""

    emit: Callable[[Refs], tuple[Any, Any]]


class _Compiler:
    def __init__(self, kernel: CondKernel, params: PolicyParams, globals_: dict[str, Any], pred_alloc):
        self.k = kernel
        self.params = params
        self.globals = globals_
        self.pred_alloc = pred_alloc  # (node, params) -> PredSpec
        self.var_defs = {v.name: v.expr.node for v in params.ordered_variables}

    # -- variable / constant inlining -------------------------------------

    def inline(self, node: A.Node, depth: int = 0) -> A.Node:
        if depth > 32:
            raise Unsupported("variable inlining too deep")
        if isinstance(node, A.Select) and isinstance(node.operand, A.Ident):
            root = node.operand.name
            if root in ("V", "variables"):
                if node.field in self.var_defs:
                    return self.inline(self.var_defs[node.field], depth + 1)
                raise Unsupported(f"undefined variable {node.field}")
            if root in ("C", "constants"):
                if node.field in self.params.constants:
                    return A.Lit(self.params.constants[node.field])
                raise Unsupported(f"undefined constant {node.field}")
            if root in ("G", "globals"):
                if node.field in self.globals:
                    return A.Lit(self.globals[node.field])
                raise Unsupported(f"undefined global {node.field}")
        # recurse
        if isinstance(node, A.Select):
            return A.Select(self.inline(node.operand, depth), node.field)
        if isinstance(node, A.Present):
            return A.Present(self.inline(node.operand, depth), node.field)
        if isinstance(node, A.Index):
            return A.Index(self.inline(node.operand, depth), self.inline(node.index, depth))
        if isinstance(node, A.Call):
            return A.Call(
                node.fn,
                tuple(self.inline(a, depth) for a in node.args),
                target=self.inline(node.target, depth) if node.target is not None else None,
            )
        if isinstance(node, A.ListLit):
            return A.ListLit(tuple(self.inline(x, depth) for x in node.items))
        if isinstance(node, A.MapLit):
            return A.MapLit(tuple((self.inline(k, depth), self.inline(v, depth)) for k, v in node.entries))
        if isinstance(node, A.Bind):
            return A.Bind(node.name, self.inline(node.init, depth), self.inline(node.body, depth))
        if isinstance(node, A.Comprehension):
            return A.Comprehension(
                kind=node.kind,
                iter_range=self.inline(node.iter_range, depth),
                iter_var=node.iter_var,
                step=self.inline(node.step, depth),
                iter_var2=node.iter_var2,
                step2=self.inline(node.step2, depth) if node.step2 is not None else None,
            )
        return node

    # -- operand classification -------------------------------------------

    def as_operand(self, node: A.Node):
        if isinstance(node, A.Lit):
            return ConstOp(node.value)
        if isinstance(node, A.ListLit):
            vals = []
            for item in node.items:
                if not isinstance(item, A.Lit):
                    raise Unsupported("non-literal list element")
                vals.append(item.value)
            return ConstOp(vals)
        path = self.path_of(node)
        if path is not None:
            self.k.paths.add(path)
            return PathOp(path)
        raise Unsupported("operand is not a literal or attribute path")

    def path_of(self, node: A.Node) -> Optional[tuple[str, ...]]:
        """Select/Index chain rooted at request/R/P → canonical path."""
        split = _split_chain(node)
        if split is None:
            return None
        root, segs = split
        if root == "runtime":
            self.k.references_runtime = True
            return None
        if root in _ROOT_ALIASES:
            return _ROOT_ALIASES[root] + segs
        return None

    # -- boolean compilation ----------------------------------------------

    def compile_bool(self, node: A.Node) -> BoolExpr:
        if isinstance(node, A.Call) and node.target is None:
            fn = node.fn
            if fn == "_&&_":
                return self._logic(node.args, is_and=True)
            if fn == "_||_":
                return self._logic(node.args, is_and=False)
            if fn == "!_":
                inner = self.compile_bool(node.args[0])

                def emit_not(refs, inner=inner):
                    v, e = inner.emit(refs)
                    return ~v & ~e, e

                return BoolExpr(emit_not)
            if fn == "_?_:_":
                c = self.compile_bool(node.args[0])
                t = self.compile_bool(node.args[1])
                f = self.compile_bool(node.args[2])

                def emit_ternary(refs, c=c, t=t, f=f):
                    cv, ce = c.emit(refs)
                    tv, te = t.emit(refs)
                    fv, fe = f.emit(refs)
                    pick_t = cv & ~ce
                    pick_f = ~cv & ~ce
                    err = ce | (pick_t & te) | (pick_f & fe)
                    val = ((pick_t & tv) | (pick_f & fv)) & ~err
                    return val, err

                return BoolExpr(emit_ternary)
            if fn in ("_==_", "_!=_"):
                return self._equality(node.args[0], node.args[1], negate=(fn == "_!=_"))
            if fn in ("_<_", "_<=_", "_>_", "_>=_"):
                return self._ordering(fn, node.args[0], node.args[1])
            if fn == "_in_":
                return self._in(node.args[0], node.args[1])
            raise Unsupported(f"function {fn}")
        if isinstance(node, A.Present):
            return self._has(node)
        if isinstance(node, A.Lit):
            if isinstance(node.value, bool):
                b = node.value

                def emit_lit(refs, b=b):
                    xp = refs.xp
                    shape = self._any_shape(refs)
                    return xp.full(shape, b, dtype=bool), xp.zeros(shape, dtype=bool)

                return BoolExpr(emit_lit)
            raise Unsupported("non-bool literal in boolean position")
        # bare attribute path in boolean position: true iff value is bool true
        path = self.path_of(node)
        if path is not None:
            self.k.paths.add(path)

            def emit_path(refs, path=path):
                tag = refs.tag(path)
                val = (tag == TAG_BOOL) & (refs.hi(path) == 1)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                return val & ~err, err

            return BoolExpr(emit_path)
        raise Unsupported("unsupported boolean expression")

    def _any_shape(self, refs: Refs):
        for d in (refs._tags, refs._pred_vals):
            for v in d.values():
                return v.shape
        return (1,)

    def _logic(self, args, is_and: bool) -> BoolExpr:
        parts = [self.compile_bool(a) for a in args]

        def emit(refs):
            vals_errs = [p.emit(refs) for p in parts]
            if is_and:
                # false if any (false & !err); err if no false and any err
                any_false = None
                any_err = None
                all_true = None
                for v, e in vals_errs:
                    f = ~v & ~e
                    any_false = f if any_false is None else (any_false | f)
                    any_err = e if any_err is None else (any_err | e)
                    t = v & ~e
                    all_true = t if all_true is None else (all_true & t)
                err = ~any_false & any_err
                val = all_true & ~err
                return val, err
            any_true = None
            any_err = None
            for v, e in vals_errs:
                t = v & ~e
                any_true = t if any_true is None else (any_true | t)
                any_err = e if any_err is None else (any_err | e)
            err = ~any_true & any_err
            val = any_true
            return val, err

        return BoolExpr(emit)

    def _has(self, node: A.Present) -> BoolExpr:
        path = self.path_of(A.Select(node.operand, node.field))
        if path is None:
            raise Unsupported("has() on non-path")
        self.k.paths.add(path)

        def emit(refs, path=path):
            tag = refs.tag(path)
            err = tag == TAG_ERR
            val = ~err & (tag != TAG_MISSING)
            return val, err

        return BoolExpr(emit)

    # value-compare helpers; `a` is PathOp, b is ConstOp/PathOp

    def _equality(self, lhs_n: A.Node, rhs_n: A.Node, negate: bool) -> BoolExpr:
        lhs, rhs = self.as_operand(lhs_n), self.as_operand(rhs_n)
        if isinstance(lhs, ConstOp) and isinstance(rhs, PathOp):
            lhs, rhs = rhs, lhs
        if isinstance(lhs, ConstOp):
            raise Unsupported("constant == constant")  # let constant folding live on host
        assert isinstance(lhs, PathOp)
        # lists/dicts at an eq path can't be compared on device
        self._add_fallback(lhs.path, {TAG_OTHER})
        if isinstance(rhs, PathOp):
            self._add_fallback(rhs.path, {TAG_OTHER})

            def emit_pp(refs, a=lhs.path, b=rhs.path, negate=negate):
                ta, tb = refs.tag(a), refs.tag(b)
                err = (ta == TAG_MISSING) | (ta == TAG_ERR) | (tb == TAG_MISSING) | (tb == TAG_ERR)
                same_num = (ta == TAG_NUM) & (tb == TAG_NUM) & ~refs.nan(a) & ~refs.nan(b) & (refs.hi(a) == refs.hi(b)) & (refs.lo(a) == refs.lo(b))
                same_str = (ta == TAG_STR) & (tb == TAG_STR) & (refs.sid(a) == refs.sid(b))
                same_bool = (ta == TAG_BOOL) & (tb == TAG_BOOL) & (refs.hi(a) == refs.hi(b))
                same_null = (ta == TAG_NULL) & (tb == TAG_NULL)
                val = same_num | same_str | same_bool | same_null
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pp)

        cval = rhs.value
        if isinstance(cval, list):
            raise Unsupported("list equality")
        if isinstance(cval, bool):
            want = 1 if cval else 0

            def emit_pb(refs, p=lhs.path, want=want, negate=negate):
                tag = refs.tag(p)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = (tag == TAG_BOOL) & (refs.hi(p) == want)
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pb)
        if cval is None:

            def emit_pn(refs, p=lhs.path, negate=negate):
                tag = refs.tag(p)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = tag == TAG_NULL
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pn)
        if isinstance(cval, (int, float)):
            f = float(cval)
            if f != f:

                def emit_pnan(refs, p=lhs.path, negate=negate):
                    tag = refs.tag(p)
                    err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                    xp = refs.xp
                    val = xp.zeros_like(err)
                    if negate:
                        val = ~val
                    return val & ~err, err

                return BoolExpr(emit_pnan)
            hi, lo = split_key(double_key(f))

            def emit_pf(refs, p=lhs.path, hi=hi, lo=lo, negate=negate):
                tag = refs.tag(p)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = (tag == TAG_NUM) & ~refs.nan(p) & (refs.hi(p) == hi) & (refs.lo(p) == lo)
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_pf)
        if isinstance(cval, str):
            sid = self.interner.intern(cval)

            def emit_ps(refs, p=lhs.path, sid=sid, negate=negate):
                tag = refs.tag(p)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = (tag == TAG_STR) & (refs.sid(p) == sid)
                if negate:
                    val = ~val
                return val & ~err, err

            return BoolExpr(emit_ps)
        raise Unsupported(f"equality against {type(cval).__name__} constant")

    def _ordering(self, fn: str, lhs_n: A.Node, rhs_n: A.Node) -> BoolExpr:
        lhs, rhs = self.as_operand(lhs_n), self.as_operand(rhs_n)
        flip = {"_<_": "_>_", "_<=_": "_>=_", "_>_": "_<_", "_>=_": "_<=_"}
        if isinstance(lhs, ConstOp) and isinstance(rhs, PathOp):
            lhs, rhs = rhs, lhs
            fn = flip[fn]
        if isinstance(lhs, ConstOp):
            raise Unsupported("constant ordering")
        assert isinstance(lhs, PathOp)
        # strings/bools/other under ordering → CPU fallback when seen
        self._add_fallback(lhs.path, {TAG_STR, TAG_OTHER})

        def cmp(refs, ahi, alo, bhi, blo, fn):
            lt = (ahi < bhi) | ((ahi == bhi) & (alo < blo))
            eq = (ahi == bhi) & (alo == blo)
            if fn == "_<_":
                return lt
            if fn == "_<=_":
                return lt | eq
            if fn == "_>_":
                return ~lt & ~eq
            return ~lt

        if isinstance(rhs, PathOp):
            self._add_fallback(rhs.path, {TAG_STR, TAG_OTHER})

            def emit_pp(refs, a=lhs.path, b=rhs.path, fn=fn):
                ta, tb = refs.tag(a), refs.tag(b)
                numeric = (ta == TAG_NUM) & (tb == TAG_NUM) & ~refs.nan(a) & ~refs.nan(b)
                err = ~numeric
                val = numeric & cmp(refs, refs.hi(a), refs.lo(a), refs.hi(b), refs.lo(b), fn)
                return val, err

            return BoolExpr(emit_pp)
        cval = rhs.value
        if isinstance(cval, bool) or not isinstance(cval, (int, float)):
            raise Unsupported("non-numeric ordering constant")
        f = float(cval)
        if f != f:
            raise Unsupported("NaN ordering constant")
        hi, lo = split_key(double_key(f))

        def emit_pc(refs, p=lhs.path, hi=hi, lo=lo, fn=fn):
            tag = refs.tag(p)
            numeric = (tag == TAG_NUM) & ~refs.nan(p)
            err = ~numeric
            xp = refs.xp
            chi = xp.asarray(hi, dtype=refs.hi(p).dtype)
            clo = xp.asarray(lo, dtype=refs.lo(p).dtype)
            val = numeric & cmp(refs, refs.hi(p), refs.lo(p), chi, clo, fn)
            return val, err

        return BoolExpr(emit_pc)

    def _in(self, lhs_n: A.Node, rhs_n: A.Node) -> BoolExpr:
        lhs = self.as_operand(lhs_n)
        rhs = self.as_operand(rhs_n)
        if isinstance(lhs, PathOp) and isinstance(rhs, ConstOp) and isinstance(rhs.value, list):
            # OR of equalities against each element
            parts = []
            for el in rhs.value:
                parts.append(self._equality(lhs_n, A.Lit(el), negate=False))

            def emit(refs, parts=parts, p=lhs.path):
                tag = refs.tag(p)
                err = (tag == TAG_MISSING) | (tag == TAG_ERR)
                val = None
                for part in parts:
                    v, _ = part.emit(refs)
                    val = v if val is None else (val | v)
                if val is None:
                    xp = refs.xp
                    val = xp.zeros_like(err)
                return val & ~err, err

            return BoolExpr(emit)
        if isinstance(rhs, PathOp) and isinstance(lhs, ConstOp) and isinstance(lhs.value, str):
            # `"x" in R.attr.list`: membership over a string-list column
            # (sid comparison per padded slot; non-list values error, which
            # collapses to false at the condition boundary like the oracle)
            self.k.list_paths.add(rhs.path)
            sid = self.interner.intern(lhs.value)

            def emit_in_list(refs, p=rhs.path, sid=sid):
                sids, state = refs.list_col(p)
                # anything but a well-formed list (missing attr, wrong type)
                # is a CEL error, which matters under ! / && / || absorption
                err = state != 1
                val = (sids == sid).any(axis=1) & ~err
                return val, err

            return BoolExpr(emit_in_list)
        raise Unsupported("in over attribute lists")

    def _add_fallback(self, path: tuple[str, ...], tags: set[int]) -> None:
        cur = self.k.fallback_tags.get(path, frozenset())
        self.k.fallback_tags[path] = cur | frozenset(tags)

    interner: StringInterner  # set by compile_condition


def _split_chain(node: A.Node) -> Optional[tuple[str, tuple[str, ...]]]:
    """Maximal select/literal-index chain → (root ident, segments)."""
    segs: list[str] = []
    cur = node
    while True:
        if isinstance(cur, A.Select):
            segs.append(cur.field)
            cur = cur.operand
        elif isinstance(cur, A.Index) and isinstance(cur.index, A.Lit) and isinstance(cur.index.value, str):
            segs.append(cur.index.value)
            cur = cur.operand
        elif isinstance(cur, A.Ident):
            return cur.name, tuple(reversed(segs))
        else:
            return None


def _chain_of(node: A.Node) -> Optional[tuple[str, ...]]:
    """Maximal chain rooted at request/R/P → canonical path."""
    split = _split_chain(node)
    if split is None or split[0] not in _ROOT_ALIASES:
        return None
    return _ROOT_ALIASES[split[0]] + split[1]


def _pred_refs(node: A.Node) -> tuple[set[tuple[str, ...]], bool, bool]:
    """(referenced request paths, references_runtime, time_dependent).

    Paths are MAXIMAL chains (e.g. ("aux_data", "jwt", "aud"), not
    ("aux_data",)) so the packer's predicate cache keys freeze only the leaf
    values actually read, not whole subtrees."""
    paths: set[tuple[str, ...]] = set()
    refs_runtime = False
    time_dep = False

    def visit(n: A.Node) -> None:
        if isinstance(n, A.Ident):
            if n.name == "runtime":
                nonlocal refs_runtime
                refs_runtime = True
            return
        if isinstance(n, A.Call):
            nonlocal time_dep
            if n.fn in ("now", "timeSince"):
                time_dep = True
            if n.target is not None:
                visit(n.target)
            for a in n.args:
                visit(a)
            return
        if isinstance(n, (A.Select, A.Present, A.Index)):
            chain = _chain_of(n if not isinstance(n, A.Present) else A.Select(n.operand, n.field))
            if chain is not None:
                paths.add(chain)
                # still visit a computed index expression
                if isinstance(n, A.Index):
                    visit(n.index)
                return
            if isinstance(n, (A.Select, A.Present)):
                visit(n.operand)
            else:
                visit(n.operand)
                visit(n.index)
            return
        if isinstance(n, A.ListLit):
            for x in n.items:
                visit(x)
            return
        if isinstance(n, A.MapLit):
            for k, v in n.entries:
                visit(k)
                visit(v)
            return
        if isinstance(n, A.Bind):
            visit(n.init)
            visit(n.body)
            return
        if isinstance(n, A.Comprehension):
            visit(n.iter_range)
            visit(n.step)
            if n.step2 is not None:
                visit(n.step2)
            return

    visit(node)
    return paths, refs_runtime, time_dep


class ConditionSetCompiler:
    """Compiles the distinct (condition, params) pairs of a rule table."""

    def __init__(self, globals_: dict[str, Any], interner: StringInterner):
        self.globals = globals_
        self.interner = interner
        self.kernels: list[CondKernel] = []
        self._by_key: dict[tuple[int, int], int] = {}
        self.preds: list[PredSpec] = []

    def cond_id(self, cond: Optional[CompiledCondition], params: Optional[PolicyParams]) -> int:
        """Intern a (condition, params) pair; -1 for condition-less.

        Interning is *structural* (condition text + params content), the
        analogue of the reference's FunctionalCore dedup by behavioral hash
        (index.go:26-32,119-148): policy corpora replicate identical
        conditions across many policies, and one kernel serves them all.
        """
        if cond is None:
            return -1
        id_key = (id(cond), id(params))
        hit = self._by_key.get(id_key)
        if hit is not None:
            return hit
        struct_key = (_cond_struct_key(cond), _params_struct_key(params))
        hit = self._by_key.get(struct_key)
        if hit is not None:
            self._by_key[id_key] = hit
            return hit
        cid = len(self.kernels)
        kernel = self._compile(cond, params or PolicyParams(), cid)
        self.kernels.append(kernel)
        self._by_key[id_key] = cid
        self._by_key[struct_key] = cid
        return cid

    def _alloc_pred(self, node: A.Node, params: PolicyParams) -> PredSpec:
        paths, refs_runtime, time_dep = _pred_refs(node)
        spec = PredSpec(
            pred_id=len(self.preds),
            node=node,
            params=params,
            ref_paths=tuple(sorted(paths)),
            time_dependent=time_dep,
        )
        self.preds.append(spec)
        return spec

    def _compile(self, cond: CompiledCondition, params: PolicyParams, cid: int) -> CondKernel:
        kernel = CondKernel(cond_id=cid)
        comp = _Compiler(kernel, params, self.globals, self._alloc_pred)
        comp.interner = self.interner

        def compile_tree(c: CompiledCondition) -> Callable[[Refs], Any]:
            """Condition-tree node → emit(refs) -> sat bool array.

            all/any/none combine *satisfied* child results (each child's
            errors collapse to false at its own boundary — check.go:650-702),
            which is not the same as CEL && / ||.
            """
            if c.kind == "expr":
                node = comp.inline(c.expr.node)
                try:
                    be = comp.compile_bool(node)

                    def emit_expr(refs, be=be):
                        v, e = be.emit(refs)
                        return v & ~e

                    return emit_expr
                except Unsupported:
                    if kernel.references_runtime:
                        raise
                    spec = self._alloc_pred(node, params)
                    kernel.preds.append(spec)

                    def emit_pred(refs, pid=spec.pred_id):
                        v, e = refs.pred(pid)
                        return v & ~e

                    return emit_pred
            subs = [compile_tree(ch) for ch in c.children]
            if c.kind == "all":
                def emit_all(refs, subs=subs):
                    out = None
                    for s in subs:
                        v = s(refs)
                        out = v if out is None else (out & v)
                    return out
                return emit_all
            if c.kind == "any":
                def emit_any(refs, subs=subs):
                    out = None
                    for s in subs:
                        v = s(refs)
                        out = v if out is None else (out | v)
                    return out
                return emit_any
            if c.kind == "none":
                def emit_none(refs, subs=subs):
                    out = None
                    for s in subs:
                        v = s(refs)
                        out = v if out is None else (out | v)
                    return ~out
                return emit_none
            raise ValueError(f"unknown condition kind {c.kind}")

        try:
            kernel.emit = compile_tree(cond)
        except Unsupported:
            # runtime-referencing conditions can't be batched at all
            kernel.emit = None
        return kernel


def _cond_struct_key(c: CompiledCondition):
    if c.kind == "expr":
        return ("e", c.expr.original)
    return (c.kind[0], tuple(_cond_struct_key(ch) for ch in c.children))


def _freeze_val(v):
    if isinstance(v, list):
        return tuple(_freeze_val(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze_val(x)) for k, x in v.items()))
    return v


def _params_struct_key(params: Optional[PolicyParams]):
    if params is None:
        return None
    return (
        tuple(sorted((k, _freeze_val(v)) for k, v in params.constants.items())),
        tuple((v.name, v.expr.original) for v in params.ordered_variables),
    )


def evaluate_pred_host(spec: PredSpec, input_obj, eval_ctx_factory) -> tuple[bool, bool]:
    """Evaluate a predicate column entry on the host → (value, error)."""
    from ..cel.interp import evaluate

    act = eval_ctx_factory(spec.params)
    try:
        v = evaluate(spec.node, act)
    except CelError:
        return False, True
    return v is True, False
