"""Vectorized host predicates (VERDICT r4 item 3, memo-cold pack cost).

Predicate columns are boolean subexpressions the device kernels can't
evaluate (string *content* ops like ``startsWith``, IP range membership).
The generic path evaluates them through the full CEL interpreter with a
per-input ``EvalContext`` — ~30µs per distinct value combination, which a
memo-cold batch pays for every input (packer._encode_preds).

This module compiles the overwhelmingly common predicate shapes into
closed-form batch evaluators: one Python-level loop per AST op over the
gathered attribute columns, no activation/context objects, no interpreter
dispatch. Everything else returns None and rides the generic path.

Supported grammar (mirrors cel.interp semantics EXACTLY — see the unit
equivalence test in tests/test_fastpred.py):

  e := Lit
     | path                                (request/R/P select chains with
                                            the packer's fast accessor
                                            shapes)
     | e == e | e != e | cond ? e : e | !e
     | str_path.startsWith/endsWith/contains(Lit str)
     | path.inIPAddrRange(Lit str)

Error semantics reproduced: missing attribute -> no_such_key error;
non-string method target/arg -> no-such-overload error; IP/CIDR parse
failure -> error; IP version mismatch -> False (not an error);
non-bool ternary condition -> error. Errors at any subexpression poison
the whole predicate (evaluate() raises), which `evaluate_pred_host`
reports as (False, True).
"""

from __future__ import annotations

import ipaddress
from typing import Callable, Optional

from ..cel import ast as A
from ..cel.values import values_equal
from .condcompile import _ROOT_ALIASES, _split_chain

# evaluation contract: an op is eval(ctx) -> (vals, errs) where
#   vals: list[Any] of length n (entries meaningless where errs[i])
#   errs: list[bool]
# and ctx maps path -> gathered value list (packer supplies, including
# _MISSING/_ERR sentinels from the accessors)

_MISSING = None  # bound by configure() to the packer's sentinels
_ERR = None


def configure(missing_sentinel, err_sentinel) -> None:
    global _MISSING, _ERR
    _MISSING = missing_sentinel
    _ERR = err_sentinel


class _Bail(Exception):
    pass


def _fast_path(node: A.Node) -> tuple[str, ...]:
    """Select chain → canonical path, restricted to the accessor shapes
    whose missing/present semantics match the oracle's Select walk
    (packer._path_accessor fast shapes)."""
    split = _split_chain(node)
    if split is None or split[0] not in _ROOT_ALIASES:
        raise _Bail
    path = _ROOT_ALIASES[split[0]] + split[1]
    if len(path) == 3 and path[0] in ("aux_data", "auxData") and path[1] == "jwt":
        return path
    if len(path) == 3 and path[0] in ("principal", "resource") and path[1] == "attr":
        return path
    if (
        len(path) == 2
        and path[0] in ("principal", "resource")
        and path[1] in ("id", "kind", "roles", "attr", "policyVersion", "scope")
    ):
        return path
    raise _Bail


def _compile(node: A.Node, paths: set) -> Callable:
    if isinstance(node, A.Lit):
        v = node.value

        def op_lit(ctx, n, v=v):
            return [v] * n, [False] * n

        return op_lit

    if isinstance(node, (A.Select, A.Index)):
        path = _fast_path(node)
        paths.add(path)

        def op_path(ctx, n, path=path):
            vals = ctx[path]
            errs = [v is _MISSING or v is _ERR for v in vals]
            return vals, errs

        return op_path

    if isinstance(node, A.Call):
        fn = node.fn
        if node.target is None:
            if fn in ("_==_", "_!=_") and len(node.args) == 2:
                a = _compile(node.args[0], paths)
                b = _compile(node.args[1], paths)
                neg = fn == "_!=_"

                def op_eq(ctx, n, a=a, b=b, neg=neg):
                    av, ae = a(ctx, n)
                    bv, be = b(ctx, n)
                    vals = [False] * n
                    errs = [False] * n
                    for i in range(n):
                        if ae[i] or be[i]:
                            errs[i] = True
                        else:
                            r = values_equal(av[i], bv[i])
                            vals[i] = (not r) if neg else r
                    return vals, errs

                return op_eq

            if fn == "_?_:_" and len(node.args) == 3:
                c = _compile(node.args[0], paths)
                t = _compile(node.args[1], paths)
                f = _compile(node.args[2], paths)

                def op_ternary(ctx, n, c=c, t=t, f=f):
                    cv, ce = c(ctx, n)
                    tv, te = t(ctx, n)
                    fv, fe = f(ctx, n)
                    vals = [None] * n
                    errs = [False] * n
                    for i in range(n):
                        if ce[i] or type(cv[i]) is not bool:
                            errs[i] = True
                        elif cv[i]:
                            vals[i], errs[i] = tv[i], te[i]
                        else:
                            vals[i], errs[i] = fv[i], fe[i]
                    return vals, errs

                return op_ternary

            if fn == "!_" and len(node.args) == 1:
                a = _compile(node.args[0], paths)

                def op_not(ctx, n, a=a):
                    av, ae = a(ctx, n)
                    vals = [False] * n
                    errs = [False] * n
                    for i in range(n):
                        if ae[i] or type(av[i]) is not bool:
                            errs[i] = True
                        else:
                            vals[i] = not av[i]
                    return vals, errs

                return op_not

            raise _Bail

        # target methods
        if fn in ("startsWith", "endsWith", "contains") and len(node.args) == 1:
            arg = node.args[0]
            if not (isinstance(arg, A.Lit) and isinstance(arg.value, str)):
                raise _Bail
            lit = arg.value
            t = _compile(node.target, paths)
            mode = fn

            def op_str(ctx, n, t=t, lit=lit, mode=mode):
                tv, te = t(ctx, n)
                vals = [False] * n
                errs = [False] * n
                for i in range(n):
                    v = tv[i]
                    if te[i] or not isinstance(v, str):
                        errs[i] = True
                    elif mode == "startsWith":
                        vals[i] = v.startswith(lit)
                    elif mode == "endsWith":
                        vals[i] = v.endswith(lit)
                    else:
                        vals[i] = lit in v
                return vals, errs

            return op_str

        if fn == "inIPAddrRange" and len(node.args) == 1:
            arg = node.args[0]
            if not (isinstance(arg, A.Lit) and isinstance(arg.value, str)):
                raise _Bail
            t = _compile(node.target, paths)
            try:
                net = ipaddress.ip_network(arg.value, strict=False)
            except ValueError:
                # the oracle raises CelError on every evaluation
                def op_ip_bad(ctx, n, t=t):
                    tv, te = t(ctx, n)
                    return [False] * n, [True] * n

                return op_ip_bad
            v4 = net.version == 4
            net_int = int(net.network_address)
            mask = int(net.netmask)
            memo: dict[str, tuple[bool, bool]] = {}

            def op_ip(ctx, n, t=t, v4=v4, net_int=net_int, mask=mask, memo=memo):
                tv, te = t(ctx, n)
                vals = [False] * n
                errs = [False] * n
                for i in range(n):
                    v = tv[i]
                    if te[i] or not isinstance(v, str):
                        errs[i] = True
                        continue
                    hit = memo.get(v)
                    if hit is None:
                        hit = _ip_check(v, v4, net_int, mask)
                        if len(memo) > 65536:
                            memo.clear()
                        memo[v] = hit
                    vals[i], errs[i] = hit
                return vals, errs

            return op_ip

    raise _Bail


def _parse_ipv4(s: str) -> Optional[int]:
    """Strict dotted-quad parse mirroring ipaddress.IPv4Address: exactly 4
    decimal octets, 0-255, no leading zeros (ambiguous octal), no signs or
    whitespace. Returns the 32-bit int or None."""
    parts = s.split(".")
    if len(parts) != 4:
        return None
    out = 0
    for p in parts:
        lp = len(p)
        if lp == 0 or lp > 3 or not p.isascii() or not p.isdigit():
            return None
        if lp > 1 and p[0] == "0":
            return None
        v = int(p)
        if v > 255:
            return None
        out = (out << 8) | v
    return out


def _ip_check(s: str, v4: bool, net_int: int, mask: int) -> tuple[bool, bool]:
    """(value, error) of inIPAddrRange for one address string, against a
    pre-parsed network. Fast path for clean IPv4; ipaddress otherwise."""
    a4 = _parse_ipv4(s)
    if a4 is not None:
        if not v4:
            return False, False  # version mismatch -> False, no error
        return (a4 & mask) == net_int, False
    try:
        addr = ipaddress.ip_address(s)
    except ValueError:
        return False, True  # oracle: CelError
    if (addr.version == 4) != v4:
        return False, False
    return (int(addr) & mask) == net_int, False


class FastPred:
    __slots__ = ("eval", "paths")

    def __init__(self, ev: Callable, paths: set):
        self.eval = ev
        self.paths = paths


def compile_fast_pred(spec) -> Optional[FastPred]:
    """PredSpec → FastPred, or None when any fragment is outside the fast
    grammar (the caller keeps the generic interpreter path)."""
    if spec.time_dependent:
        return None
    paths: set = set()
    try:
        op = _compile(spec.node, paths)
    except _Bail:
        return None

    def run(ctx, n, op=op):
        vals, errs = op(ctx, n)
        # evaluate_pred_host contract: value = (result is True) and errors
        # report as (False, True)
        return [(not e) and (v is True) for v, e in zip(vals, errs)], errs

    return FastPred(run, paths)
