"""Batched device evaluator: condition kernels + effect-resolution lattice.

The device computes ``sat_cond[B, C]`` (every distinct condition over every
input) and resolves effects as a masked reduction over
(policy-type, role-slot, scope-depth) — the reference's sequential
short-circuits (check.go:183-438) become "evaluate everything, select by
priority", which is sound because conditions are pure. The host then
assembles CheckOutputs, reconstructing policy attribution, outputs and
effective derived roles from the device's winning (pt, role, depth, j).

Sharding: the batch axis shards over a jax Mesh ("data" axis); candidate
tensors are batch-aligned so the same jit works single-chip or multi-chip
(see cerbos_tpu.parallel.mesh).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

from .. import namer
from ..engine import types as T
from ..observability import start_span
from ..ruletable.check import EvalContext, build_request_messages, check_input
from ..ruletable.table import RuleTable
from . import compilestats
from .condcompile import Refs
from .lowering import (
    EFFECT_ALLOW_CODE,
    EFFECT_DENY_CODE,
    LoweredTable,
    SP_OVERRIDE,
    lower_table,
)
from .packer import PackedBatch, Packer, PT_PRINCIPAL, PT_RESOURCE

def _clone_output(template: "T.CheckOutput", inp: "T.CheckInput") -> "T.CheckOutput":
    """Fresh CheckOutput from a memoized assembly (ids swapped). ActionEffect
    values are immutable once assembly returns (only the oracle mutates its
    own in-flight effects), so the clone shares them with the template.
    Built via __new__: the dataclass __init__'s default factories cost ~3x
    on this per-input path. Templates are only memoized when the table has
    no outputs and no validation errors, so those fields start empty."""
    out = T.CheckOutput.__new__(T.CheckOutput)
    out.request_id = inp.request_id
    out.resource_id = inp.resource.id
    out.actions = dict(template.actions)
    out.effective_derived_roles = list(template.effective_derived_roles)
    out.validation_errors = []
    out.outputs = []
    out.effective_policies = dict(template.effective_policies)
    return out


CODE_NO_MATCH = 0
CODE_ALLOW = 1
CODE_DENY = 2

_BIG = 127


def _sat_groups(xp, compiler, B: int, refs, variant=None):
    """Condition satisfaction per TEMPLATE GROUP — one broadcast subgraph
    per distinct condition structure covers all its members at once (graph
    size is O(templates), not O(conditions)).

    With ``variant`` (a static tuple of
    ``(group_index, member_positions | None)``, None = every member) each
    group is restricted to the members the batch references, and the result
    is a COMPACT [B, A] matrix in variant (concat) order — device work is
    O(active conditions) even when the table holds thousands (VERDICT r3
    item 2); the caller translates cond ids through its col_map. Without
    ``variant``, the full [B, C] matrix in cond-id order."""
    compiler.build_groups()
    C = len(compiler.kernels)
    if not C:
        return xp.zeros((B, 1), dtype=bool)
    if variant is not None:
        from .condcompile import subset_group_consts

        blocks = []
        for gi, sel in variant:
            g = compiler.groups[gi]
            if sel is None:
                blocks.append(xp.broadcast_to(g.emit(refs, g.gc), (B, g.gc.size)))
            else:
                sub = subset_group_consts(g.gc, sel)
                blocks.append(xp.broadcast_to(g.emit(refs, sub), (B, len(sel))))
        if not blocks:
            return xp.zeros((B, 1), dtype=bool)
        # COMPACT [B, A] in variant (concat) order — the caller translates
        # cond ids through its col_map; dead/unreferenced columns simply
        # don't exist here
        return xp.concatenate(blocks, axis=1)
    blocks = [
        xp.broadcast_to(g.emit(refs, g.gc), (B, g.gc.size))
        for g in compiler.groups
    ]
    if not blocks:
        return xp.zeros((B, C), dtype=bool)
    allsat = xp.concatenate(blocks, axis=1)
    sat_cond = allsat[:, compiler.perm]
    if compiler.dead.any():
        sat_cond = sat_cond & ~xp.asarray(compiler.dead)[None, :]
    return sat_cond


def _compute(
    xp,
    compiler,
    K: int,
    J: int,
    D: int,
    tags,
    his,
    los,
    sids,
    nans,
    pred_vals,
    pred_errs,
    ba_input,
    cand_cond,
    cand_drcond,
    cand_effect,
    cand_pt,
    cand_depth,
    cand_valid,
    scope_sp,
    list_sids=None,
    list_states=None,
    ts_his=None,
    ts_los=None,
    ts_states=None,
    now_hi=None,
    now_lo=None,
    variant=None,
):
    """Pure array computation: jittable with `xp=jnp`, testable with numpy.

    Returns (final [BA,4], role_results [BA,K,2,2], win_j [BA,K,2],
    sat_cond [B,C]) — see module docstring for the lattice.

    With ``variant`` (static group-member subsets — see _sat_groups), the
    sat matrix is compact over the referenced columns and the cand id
    arrays must already be remapped into that compact space.
    """
    refs = Refs(xp, tags, his, los, sids, nans, pred_vals, pred_errs,
                list_sids=list_sids, list_states=list_states,
                ts_his=ts_his, ts_los=ts_los, ts_states=ts_states,
                now_hi=now_hi, now_lo=now_lo)
    # scope_sp is always [B, 2, D]; column dicts can all be empty when the
    # policy set has only unconditional rules, so B must not come from them
    B = scope_sp.shape[0]
    sat_cond = _sat_groups(xp, compiler, B, refs, variant=variant)

    BA = cand_cond.shape[0]
    sat_by_input = sat_cond[ba_input]  # [BA, C]

    ba_idx = xp.arange(BA)[:, None, None]
    cond_ok = cand_cond >= 0
    drcond_ok = cand_drcond >= 0
    cond_safe = xp.where(cond_ok, cand_cond, 0)
    drcond_safe = xp.where(drcond_ok, cand_drcond, 0)
    sat_c = xp.where(cond_ok, sat_by_input[ba_idx, cond_safe], True)
    sat_dr = xp.where(drcond_ok, sat_by_input[ba_idx, drcond_safe], True)
    sat = cand_valid & sat_c & sat_dr  # [BA, K, J]

    deny_mask = sat & (cand_effect == EFFECT_DENY_CODE)
    allow_mask = sat & (cand_effect == EFFECT_ALLOW_CODE)

    sp_by_ba = scope_sp[ba_input]  # [BA, 2, D]

    role_codes = []
    role_depths = []
    winjs = []
    for pt in (PT_PRINCIPAL, PT_RESOURCE):
        pt_mask = cand_pt == pt
        # per-depth any / first-j
        code = xp.zeros((BA, K), dtype=xp.int8)
        depth_out = xp.full((BA, K), D, dtype=xp.int8)
        wj = xp.full((BA, K), -1, dtype=xp.int8)
        decided = xp.zeros((BA, K), dtype=bool)
        for d in range(D):
            at_d = pt_mask & (cand_depth == d)
            deny_d = (deny_mask & at_d).any(axis=2)  # [BA, K]
            allow_d = (allow_mask & at_d).any(axis=2)
            sp_d = sp_by_ba[:, pt, d][:, None]  # [BA, 1]
            allow_ok = allow_d & (sp_d == SP_OVERRIDE)
            # first satisfied deny/allow j at this depth — the winning-rule
            # column (ISSUE 20) is this one extra min-reduction over the
            # already-computed activation masks, not a second pass
            j_idx = xp.arange(J)[None, None, :]
            deny_j = xp.where(deny_mask & at_d, j_idx, _BIG).min(axis=2)  # [BA, K]
            allow_j = xp.where(allow_mask & at_d, j_idx, _BIG).min(axis=2)
            newly_deny = ~decided & deny_d
            newly_allow = ~decided & ~deny_d & allow_ok
            code = xp.where(newly_deny, CODE_DENY, xp.where(newly_allow, CODE_ALLOW, code))
            depth_out = xp.where(newly_deny | newly_allow, d, depth_out)
            wj = xp.where(
                newly_deny,
                deny_j.astype(xp.int8),
                xp.where(newly_allow, allow_j.astype(xp.int8), wj),
            )
            decided = decided | newly_deny | newly_allow
        role_codes.append(code)
        role_depths.append(depth_out)
        winjs.append(wj)

    role_results = xp.stack(
        [xp.stack([role_codes[0], role_depths[0]], axis=-1), xp.stack([role_codes[1], role_depths[1]], axis=-1)],
        axis=2,
    )  # [BA, K, 2(pt), 2(code,depth)]
    win_j = xp.stack(winjs, axis=2)  # [BA, K, 2]

    # merge roles within each policy type:
    #   first role with ALLOW wins; else first role with any non-NO_MATCH
    def merge(codes, depths, wjs, single_role: bool):
        if single_role:
            return codes[:, 0], depths[:, 0], wjs[:, 0], xp.zeros(codes.shape[0], dtype=xp.int8)
        k_idx = xp.arange(K)[None, :]
        allow_k = xp.where(codes == CODE_ALLOW, k_idx, _BIG).min(axis=1)
        nonmatch_k = xp.where(codes != CODE_NO_MATCH, k_idx, _BIG).min(axis=1)
        pick = xp.where(allow_k < _BIG, allow_k, xp.where(nonmatch_k < _BIG, nonmatch_k, 0))
        pick = pick.astype(xp.int32)
        rows = xp.arange(codes.shape[0])
        return codes[rows, pick], depths[rows, pick], wjs[rows, pick], pick.astype(xp.int8)

    p_code, p_depth, p_wj, p_k = merge(role_codes[0], role_depths[0], winjs[0], single_role=True)
    r_code, r_depth, r_wj, r_k = merge(role_codes[1], role_depths[1], winjs[1], single_role=False)

    use_p = p_code != CODE_NO_MATCH
    f_code = xp.where(use_p, p_code, r_code)
    f_pt = xp.where(use_p, PT_PRINCIPAL, PT_RESOURCE).astype(xp.int8)
    f_depth = xp.where(use_p, p_depth, r_depth)
    f_k = xp.where(use_p, p_k, r_k)
    final = xp.stack([f_code.astype(xp.int8), f_pt, f_depth.astype(xp.int8), f_k], axis=1)

    return final, role_results, win_j, sat_cond


def _next_bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class _StackLayout:
    """Static description of how column families were stacked for transfer.

    ``sig`` participates in the jit-cache key: two batches share a trace only
    when the path orders, list widths and presence flags line up."""

    __slots__ = ("paths", "ts_paths", "list_paths", "list_widths", "pred_ids",
                 "D", "has_now", "sig")

    def __init__(self, paths, ts_paths, list_paths, list_widths, pred_ids, D, has_now):
        self.paths = paths
        self.ts_paths = ts_paths
        self.list_paths = list_paths
        self.list_widths = list_widths
        self.pred_ids = pred_ids
        self.D = D
        self.has_now = has_now
        self.sig = (paths, ts_paths, list_paths, list_widths, pred_ids, D, has_now)


def _stack_padded(padded: dict) -> tuple[dict, _StackLayout]:
    """Fuse the per-path column dicts into a handful of typed matrices so a
    device dispatch costs O(1) host->device transfers (see _device_eval)."""
    paths = tuple(sorted(padded["tags"]))
    ts_paths = tuple(sorted(padded["ts_his"]))
    list_paths = tuple(sorted(padded["list_sids"]))
    list_widths = tuple(int(padded["list_sids"][p].shape[1]) for p in list_paths)
    pred_ids = tuple(sorted(padded["pred_vals"]))
    scope_sp = padded["scope_sp"]
    B = scope_sp.shape[0]
    D = scope_sp.shape[2]
    has_now = padded["now_hi"] is not None

    i32_rows = (
        [padded["his"][p] for p in paths]
        + [padded["los"][p] for p in paths]
        + [padded["sids"][p] for p in paths]
        + [padded["ts_his"][p] for p in ts_paths]
        + [padded["ts_los"][p] for p in ts_paths]
    )
    i32_cols = np.stack(i32_rows) if i32_rows else np.zeros((0, B), dtype=np.int32)
    i8_rows = (
        [padded["tags"][p] for p in paths]
        + [padded["ts_states"][p] for p in ts_paths]
        + [padded["list_states"][p] for p in list_paths]
    )
    i8_cols = np.concatenate(
        [
            np.stack(i8_rows).astype(np.int8) if i8_rows else np.zeros((0, B), np.int8),
            np.ascontiguousarray(scope_sp.transpose(1, 2, 0).reshape(2 * D, B)),
        ]
    )
    bool_rows = (
        [padded["nans"][p] for p in paths]
        + [padded["pred_vals"][q] for q in pred_ids]
        + [padded["pred_errs"][q] for q in pred_ids]
    )
    bool_cols = np.stack(bool_rows) if bool_rows else np.zeros((0, B), dtype=bool)
    if list_paths:
        wmax = max(list_widths)
        lists = np.zeros((len(list_paths), B, wmax), dtype=np.int32)
        for i, p in enumerate(list_paths):
            a = padded["list_sids"][p]
            lists[i, :, : a.shape[1]] = a
    else:
        lists = np.zeros((0, B, 1), dtype=np.int32)
    cand_i32 = np.stack([padded["cand_cond"], padded["cand_drcond"]])
    cand_i8 = np.stack(
        [
            padded["cand_effect"],
            padded["cand_pt"],
            padded["cand_depth"],
            padded["cand_valid"].astype(np.int8),
        ]
    )
    now = (
        np.asarray([int(padded["now_hi"]), int(padded["now_lo"])], dtype=np.int32)
        if has_now
        else np.zeros(2, dtype=np.int32)
    )
    layout = _StackLayout(paths, ts_paths, list_paths, list_widths, pred_ids, D, has_now)
    stacked = dict(
        i32_cols=i32_cols,
        i8_cols=i8_cols,
        bool_cols=bool_cols,
        lists=lists,
        cand_i32=cand_i32,
        cand_i8=cand_i8,
        ba_input=padded["ba_input"],
        now=now,
    )
    return stacked, layout


def _unstack_padded(xp, lay: _StackLayout, kw: dict) -> dict:
    """Inverse of _stack_padded, executed INSIDE the traced graph (slices of
    traced arrays are free — XLA fuses them into the consumers)."""
    i32 = kw["i32_cols"]
    i8 = kw["i8_cols"]
    bools = kw["bool_cols"]
    lists = kw["lists"]
    cand_i32 = kw["cand_i32"]
    cand_i8 = kw["cand_i8"]
    P = len(lay.paths)
    T = len(lay.ts_paths)
    L = len(lay.list_paths)
    his = {p: i32[i] for i, p in enumerate(lay.paths)}
    los = {p: i32[P + i] for i, p in enumerate(lay.paths)}
    sids = {p: i32[2 * P + i] for i, p in enumerate(lay.paths)}
    ts_his = {p: i32[3 * P + i] for i, p in enumerate(lay.ts_paths)}
    ts_los = {p: i32[3 * P + T + i] for i, p in enumerate(lay.ts_paths)}
    tags = {p: i8[i] for i, p in enumerate(lay.paths)}
    ts_states = {p: i8[P + i] for i, p in enumerate(lay.ts_paths)}
    list_states = {p: i8[P + T + i] for i, p in enumerate(lay.list_paths)}
    B = i8.shape[1]
    scope_sp = i8[P + T + L :].reshape(2, lay.D, B).transpose(2, 0, 1)
    nans = {p: bools[i] for i, p in enumerate(lay.paths)}
    Q = len(lay.pred_ids)
    pred_vals = {q: bools[P + i] for i, q in enumerate(lay.pred_ids)}
    pred_errs = {q: bools[P + Q + i] for i, q in enumerate(lay.pred_ids)}
    list_sids = {
        p: lists[i][:, : lay.list_widths[i]] for i, p in enumerate(lay.list_paths)
    }
    now_hi = kw["now"][0] if lay.has_now else None
    now_lo = kw["now"][1] if lay.has_now else None
    return dict(
        tags=tags, his=his, los=los, sids=sids, nans=nans,
        pred_vals=pred_vals, pred_errs=pred_errs,
        ba_input=kw["ba_input"],
        cand_cond=cand_i32[0], cand_drcond=cand_i32[1],
        cand_effect=cand_i8[0], cand_pt=cand_i8[1], cand_depth=cand_i8[2],
        cand_valid=cand_i8[3].astype(bool),
        scope_sp=scope_sp,
        list_sids=list_sids, list_states=list_states,
        ts_his=ts_his, ts_los=ts_los, ts_states=ts_states,
        now_hi=now_hi, now_lo=now_lo,
    )


def _variant_remap(variant, compiler, C, cand_cond, cand_drcond):
    """col_map + compact-space remap of the candidate id arrays for one
    group-member variant. Single source of truth for both the primary
    variant and the budget-fallback full variant."""
    cols_parts = []
    for gi, sel in variant:
        g = compiler.groups[gi]
        if sel is None:
            cols_parts.append(g.cond_id_arr)
        else:
            cols_parts.append(g.cond_id_arr[np.asarray(sel, dtype=np.int64)])
    colcat = np.concatenate(cols_parts) if cols_parts else np.zeros(0, dtype=np.int64)
    A = int(colcat.size)
    col_map = np.full(max(C, 1), -1, dtype=np.int64)
    if A:
        col_map[colcat] = np.arange(A, dtype=np.int64)
        safe = np.clip(cand_cond, 0, max(C - 1, 0))
        cand_cond_c = np.where(cand_cond >= 0, col_map[safe], -1).astype(np.int32)
        safe = np.clip(cand_drcond, 0, max(C - 1, 0))
        cand_drcond_c = np.where(cand_drcond >= 0, col_map[safe], -1).astype(np.int32)
    else:
        cand_cond_c = np.full_like(cand_cond, -1)
        cand_drcond_c = np.full_like(cand_drcond, -1)
    return col_map, cand_cond_c, cand_drcond_c


def _zero_result(B: int, K: int, C: int):
    return (
        np.zeros((0, 4), dtype=np.int8),
        np.zeros((0, K, 2, 2), dtype=np.int8),
        np.zeros((0, K, 2), dtype=np.int8),
        np.zeros((B, 1), dtype=bool),
        np.full(max(C, 1), -1, dtype=np.int64),
    )


def _active_variant(lt: LoweredTable, batch: PackedBatch):
    """Group-member variant for one batch: per template group, the members
    the batch references (None = all of them). Active columns are the
    candidates + synthetic denies (both live in the cand arrays) plus every
    derived-role condition (host assembly reads those off sat regardless of
    candidates). Static structure — the jit cache keys on it; the numpy
    path just iterates it."""
    compiler = lt.compiler
    C = len(compiler.kernels)
    active = np.zeros(max(C, 1), dtype=bool)
    for arr in (batch.cand_cond, batch.cand_drcond):
        ids = arr[arr >= 0]
        if ids.size:
            active[ids] = True
    if lt.dr_cond_id_arr.size:
        active[lt.dr_cond_id_arr] = True
    variant: list[tuple[int, Optional[tuple[int, ...]]]] = []
    for gi, g in enumerate(compiler.groups):
        mask = active[g.cond_id_arr]
        if mask.all():
            variant.append((gi, None))
        elif mask.any():
            variant.append((gi, tuple(int(i) for i in np.nonzero(mask)[0])))
    return tuple(variant)


def _select_variant(lt: LoweredTable, batch: PackedBatch, jit_cache: dict):
    """Pick the (static) group-member variant for a jitted evaluation.

    Small tables ride one full-variant trace per shape bucket: computing
    every condition costs microseconds on device, while every distinct
    member subset is a separate trace — a fresh multi-second XLA compile
    and a persistent-cache miss. Large tables keep the O(active) compact
    variants, with a budget of DISTINCT VARIANTS (not cache entries:
    shape-bucket churn must not evict sparse variants that are already
    compiled); past the budget, new subsets ride the full variant."""
    compiler = lt.compiler
    C = len(compiler.kernels)
    full_variant = tuple((gi, None) for gi in range(len(compiler.groups)))
    if C <= 256:
        return full_variant
    variant_key = _active_variant(lt, batch)
    seen_variants = jit_cache.setdefault(("_variant_budget",), set())
    if (
        variant_key != full_variant
        and variant_key not in seen_variants
        and len(seen_variants) >= 32
    ):
        compilestats.stats().record_variant_fallback()
        return full_variant
    seen_variants.add(variant_key)
    return variant_key


def _device_eval(
    lt: LoweredTable,
    batch: PackedBatch,
    use_jax: bool = True,
    jit_cache: Optional[dict] = None,
    mesh=None,
):
    """Run the condition kernels + lattice, returning
    ``(final, role_results, win_j, sat_arr, col_map)``.

    ``sat_arr`` is COMPACT: [B, A] over only the condition columns this
    batch references (candidates, synthetic denies, derived-role
    conditions); ``col_map`` [C] maps cond_id -> compact column (-1 for
    columns not computed — assembly never reads those by construction).
    Keeping sat compact makes device and host work O(active conditions)
    even when the table holds thousands (VERDICT r3 item 2).

    With jax, runs through a shape-bucketed ``jax.jit`` cache whose key
    includes the group-member subset (static trace structure); with a
    ``mesh``, batch-axis arrays are placed with a NamedSharding over the
    mesh's "data" axis (padded bucket sizes are powers of two >=16, so they
    divide evenly over 2/4/8-device meshes) and XLA partitions the
    computation across devices.
    """
    if use_jax and mesh is None:
        # single-chip device path: async dispatch + blocking finalize
        # (an EMPTY caller dict is still the caller's cache — only None
        # gets a throwaway)
        return _device_finalize(
            _device_dispatch(lt, batch, jit_cache if jit_cache is not None else {})
        )

    compiler = lt.compiler
    K, J, D = batch.K, batch.J, batch.D
    BA = batch.cand_cond.shape[0]
    B = batch.columns.size

    compiler.build_groups()
    C = len(compiler.kernels)

    if BA == 0:
        return _zero_result(B, K, C)

    if use_jax:
        # decide the (static trace structure) variant BEFORE remapping /
        # padding / sharding so those all see the final choice
        if jit_cache is None:
            jit_cache = {}
        B_pad = _next_bucket(B)
        BA_pad = _next_bucket(BA)
        variant_key = _select_variant(lt, batch, jit_cache)
    else:
        # the numpy path pays no compile cost: always evaluate compactly
        # over just the columns this batch references
        variant_key = _active_variant(lt, batch)

    # remap candidate cond ids into compact columns (-1 preserved); by the
    # active-set construction every referenced id has a compact column
    col_map, cand_cond_c, cand_drcond_c = _variant_remap(
        variant_key, compiler, C, batch.cand_cond, batch.cand_drcond
    )
    cols = batch.columns
    arrays = dict(
        tags=cols.tags, his=cols.his, los=cols.los, sids=cols.sids, nans=cols.nans,
        pred_vals=cols.pred_vals, pred_errs=cols.pred_errs,
        ba_input=batch.ba_input, cand_cond=cand_cond_c, cand_drcond=cand_drcond_c,
        cand_effect=batch.cand_effect, cand_pt=batch.cand_pt, cand_depth=batch.cand_depth,
        cand_valid=batch.cand_valid, scope_sp=batch.scope_sp,
        list_sids=cols.list_sids, list_states=cols.list_states,
        ts_his=cols.ts_his, ts_los=cols.ts_los, ts_states=cols.ts_states,
        now_hi=cols.now_hi, now_lo=cols.now_lo,
    )

    if not use_jax:
        from .. import native as native_mod

        native = native_mod.get()
        if native is not None and hasattr(native, "resolve_effects"):
            # fused C lattice: sat via the template groups as usual, then one
            # memory pass replaces ~40 small-array numpy kernels
            refs = Refs(np, cols.tags, cols.his, cols.los, cols.sids, cols.nans,
                        cols.pred_vals, cols.pred_errs,
                        list_sids=cols.list_sids, list_states=cols.list_states,
                        ts_his=cols.ts_his, ts_los=cols.ts_los, ts_states=cols.ts_states,
                        now_hi=cols.now_hi, now_lo=cols.now_lo)
            sat_arr = np.ascontiguousarray(
                _sat_groups(np, compiler, B, refs, variant=variant_key), dtype=bool
            )
            final = np.empty((BA, 4), dtype=np.int8)
            role_results = np.empty((BA, K, 2, 2), dtype=np.int8)
            win_j = np.empty((BA, K, 2), dtype=np.int8)
            native.resolve_effects(
                BA, K, J, D, sat_arr.shape[1],
                np.ascontiguousarray(batch.ba_input, dtype=np.int32),
                np.ascontiguousarray(cand_cond_c, dtype=np.int32),
                np.ascontiguousarray(cand_drcond_c, dtype=np.int32),
                np.ascontiguousarray(batch.cand_effect, dtype=np.int8),
                np.ascontiguousarray(batch.cand_pt, dtype=np.int8),
                np.ascontiguousarray(batch.cand_depth, dtype=np.int8),
                np.ascontiguousarray(batch.cand_valid, dtype=bool),
                np.ascontiguousarray(batch.scope_sp, dtype=np.int8),
                sat_arr,
                EFFECT_ALLOW_CODE, EFFECT_DENY_CODE, SP_OVERRIDE,
                memoryview(final), memoryview(role_results), memoryview(win_j),
            )
            return final, role_results, win_j, sat_arr, col_map

        final, role_results, win_j, sat_arr = _compute(
            np, compiler, K, J, D, variant=variant_key, **arrays
        )
        return (
            np.asarray(final), np.asarray(role_results), np.asarray(win_j),
            np.asarray(sat_arr), col_map,
        )

    import jax
    import jax.numpy as jnp

    padded = _pad_arrays(batch, cols, cand_cond_c, cand_drcond_c, B_pad, BA_pad)

    # multi-chip path: per-path arrays shard independently over the
    # mesh's batch axis; transfer fusion doesn't apply (and would fight
    # the shardings), so call _compute directly
    from ..parallel.mesh import shard_packed_arrays

    padded = shard_packed_arrays(padded, mesh)
    key = (B_pad, BA_pad, K, J, D, variant_key)
    fn = jit_cache.get(key)
    if fn is None:
        vt = variant_key  # bind the static variant into the trace
        fn = jax.jit(lambda **kw: _compute(jnp, compiler, K, J, D, variant=vt, **kw))
        jit_cache[key] = fn
        compilestats.stats().record_miss()
        # the first call runs trace + XLA compile synchronously
        final, role_results, win_j, sat_arr = compilestats.timed_first_call(
            f"B{B_pad}xBA{BA_pad}", fn, padded, trace_key=key
        )
    else:
        compilestats.stats().record_hit()
        final, role_results, win_j, sat_arr = fn(**padded)
    return (
        np.asarray(final)[:BA],
        np.asarray(role_results)[:BA],
        np.asarray(win_j)[:BA],
        np.asarray(sat_arr)[:B],
        col_map,
    )


def _pad_arrays(batch: PackedBatch, cols, cand_cond_c, cand_drcond_c, B_pad: int, BA_pad: int) -> dict:
    """Pad every batch-axis array to its shape bucket so jit traces are
    reused across batches."""

    def pad_b(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == B_pad:
            return a
        return np.concatenate([a, np.zeros((B_pad - a.shape[0],) + a.shape[1:], dtype=a.dtype)])

    def pad_ba(a: np.ndarray, fill=0) -> np.ndarray:
        if a.shape[0] == BA_pad:
            return a
        pad = np.full((BA_pad - a.shape[0],) + a.shape[1:], fill, dtype=a.dtype)
        return np.concatenate([a, pad])

    return dict(
        list_sids={p: pad_b(a) for p, a in cols.list_sids.items()},
        list_states={p: pad_b(a) for p, a in cols.list_states.items()},
        ts_his={p: pad_b(a) for p, a in cols.ts_his.items()},
        ts_los={p: pad_b(a) for p, a in cols.ts_los.items()},
        ts_states={p: pad_b(a) for p, a in cols.ts_states.items()},
        now_hi=cols.now_hi,
        now_lo=cols.now_lo,
        tags={p: pad_b(a) for p, a in cols.tags.items()},
        his={p: pad_b(a) for p, a in cols.his.items()},
        los={p: pad_b(a) for p, a in cols.los.items()},
        sids={p: pad_b(a) for p, a in cols.sids.items()},
        nans={p: pad_b(a) for p, a in cols.nans.items()},
        pred_vals={i: pad_b(a) for i, a in cols.pred_vals.items()},
        pred_errs={i: pad_b(a) for i, a in cols.pred_errs.items()},
        ba_input=pad_ba(batch.ba_input),
        cand_cond=pad_ba(cand_cond_c, -1),
        cand_drcond=pad_ba(cand_drcond_c, -1),
        cand_effect=pad_ba(batch.cand_effect),
        cand_pt=pad_ba(batch.cand_pt),
        cand_depth=pad_ba(batch.cand_depth, -1),
        cand_valid=pad_ba(batch.cand_valid),
        scope_sp=pad_b(batch.scope_sp),
    )


class _BufferPool:
    """Bounded free-lists of host staging buffers keyed by (shape, dtype).

    The padded transfer matrices built per device batch dominate the host
    dispatch path's allocations; batches in the same shape bucket need
    byte-identical buffers, so recycle them instead of reallocating. A
    buffer is leased at dispatch and released at finalize — by then the
    single output fetch has completed, so every host->device transfer that
    read the buffer is done (and outputs never alias inputs: nothing is
    donated)."""

    MAX_FREE = 4  # per key: bounds idle memory at ~one in-flight window

    def __init__(self):
        self._free: dict = {}
        self._lock = threading.Lock()

    def lease(self, shape, dtype) -> np.ndarray:
        key = (shape, np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                return free.pop()
        return np.empty(shape, dtype=dtype)

    def release(self, arrs) -> None:
        with self._lock:
            for a in arrs:
                free = self._free.setdefault((a.shape, a.dtype.str), [])
                if len(free) < self.MAX_FREE:
                    free.append(a)


_buffer_pool = _BufferPool()

_layout_memo: dict = {}


def _marshal_layout(cols, scope_D: int, has_now: bool) -> _StackLayout:
    """Memoized _StackLayout marshalling: the sorted row orders only depend
    on which columns the packer emitted, so key on the raw insertion-order
    key tuples — cheap to build — and sort once per distinct signature."""
    raw = (
        tuple(cols.tags), tuple(cols.ts_his), tuple(cols.list_sids),
        tuple(int(a.shape[1]) for a in cols.list_sids.values()),
        tuple(cols.pred_vals), scope_D, has_now,
    )
    lay = _layout_memo.get(raw)
    if lay is None:
        if len(_layout_memo) > 512:
            _layout_memo.clear()
        list_paths = tuple(sorted(cols.list_sids))
        lay = _StackLayout(
            tuple(sorted(cols.tags)),
            tuple(sorted(cols.ts_his)),
            list_paths,
            tuple(int(cols.list_sids[p].shape[1]) for p in list_paths),
            tuple(sorted(cols.pred_vals)),
            scope_D,
            has_now,
        )
        _layout_memo[raw] = lay
    return lay


def _fill_rows(dst: np.ndarray, rows: list, native) -> None:
    """Copy unpadded rows into the leading slots of dst's row stride,
    zeroing each padded tail. Rows pad along their leading axis, so for
    contiguous byte-compatible arrays this is a prefix memcpy + tail memset
    — one native call per column family instead of a Python loop."""
    if native is not None and all(
        r.flags["C_CONTIGUOUS"]
        and (r.dtype == dst.dtype or (dst.dtype == np.int8 and r.dtype == np.bool_))
        for r in rows
    ):
        try:
            native.stack_pad_rows(dst, rows)
            return
        except Exception:  # noqa: BLE001  (fall through to numpy)
            pass
    for i, r in enumerate(rows):
        nv = r.shape[0]
        dst[i, :nv] = r
        dst[i, nv:] = 0


def _pad_stack(batch: PackedBatch, cols, cand_cond_c, cand_drcond_c, B_pad: int, BA_pad: int):
    """Fused _pad_arrays + _stack_padded for the single-device path.

    The two-step version materializes a padded copy of every column (~100
    np.concatenate) and then stacks those copies into the transfer matrices
    (another full pass). Here each column's bytes are written exactly once,
    straight into pooled padded matrices. Returns (stacked, layout, leased);
    hand ``leased`` back to ``_buffer_pool`` once the device is done with
    the batch (see _device_finalize)."""
    from .. import native as native_mod

    native = native_mod.get()
    if native is not None and not hasattr(native, "stack_pad_rows"):
        native = None
    has_now = cols.now_hi is not None
    D = batch.scope_sp.shape[2]
    B = batch.scope_sp.shape[0]
    lay = _marshal_layout(cols, D, has_now)
    P, Tn, L, Q = len(lay.paths), len(lay.ts_paths), len(lay.list_paths), len(lay.pred_ids)
    leased: list[np.ndarray] = []

    def lease(shape, dtype):
        a = _buffer_pool.lease(shape, dtype)
        leased.append(a)
        return a

    n_i32 = 3 * P + 2 * Tn
    if n_i32:
        i32_cols = lease((n_i32, B_pad), np.int32)
        _fill_rows(
            i32_cols,
            [cols.his[p] for p in lay.paths]
            + [cols.los[p] for p in lay.paths]
            + [cols.sids[p] for p in lay.paths]
            + [cols.ts_his[p] for p in lay.ts_paths]
            + [cols.ts_los[p] for p in lay.ts_paths],
            native,
        )
    else:
        i32_cols = np.zeros((0, B_pad), dtype=np.int32)

    n_i8 = P + Tn + L + 2 * D
    if n_i8:
        i8_cols = lease((n_i8, B_pad), np.int8)
        if P + Tn + L:
            _fill_rows(
                i8_cols[: P + Tn + L],
                [cols.tags[p] for p in lay.paths]
                + [cols.ts_states[p] for p in lay.ts_paths]
                + [cols.list_states[p] for p in lay.list_paths],
                native,
            )
        if D:
            sp = i8_cols[P + Tn + L :]
            sp[:, :B] = batch.scope_sp.transpose(1, 2, 0).reshape(2 * D, B)
            sp[:, B:] = 0
    else:
        i8_cols = np.zeros((0, B_pad), dtype=np.int8)

    n_bool = P + 2 * Q
    if n_bool:
        bool_cols = lease((n_bool, B_pad), np.bool_)
        _fill_rows(
            bool_cols,
            [cols.nans[p] for p in lay.paths]
            + [cols.pred_vals[q] for q in lay.pred_ids]
            + [cols.pred_errs[q] for q in lay.pred_ids],
            native,
        )
    else:
        bool_cols = np.zeros((0, B_pad), dtype=bool)

    if L:
        wmax = max(lay.list_widths)
        lists = lease((L, B_pad, wmax), np.int32)
        for i, p in enumerate(lay.list_paths):
            a = cols.list_sids[p]
            nb, w = a.shape
            lists[i, :nb, :w] = a
            if w < wmax:
                lists[i, :nb, w:] = 0
            if nb < B_pad:
                lists[i, nb:] = 0
    else:
        lists = np.zeros((0, B_pad, 1), dtype=np.int32)

    BA = cand_cond_c.shape[0]
    cand_i32 = lease((2, BA_pad) + cand_cond_c.shape[1:], np.int32)
    cand_i32[0, :BA] = cand_cond_c
    cand_i32[1, :BA] = cand_drcond_c
    cand_i32[:, BA:] = -1  # pad_ba fill for cond ids
    cand_i8 = lease((4, BA_pad) + batch.cand_effect.shape[1:], np.int8)
    cand_i8[0, :BA] = batch.cand_effect
    cand_i8[1, :BA] = batch.cand_pt
    cand_i8[2, :BA] = batch.cand_depth
    cand_i8[3, :BA] = batch.cand_valid
    cand_i8[:, BA:] = 0
    cand_i8[2, BA:] = -1  # pad_ba fill for depth

    ba_input = lease((BA_pad,) + batch.ba_input.shape[1:], batch.ba_input.dtype)
    ba_input[:BA] = batch.ba_input
    ba_input[BA:] = 0

    now = (
        np.asarray([int(cols.now_hi), int(cols.now_lo)], dtype=np.int32)
        if has_now
        else np.zeros(2, dtype=np.int32)
    )
    stacked = dict(
        i32_cols=i32_cols,
        i8_cols=i8_cols,
        bool_cols=bool_cols,
        lists=lists,
        cand_i32=cand_i32,
        cand_i8=cand_i8,
        ba_input=ba_input,
        now=now,
    )
    return stacked, lay, leased


class _DeviceHandle:
    """An in-flight device batch: the queued output array (device->host copy
    already started) plus everything needed to slice results back apart.
    ``ready`` short-circuits degenerate batches that never touch the device."""

    __slots__ = ("ready", "out", "BA", "B", "K", "BA_pad", "B_pad", "col_map", "leased")

    def __init__(self):
        self.ready = None
        self.out = None
        self.leased = ()


def _device_dispatch(lt: LoweredTable, batch: PackedBatch, jit_cache: dict) -> _DeviceHandle:
    """Queue one packed batch on the single device WITHOUT blocking.

    FUSE TRANSFERS: every host->device put and device->host fetch pays the
    interconnect's per-transfer latency (on a tunneled chip, milliseconds
    each), and the naive call ships ~5 arrays per column path (100+ puts)
    and fetches 4 results. Stack all per-path columns into a handful of
    typed matrices host-side — slicing them back apart INSIDE the traced
    graph is free (XLA fuses) — and pack every result into one int8 vector
    on device, so a batch costs ~8 puts + 1 fetch regardless of how many
    columns the table has.

    HIDE LATENCY: jax dispatch is async — ``fn(**stacked)`` returns before
    the device runs — and the device->host copy is started eagerly with
    ``copy_to_host_async``, so the caller can pack/assemble other batches
    while this one's transfers and compute are in flight; only
    ``_device_finalize`` blocks (VERDICT r4 item 1).
    """
    import jax
    import jax.numpy as jnp

    compiler = lt.compiler
    K, J, D = batch.K, batch.J, batch.D
    BA = batch.cand_cond.shape[0]
    B = batch.columns.size
    compiler.build_groups()
    C = len(compiler.kernels)

    h = _DeviceHandle()
    if BA == 0:
        h.ready = _zero_result(B, K, C)
        return h

    B_pad = _next_bucket(B)
    BA_pad = _next_bucket(BA)
    variant_key = _select_variant(lt, batch, jit_cache)

    col_map, cand_cond_c, cand_drcond_c = _variant_remap(
        variant_key, compiler, C, batch.cand_cond, batch.cand_drcond
    )
    stacked, layout, leased = _pad_stack(
        batch, batch.columns, cand_cond_c, cand_drcond_c, B_pad, BA_pad
    )
    key = (B_pad, BA_pad, K, J, D, variant_key, layout.sig)
    fn = jit_cache.get(key)
    if fn is None:
        vt = variant_key
        lay = layout

        def run(**kw):
            parts = _unstack_padded(jnp, lay, kw)
            final, role_results, win_j, sat_arr = _compute(
                jnp, compiler, K, J, D, variant=vt, **parts
            )
            out = jnp.concatenate(
                [
                    final.reshape(BA_pad, -1).astype(jnp.int8),
                    role_results.reshape(BA_pad, -1).astype(jnp.int8),
                    win_j.reshape(BA_pad, -1).astype(jnp.int8),
                ],
                axis=1,
            )
            return jnp.concatenate(
                [out.ravel(), sat_arr.astype(jnp.int8).ravel()]
            )

        fn = jax.jit(run)
        jit_cache[key] = fn
        compilestats.stats().record_miss()
        # jit defers trace+compile to the first call: time it there so the
        # compile histogram sees the real XLA cost (dispatch of the compiled
        # program stays async and costs microseconds by comparison)
        out = compilestats.timed_first_call(
            f"B{B_pad}xBA{BA_pad}", fn, stacked, trace_key=key
        )
    else:
        compilestats.stats().record_hit()
        out = fn(**stacked)
    try:
        out.copy_to_host_async()  # start the (single) fetch immediately
    except (AttributeError, RuntimeError):
        pass
    h.out = out
    h.BA, h.B, h.K = BA, B, K
    h.BA_pad, h.B_pad = BA_pad, B_pad
    h.col_map = col_map
    h.leased = leased
    return h


def _device_finalize(h: _DeviceHandle):
    """Block on one in-flight batch and slice its results apart."""
    if h.ready is not None:
        return h.ready
    K, BA = h.K, h.BA
    flat = np.asarray(h.out)  # ONE device->host fetch
    if h.leased:
        # the output is materialized, so every transfer that read the staging
        # buffers has completed — recycle them for the next batch
        _buffer_pool.release(h.leased)
        h.leased = ()
    per_ba = 4 + K * 2 * 2 + K * 2
    cut = h.BA_pad * per_ba
    out_mat = flat[:cut].reshape(h.BA_pad, per_ba)
    A_sat = max((flat.size - cut) // h.B_pad, 1)
    final = out_mat[:BA, :4]
    role_results = out_mat[:BA, 4 : 4 + K * 4].reshape(BA, K, 2, 2)
    win_j = out_mat[:BA, 4 + K * 4 :].reshape(BA, K, 2)
    sat_arr = flat[cut:].reshape(h.B_pad, A_sat)[: h.B].astype(bool)
    return final, role_results, win_j, sat_arr, h.col_map


class CheckTicket:
    """An in-flight batch submitted via TpuEvaluator.submit."""

    __slots__ = ("parts", "ready", "params", "pack_s", "occupancy", "layout_key", "padded_rows")

    def __init__(self):
        self.parts = None  # [(PackedBatch, _DeviceHandle)]
        self.ready = None
        self.params = None
        # device-economics attribution read by the serving batcher: host
        # pack time, real/padded row ratio, and the padded layout shape
        self.pack_s = 0.0
        self.occupancy = None  # None = no packed device layout (sync path)
        self.layout_key = None
        self.padded_rows = None


class TpuEvaluator:
    """Batched evaluator over a lowered rule table.

    Drop-in for the engine's ``tpu_evaluator`` hook: bit-exact effects vs the
    CPU oracle, with automatic per-input oracle fallback for anything outside
    device coverage.
    """

    def __init__(
        self,
        rule_table: RuleTable,
        globals_: Optional[dict[str, Any]] = None,
        schema_mgr: Any = None,
        max_roles: int = 8,
        max_candidates: int = 32,
        max_depth: int = 8,
        use_jax: bool = True,
        min_device_batch: int = 16,
        mesh=None,
        pipeline_chunk: int = 4096,
        streaming_threshold: int = 1024,
        inflight_depth: int = 3,
        device=None,
        shard_id: Optional[int] = None,
        _lowered: Optional[LoweredTable] = None,
    ):
        self.rule_table = rule_table
        self.schema_mgr = schema_mgr
        # lowering is the expensive part of construction; shard clones pass
        # the shared LoweredTable in so a pool of N evaluators lowers ONCE
        self.lowered = _lowered if _lowered is not None else lower_table(rule_table, globals_)
        self.packer = Packer(self.lowered, max_roles=max_roles, max_candidates=max_candidates, max_depth=max_depth)
        self.use_jax = use_jax
        self.min_device_batch = min_device_batch
        self.mesh = mesh
        # pin this evaluator's dispatches to one jax device (a shard of the
        # pool); None = jax's default device (single-evaluator serving)
        self.device = device
        self.shard_id = shard_id
        self.pipeline_chunk = pipeline_chunk
        # batch size at which check() switches to the chunked double-buffered
        # pipeline; 0 disables. Small enough that cross-request batches from
        # the serving path engage it, not just bench-sized megabatches.
        self.streaming_threshold = streaming_threshold
        # device batches kept in flight by the pipelined path
        self.inflight_depth = max(1, int(inflight_depth))
        if use_jax:
            from .jitcache import enable as _enable_jit_cache

            _enable_jit_cache()  # persistent XLA cache: restart = load, not recompile
        self.stats = {"device_inputs": 0, "oracle_inputs": 0, "trivial_inputs": 0}
        self._jit_cache: dict = {}
        self._dr_table_cache: dict = {}
        self._roles_cache: dict = {}
        self._edr_memo: dict = {}
        self._assemble_memo: dict = {}
        self._dr_cids_cache: dict = {}
        self._dr_cids_canon: dict[bytes, "np.ndarray"] = {}

    def refresh(self) -> None:
        """Re-lower after a policy reload (storage event hook)."""
        self.lowered.refresh()
        self.invalidate()

    def invalidate(self) -> None:
        """Drop every per-instance cache derived from the lowered table.
        ``refresh()`` re-lowers and then calls this; shard clones sharing the
        lowered table call only this after the owner re-lowered."""
        self.packer.invalidate()
        self._jit_cache.clear()
        self._dr_table_cache.clear()
        self._roles_cache.clear()
        self._edr_memo.clear()
        self._assemble_memo.clear()
        self._dr_cids_cache.clear()
        self._dr_cids_canon.clear()

    def shard_clone(self, devices, shard_id: int) -> "TpuEvaluator":
        """A pool-shard evaluator over the SAME lowered rule table.

        The clone shares the read-only artifacts (rule table, lowered
        tables, schema manager) but owns everything mutated on the serving
        path — packer, jit cache, memo caches, stats — so each shard's
        worker thread runs lock-free against its siblings. ``devices`` is
        the shard's placement from ``parallel.mesh.shard_devices``: one
        device pins via ``jax.default_device``, several become a per-shard
        data-parallel mesh slice."""
        device = None
        mesh = None
        if devices is not None:
            devs = list(devices)
            if len(devs) == 1:
                device = devs[0]
            elif len(devs) > 1:
                from ..parallel.mesh import make_mesh_for

                mesh = make_mesh_for(devs)
        clone = TpuEvaluator(
            self.rule_table,
            schema_mgr=self.schema_mgr,
            max_roles=self.packer.K,
            max_candidates=self.packer.J,
            max_depth=self.packer.D,
            use_jax=self.use_jax,
            min_device_batch=self.min_device_batch,
            mesh=mesh,
            pipeline_chunk=self.pipeline_chunk,
            streaming_threshold=self.streaming_threshold,
            inflight_depth=self.inflight_depth,
            device=device,
            shard_id=shard_id,
            _lowered=self.lowered,
        )
        return clone

    def _device_scope(self):
        """Context manager pinning jax dispatch to this shard's device."""
        if self.device is None:
            import contextlib

            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)

    def check(self, inputs: list[T.CheckInput], params: Optional[T.EvalParams] = None) -> list[T.CheckOutput]:
        params = params or T.EvalParams()
        if len(inputs) < self.min_device_batch:
            # device dispatch has a fixed cost; tiny batches are faster on
            # the serial oracle (the reference's parallelismThreshold analogue)
            self.stats["oracle_inputs"] += len(inputs)
            return [check_input(self.rule_table, i, params, self.schema_mgr) for i in inputs]
        if (
            self.use_jax
            and self.mesh is None
            and self.pipeline_chunk > 0
            and self.streaming_threshold > 0
            and len(inputs) >= self.streaming_threshold
        ):
            return self._check_pipelined(inputs, params)
        batch = self.packer.pack(inputs, params)
        with self._device_scope():
            final, role_results, win_j, sat_arr, col_map = _device_eval(
                self.lowered, batch, use_jax=self.use_jax, jit_cache=self._jit_cache, mesh=self.mesh
            )
        return self._assemble_batch(batch, final, role_results, win_j, sat_arr, col_map, params)

    def submit(self, inputs: list[T.CheckInput], params: Optional[T.EvalParams] = None) -> "CheckTicket":
        """Queue one batch WITHOUT waiting for its results.

        The device work (transfers + compute + result copy) runs
        asynchronously; the caller keeps packing/submitting further batches
        — or assembling earlier ones via :meth:`collect` — while this one
        is in flight. This is how a serving loop hides the interconnect's
        per-batch latency: N batches in flight amortize transfer latency
        the way the reference's ghz load (hundreds of concurrent requests)
        amortizes per-request overhead. Non-device paths (numpy backend,
        mesh, tiny batches) evaluate synchronously and the ticket is
        already complete."""
        params = params or T.EvalParams()
        t = CheckTicket()
        t.params = params
        if (
            not self.use_jax
            or self.mesh is not None
            or len(inputs) < self.min_device_batch
        ):
            t.ready = self.check(inputs, params)
            return t
        # split oversized batches along the same chunk boundaries as
        # check(), so streaming reuses the already-traced shape buckets
        # instead of compiling a monolithic one
        chunks = self._chunk_inputs(inputs)
        t.parts = []
        with start_span("batch.pack", inputs=len(inputs), chunks=len(chunks)), self._device_scope():
            for ch in chunks:
                p0 = time.perf_counter()
                batch = self.packer.pack(ch, params)
                t.pack_s += time.perf_counter() - p0
                t.parts.append((batch, _device_dispatch(self.lowered, batch, self._jit_cache)))
        real = sum(h.B for _, h in t.parts)
        padded = sum(h.B_pad for _, h in t.parts)
        if padded:
            t.occupancy = real / padded
            t.padded_rows = padded
            t.layout_key = "+".join(f"B{h.B_pad}xBA{h.BA_pad}" for _, h in t.parts)
        return t

    def collect(self, ticket: "CheckTicket") -> list[T.CheckOutput]:
        """Block on one submitted batch and assemble its CheckOutputs."""
        if ticket.ready is not None:
            return ticket.ready
        out: list[T.CheckOutput] = []
        for batch, handle in ticket.parts:
            out.extend(self._assemble_batch(batch, *_device_finalize(handle), ticket.params))
        ticket.ready = out
        ticket.parts = None
        return out

    def _chunk_inputs(self, inputs: list[T.CheckInput]) -> list[list[T.CheckInput]]:
        """Pipeline-chunk boundaries shared by check() and submit(): fixed
        pipeline_chunk-sized slices, with a tail smaller than the device
        threshold riding with its neighbor rather than paying a dispatch
        (or an oracle walk) of its own.

        Batches below 2x pipeline_chunk would land in a single chunk and get
        no overlap at all, so the chunk shrinks to split them into roughly
        ``inflight_depth`` pieces — rounded to the next pow2 bucket so the
        shrunk chunks reuse already-traced jit shapes (B_pad buckets are
        pow2 too)."""
        chunk = self.pipeline_chunk if self.pipeline_chunk > 0 else len(inputs)
        n = len(inputs)
        if n < 2 * chunk:
            depth = max(2, self.inflight_depth)
            target = (n + depth - 1) // depth
            chunk = min(chunk, _next_bucket(target, max(self.min_device_batch, 16)))
        chunks = [inputs[b : b + chunk] for b in range(0, len(inputs), chunk)]
        if len(chunks) > 1 and len(chunks[-1]) < self.min_device_batch:
            chunks[-2] = chunks[-2] + chunks[-1]
            chunks.pop()
        return chunks

    def _check_pipelined(self, inputs: list[T.CheckInput], params: T.EvalParams) -> list[T.CheckOutput]:
        """Chunked double-buffered device pipeline (VERDICT r4 item 1).

        The serial path pays pack -> put -> compute -> fetch -> assemble
        per batch with the device idle during host work and vice versa.
        Here the batch is split into fixed-size chunks; each chunk's device
        work is QUEUED asynchronously (`_device_dispatch` returns before
        the device runs, with the result copy already started), so chunk
        N's transfers/compute overlap chunk N-1's assembly and chunk N+1's
        packing. Wall-clock approaches max(host work, device work) instead
        of their sum."""
        outputs: list[T.CheckOutput] = []
        chunks = self._chunk_inputs(inputs)
        inflight: list[tuple[PackedBatch, _DeviceHandle]] = []
        for ci, ch in enumerate(chunks):
            batch = self.packer.pack(ch, params)
            with self._device_scope():
                h = _device_dispatch(self.lowered, batch, self._jit_cache)
            inflight.append((batch, h))
            if len(inflight) >= self.inflight_depth:
                b, hh = inflight.pop(0)
                outputs.extend(
                    self._assemble_batch(b, *_device_finalize(hh), params)
                )
        for b, hh in inflight:
            outputs.extend(self._assemble_batch(b, *_device_finalize(hh), params))
        return outputs

    def _assemble_batch(
        self, batch: PackedBatch, final, role_results, win_j, sat_arr, col_map, params
    ) -> list[T.CheckOutput]:
        # one contiguous int8 matrix of all per-(input,action) decision state,
        # exported to bytes ONCE; the memo key for input bi is then a pure
        # bytes slice (no per-input ndarray views or copies)
        dec_buf = None
        dec_w = 0
        if not self.lowered.has_outputs and final.shape[0]:
            BA = final.shape[0]
            dec_bytes = np.concatenate(
                [
                    np.asarray(final).reshape(BA, -1),
                    np.asarray(role_results).reshape(BA, -1),
                    np.asarray(win_j).reshape(BA, -1),
                ],
                axis=1,
            )
            dec_w = dec_bytes.shape[1] * dec_bytes.itemsize
            dec_buf = dec_bytes.tobytes()
        dr_bits_by_bi = (
            self._batch_dr_bits(batch, sat_arr, col_map, params) if dec_buf is not None else None
        )

        outputs: list[T.CheckOutput] = []
        for bi, plan in enumerate(batch.plans):
            inp = plan.input
            if plan.oracle:
                self.stats["oracle_inputs"] += 1
                outputs.append(check_input(self.rule_table, inp, params, self.schema_mgr))
                continue
            if plan.trivial:
                self.stats["trivial_inputs"] += 1
                out = T.CheckOutput(request_id=inp.request_id, resource_id=inp.resource.id)
                for action in inp.actions:
                    out.actions[action] = T.ActionEffect(
                        effect=T.EFFECT_DENY, policy=T.NO_POLICY_MATCH, source="device"
                    )
                outputs.append(out)
                continue
            self.stats["device_inputs"] += 1
            # schema validation runs on host per input, mirroring the
            # oracle's pre-loop check (check.go:129-151); a reject means
            # every action denies without evaluating rules
            vr_errors: list = []
            if self.schema_mgr is not None:
                vr_errors, reject = self.schema_mgr.validate_check_input(
                    self.rule_table.get_schema(plan.resource_policy_fqn), inp
                )
                if reject:
                    out = T.CheckOutput(request_id=inp.request_id, resource_id=inp.resource.id)
                    for action in inp.actions:
                        out.actions[action] = T.ActionEffect(
                            effect=T.EFFECT_DENY, policy=plan.resource_policy_key, source="device"
                        )
                    out.validation_errors = vr_errors
                    outputs.append(out)
                    continue
            key = None
            if not vr_errors and dec_buf is not None:
                dr_bits = dr_bits_by_bi.get(bi)
                if dr_bits is not None:
                    start, end = plan.ba_range
                    key = (plan.sig, dec_buf[start * dec_w : end * dec_w], dr_bits)
            if key is not None:
                hit = self._assemble_memo.get(key)
                if hit is not None:
                    outputs.append(_clone_output(hit, inp))
                    continue
            out = self._assemble(plan, bi, batch, final, role_results, win_j, sat_arr, col_map, params)
            out.validation_errors = vr_errors
            if key is not None:
                if len(self._assemble_memo) > 65536:
                    self._assemble_memo.clear()
                self._assemble_memo[key] = out
            outputs.append(out)
        return outputs

    def _batch_dr_bits(self, batch: PackedBatch, sat_arr, col_map, params) -> dict[int, bytes]:
        """Per-input derived-role condition bits (part of the assembly memo
        key: inputs with the same shape sig, decision rows and DR bits
        assemble to identical outputs modulo ids). Gathered per shape group
        in one fancy-index instead of per input. Inputs whose scope chain has
        host-evaluated DR conditions are absent (their outcome depends on raw
        attrs — not memoizable)."""
        plans = batch.plans
        out: dict[int, bytes] = {}
        cache = self._dr_cids_cache
        # group by the CONTENT of the cid vector, not the shape sig — many
        # sigs (same chain, different action sets) share one gather
        groups: dict[int, list[int]] = {}
        arr_by_gid: dict[int, np.ndarray] = {}
        canon_by_content: dict[bytes, np.ndarray] = self._dr_cids_canon
        for bi, plan in enumerate(plans):
            if plan.oracle or plan.trivial:
                continue
            cids = cache.get(plan.sig)
            if cids is None:
                inp = plan.input
                version = T.effective_version(inp.resource.policy_version, params)
                all_cids: list[int] = []
                for scope in plan.resource_scopes:
                    for _, _, cid, dr in self._dr_table(inp.resource.kind, version, scope):
                        if cid >= 0:
                            all_cids.append(cid)
                        elif dr.condition is not None:
                            all_cids = None  # host-evaluated DR: not memoizable
                            break
                    if all_cids is None:
                        break
                if all_cids is None:
                    cids = "host"
                else:
                    arr = np.asarray(all_cids, dtype=np.int64)
                    cids = canon_by_content.setdefault(arr.tobytes(), arr)
                # sigs regenerate after packer shape-memo evictions, so this
                # cache must be bounded too (canon stays content-bounded)
                if len(cache) > 65536:
                    cache.clear()
                cache[plan.sig] = cids
            if isinstance(cids, str):
                continue
            if not cids.size:
                out[bi] = b""
                continue
            gid = id(cids)
            g = groups.get(gid)
            if g is None:
                groups[gid] = [bi]
                arr_by_gid[gid] = cids
            else:
                g.append(bi)
        for gid, bis in groups.items():
            cids = arr_by_gid[gid]
            rows = np.ascontiguousarray(sat_arr[np.asarray(bis, dtype=np.int64)][:, col_map[cids]])
            w = rows.shape[1] * rows.itemsize
            buf = rows.tobytes()
            for i, bi in enumerate(bis):
                out[bi] = buf[i * w : (i + 1) * w]
        return out

    # -- host assembly -----------------------------------------------------

    def _assemble(self, plan, bi, batch: PackedBatch, final, role_results, win_j, sat_arr, col_map, params) -> T.CheckOutput:
        inp = plan.input
        out = T.CheckOutput(request_id=inp.request_id, resource_id=inp.resource.id)
        start, end = plan.ba_range
        action_to_ba = {batch.ba_action[ci]: ci for ci in range(start, end)}

        processed_scopes: set[int] = set()  # resource-chain depths processed
        output_entries: list[T.OutputEntry] = []
        effective_policies: dict[str, Any] = {}
        ec_cache: dict[Any, Any] = {}

        def eval_ctx():
            if "ec" not in ec_cache:
                request, principal, resource = build_request_messages(inp)
                ec_cache["ec"] = EvalContext(params, request, principal, resource)
            return ec_cache["ec"]

        emit_outputs = self.lowered.has_outputs

        def bookkeep_depth(depth: int):
            """EDR bookkeeping for a newly visited resource-chain scope: the
            current context is REPLACED with that scope's activated set, and
            later rule visits — including other roles re-walking already
            processed scopes — keep whatever context is current, mirroring
            the oracle's processedScopedDerivedRoles statefulness
            (check.go:231-271 / check.py:321-341). Tables without outputs
            never read the context (only processed_scopes feeds
            effective_derived_roles), so skip the per-input EvalContext."""
            if depth in processed_scopes:
                return
            processed_scopes.add(depth)
            if not emit_outputs:
                return
            edr = self._edr_at_depth(plan, bi, depth, params, eval_ctx, sat_arr, col_map)
            ec_cache["cur"] = eval_ctx().with_effective_derived_roles(edr)

        def current_ctx():
            return ec_cache.get("cur") or eval_ctx()

        for action in inp.actions:
            ci = action_to_ba.get(action)
            if ci is None:
                out.actions[action] = T.ActionEffect(
                    effect=T.EFFECT_DENY, policy=T.NO_POLICY_MATCH, source="device"
                )
                continue
            code, pt, depth, k = (int(x) for x in final[ci])

            chain = plan.principal_scopes if pt == PT_PRINCIPAL else plan.resource_scopes
            main_key = plan.principal_policy_key if pt == PT_PRINCIPAL else plan.resource_policy_key
            exists = plan.scoped_principal_exists if pt == PT_PRINCIPAL else plan.scoped_resource_exists

            if code in (CODE_ALLOW, CODE_DENY):
                # winning-rule attribution (ISSUE 20): win_j carries the
                # first-match j for BOTH effects now, so the decision names
                # the rule row that produced it
                policy = main_key if (code == CODE_ALLOW or exists) else T.NO_POLICY_MATCH
                matched_rule, row_id = "", -1
                wj = int(win_j[ci, k, pt])
                if 0 <= wj:
                    entry = self._entry_at(batch, ci, k, wj)
                    if entry is not None:
                        if code == CODE_DENY and entry.from_role_policy:
                            policy = namer.policy_key_from_fqn(entry.origin_fqn)
                        if entry.row is not None:
                            matched_rule = self._rule_src(entry)
                            row_id = entry.row.id
                ae = T.ActionEffect(
                    effect=T.EFFECT_ALLOW if code == CODE_ALLOW else T.EFFECT_DENY,
                    policy=policy,
                    scope=chain[depth] if depth < len(chain) else "",
                    matched_rule=matched_rule,
                    rule_row_id=row_id,
                    source="device",
                )
            else:
                # NO_MATCH → default deny (resource-pass attribution)
                policy = plan.resource_policy_key if plan.scoped_resource_exists else T.NO_POLICY_MATCH
                ae = T.ActionEffect(effect=T.EFFECT_DENY, policy=policy, source="device")
            out.actions[action] = ae

            # reconstruct processed resource-chain depths + emitted outputs
            self._reconstruct(
                plan, bi, batch, ci, role_results, win_j, sat_arr, col_map,
                output_entries, eval_ctx, bookkeep_depth, current_ctx,
                effective_policies,
            )

        # effective derived roles for processed resource scopes
        if processed_scopes:
            out.effective_derived_roles = self._effective_derived_roles(
                plan, bi, sorted(processed_scopes), params, eval_ctx, sat_arr, col_map
            )
        out.outputs = output_entries
        out.effective_policies = {
            namer.policy_key_from_fqn(fqn): attrs for fqn, attrs in effective_policies.items()
        }
        return out

    def _entry_at(self, batch: PackedBatch, ci: int, k: int, j: int):
        per_k = batch.cand_entries[ci]
        if k < len(per_k) and j < len(per_k[k]):
            return per_k[k][j]
        return None

    def _reconstruct(self, plan, bi, batch, ci, role_results, win_j, sat_arr, col_map, output_entries, eval_ctx, bookkeep_depth, current_ctx, effective_policies):
        """Mirror the visit order: per role, walk resource-chain depths in
        order, bookkeeping each newly visited scope's derived roles BEFORE
        evaluating that scope's rule outputs, so outputs see the same
        (stateful) runtime.effectiveDerivedRoles context as the oracle."""
        inp = plan.input
        sat_b = sat_arr[bi]
        # principal pass decided?
        p_code = int(role_results[ci, 0, PT_PRINCIPAL, 0])
        passes = [(PT_PRINCIPAL, [0])]
        if p_code == CODE_NO_MATCH:
            ks = list(range(min(len(plan.roles), batch.K)))
            passes.append((PT_RESOURCE, ks))

        emit_outputs = self.lowered.has_outputs
        for pt, ks in passes:
            chain = plan.principal_scopes if pt == PT_PRINCIPAL else plan.resource_scopes
            if not emit_outputs and pt == PT_RESOURCE:
                # no outputs anywhere in the table: only the processed-depth
                # bookkeeping and policy provenance matter; the max depth
                # over roles covers both
                overall = -1
                last_k = 0
                for k in ks:
                    code = int(role_results[ci, k, pt, 0])
                    depth = int(role_results[ci, k, pt, 1])
                    overall = max(overall, min(depth, len(chain) - 1) if code != CODE_NO_MATCH else len(chain) - 1)
                    last_k = k
                    if code == CODE_ALLOW:
                        break
                for d in range(0, overall + 1):
                    bookkeep_depth(d)
                for k in ks[: last_k + 1]:
                    entries = batch.cand_entries[ci][k] if k < len(batch.cand_entries[ci]) else []
                    code = int(role_results[ci, k, pt, 0])
                    depth = int(role_results[ci, k, pt, 1])
                    maxd = min(depth, len(chain) - 1) if code != CODE_NO_MATCH else len(chain) - 1
                    self._collect_effective(entries, pt, maxd, effective_policies)
                continue
            for k in ks:
                code = int(role_results[ci, k, pt, 0])
                depth = int(role_results[ci, k, pt, 1])
                max_depth = min(depth, len(chain) - 1) if code != CODE_NO_MATCH else len(chain) - 1
                entries = batch.cand_entries[ci][k] if k < len(batch.cand_entries[ci]) else []
                wj = int(win_j[ci, k, pt]) if code == CODE_DENY else -1
                self._collect_effective(entries, pt, max_depth, effective_policies)
                for d in range(0, max_depth + 1):
                    if pt == PT_RESOURCE:
                        bookkeep_depth(d)
                    if not emit_outputs:
                        continue
                    for j, e in enumerate(entries):
                        if e is None or e.pt != pt or e.depth != d:
                            continue
                        if code == CODE_DENY and e.depth == depth and wj >= 0 and j > wj:
                            continue
                        if not e.has_output or e.row is None or e.row.emit_output is None:
                            continue
                        sat = True
                        if e.cond_id >= 0:
                            sat = bool(sat_b[col_map[e.cond_id]])
                        if e.drcond_id >= 0 and not bool(sat_b[col_map[e.drcond_id]]):
                            continue  # derived-role condition unmet: rule skipped entirely
                        emit = e.row.emit_output
                        expr = emit.rule_activated if sat else emit.condition_not_met
                        if expr is None:
                            continue
                        ec = current_ctx() if pt == PT_RESOURCE else eval_ctx()
                        constants, variables = {}, {}
                        if e.row.params is not None:
                            constants = e.row.params.constants
                            variables = ec.evaluate_variables(constants, e.row.params.ordered_variables)
                        src = self._rule_src(e)
                        output_entries.append(
                            ec.evaluate_output(e.row.name, src, batch.ba_action[ci], expr, constants, variables)
                        )
                # stop visiting further roles if this role allowed
                if code == CODE_ALLOW:
                    break

    def _collect_effective(self, entries, pt, max_depth, effective_policies) -> None:
        """Policy provenance for every binding in a visited scope — the
        oracle records source attributes for all QUERIED bindings, satisfied
        or not (check.py:356-358 / check.go effectivePolicies)."""
        rt = self.rule_table
        for e in entries:
            if e is None or e.pt != pt or e.depth > max_depth:
                continue
            if e.origin_fqn in effective_policies:
                continue
            for f, attrs in rt.get_chain_source_attributes(e.origin_fqn).items():
                effective_policies.setdefault(f, dict(attrs))

    def _rule_src(self, e) -> str:
        meta = self.rule_table.get_meta(e.origin_fqn)
        b = e.row
        if meta is None:
            return f"{namer.policy_key_from_fqn(e.origin_fqn)}#{b.name}"
        if meta.kind == "PRINCIPAL":
            fqn = namer.principal_policy_fqn(meta.name, meta.version, b.scope)
        elif meta.kind == "RESOURCE":
            fqn = namer.resource_policy_fqn(meta.name, meta.version, b.scope)
        else:
            fqn = namer.role_policy_fqn(meta.name, meta.version, b.scope)
        return f"{namer.policy_key_from_fqn(fqn)}#{b.name}"

    def _dr_table(self, kind: str, version: str, scope: str):
        """Cached per-(kind, version, scope): [(name, parent_roles, cond_id, dr)]."""
        key = (kind, version, scope)
        hit = self._dr_table_cache.get(key)
        if hit is None:
            drs = self.rule_table.get_derived_roles(namer.resource_policy_fqn(kind, version, scope))
            hit = []
            if drs:
                for name, dr in drs.items():
                    cid = self.lowered.dr_cond_ids.get(id(dr), -1)
                    device_ok = cid >= 0 and self.lowered.compiler.kernels[cid].emit is not None
                    hit.append((name, dr.parent_roles, cid if device_ok else -1, dr))
            self._dr_table_cache[key] = hit
        return hit

    def _edr_at_depth(self, plan, bi, depth, params, eval_ctx, sat_arr, col_map) -> set[str]:
        """Derived roles activated at one resource-chain scope depth.

        Memoized per (scope fqn, principal roles, device condition bits) —
        inputs sharing role sets and condition outcomes (the common case in
        large batches) reuse the set. Tables with host-evaluated derived-role
        conditions bypass the cache (their outcome depends on raw attrs)."""
        inp = plan.input
        if depth >= len(plan.resource_scopes):
            return set()
        resource_version = T.effective_version(inp.resource.policy_version, params)
        rt = self.rule_table
        roles_key = (T.effective_scope(inp.resource.scope, params), tuple(inp.principal.roles))
        all_roles = self._roles_cache.get(roles_key)
        if all_roles is None:
            all_roles = set(rt.idx.add_parent_roles([roles_key[0]], list(inp.principal.roles)))
            if len(self._roles_cache) > 65536:
                self._roles_cache.clear()
            self._roles_cache[roles_key] = all_roles
        edr: set[str] = set()
        sat_b = sat_arr[bi]
        table = self._dr_table(inp.resource.kind, resource_version, plan.resource_scopes[depth])
        cacheable = all(cid >= 0 or dr.condition is None for _, _, cid, dr in table)
        if cacheable:
            bits = tuple(bool(sat_b[col_map[cid]]) for _, _, cid, _ in table if cid >= 0)
            mkey = (inp.resource.kind, resource_version, plan.resource_scopes[depth], roles_key, bits)
            hit = self._edr_memo.get(mkey)
            if hit is not None:
                return hit
        else:
            mkey = None
        for name, parent_roles, cid, dr in table:
            if name in edr:
                continue
            # literal "*" parent role matches any principal role
            # (internal/utils.go:56-68), mirroring the oracle
            if "*" not in parent_roles and not (parent_roles & all_roles):
                continue
            if dr.condition is None:
                edr.add(name)
            elif cid >= 0:
                if bool(sat_b[col_map[cid]]):
                    edr.add(name)
            else:
                # condition outside device coverage: host-evaluate
                ec = eval_ctx()
                variables = ec.evaluate_variables(dr.params.constants, dr.params.ordered_variables)
                if ec.satisfies_condition(dr.condition, dr.params.constants, variables):
                    edr.add(name)
        if mkey is not None:
            if len(self._edr_memo) > 65536:
                self._edr_memo.clear()
            self._edr_memo[mkey] = edr
        return edr

    def _effective_derived_roles(self, plan, bi, depths, params, eval_ctx, sat_arr, col_map) -> list[str]:
        edr: set[str] = set()
        for d in depths:
            edr |= self._edr_at_depth(plan, bi, d, params, eval_ctx, sat_arr, col_map)
        return sorted(edr)
