"""TPU evaluation backend: lowering, batched condition kernels, effect lattice.

This is the subsystem that replaces the reference's per-request hot loop
(internal/ruletable/check.go:183-438) with batched device evaluation:

- ``condcompile``  CEL condition AST → vectorized JAX kernel over SoA
                   attribute columns, with an (value, error) lattice matching
                   cel-go error-absorption semantics; unsupported fragments
                   become host-evaluated predicate columns.
- ``lowering``     rule table → static row metadata + interned condition set.
- ``packer``       request batch → candidate-row tensors (the analogue of the
                   reference's bitmap Query, memoized per dimension tuple)
                   and attribute columns.
- ``evaluator``    the jitted sat/lattice computation + host assembly of
                   CheckOutputs (bit-exact vs the CPU oracle).
"""

from .evaluator import TpuEvaluator  # noqa: F401
