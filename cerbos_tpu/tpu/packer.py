"""Request batch → device tensors.

The packer is the host half of the TPU evaluator: it resolves scope chains,
expands parent roles, gathers candidate rule rows per (input, action, role)
— by calling the same Index.query the CPU oracle uses, memoized per
dimension tuple — and encodes attribute columns. Inputs the device cannot
evaluate faithfully (candidate overflow, unsupported value shapes at
device-compared paths, runtime-referencing conditions) are flagged for CPU
oracle fallback, so device coverage is a performance property, never a
correctness property.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from .. import namer
from ..engine import types as T
from ..ruletable.rows import KIND_PRINCIPAL, KIND_RESOURCE, RuleRow
from ..ruletable.check import EvalContext, build_request_messages
from .columns import (
    ColumnBatch,
    TAG_OTHER,
    encode_value,
)
from .condcompile import evaluate_pred_host
from .lowering import (
    EFFECT_DENY_CODE,
    EFFECT_NONE,
    LoweredTable,
    sp_code,
)

PT_PRINCIPAL = 0
PT_RESOURCE = 1


@dataclass(slots=True)
class CandEntry:
    """One candidate binding for an (input, action, role) cell."""

    cond_id: int
    drcond_id: int
    effect: int
    pt: int
    depth: int
    from_role_policy: bool
    origin_fqn: str
    row: Optional[RuleRow]  # original row (for outputs); None for pure synthetics
    needs_oracle: bool
    has_output: bool


@dataclass(slots=True)
class InputPlan:
    input: T.CheckInput
    principal_scopes: list[str]
    resource_scopes: list[str]
    principal_policy_key: str
    resource_policy_key: str
    resource_policy_fqn: str
    scoped_principal_exists: bool
    scoped_resource_exists: bool
    roles: list[str]
    oracle: bool = False  # fall back to the CPU oracle for this input
    trivial: bool = False  # no scopes/rows at all: every action default-DENY
    ba_range: tuple[int, int] = (0, 0)  # [start, end) in the flattened axis
    # small integer identifying the request SHAPE (one per distinct shape-memo
    # entry); the evaluator's assembly memo keys on it instead of re-hashing
    # every shape field per input
    sig: int = -1


@dataclass
class PackedBatch:
    plans: list[InputPlan]
    columns: ColumnBatch
    # flattened (input, action) axis
    ba_input: np.ndarray  # [BA] int32 → input index
    ba_action: list[str]
    # candidates [BA, K, J]
    cand_cond: np.ndarray
    cand_drcond: np.ndarray
    cand_effect: np.ndarray
    cand_pt: np.ndarray
    cand_depth: np.ndarray
    cand_valid: np.ndarray
    # scope permissions per input [B, 2, D]
    scope_sp: np.ndarray
    # host-side candidate entries for attribution/output reconstruction
    cand_entries: list[list[list[Optional[CandEntry]]]]  # [BA][K][J]
    K: int
    J: int
    D: int



def _memo_put(memo: dict, key, val):
    """Bounded memo insert: wholesale clear past the cap (simple, O(1)
    amortized; the caches re-warm in one batch)."""
    if len(memo) > 65536:
        memo.clear()
    memo[key] = val
    return val


class Packer:
    def __init__(self, lowered: LoweredTable, max_roles: int = 8, max_candidates: int = 32, max_depth: int = 8):
        self.lt = lowered
        self.K = max_roles
        self.J = max_candidates
        self.D = max_depth
        self._cand_cache: dict[tuple, Optional[list[list[CandEntry]]]] = {}
        self._pred_cache: dict[tuple, tuple[bool, bool]] = {}
        self._scope_cache: dict[tuple, tuple] = {}
        self._exists_cache: dict[tuple, bool] = {}
        self._cell_cache: dict[tuple, Optional[tuple]] = {}
        self._accessors: dict[tuple, Any] = {}
        self._pred_accessors: dict[int, list] = {}
        self._encode_cache: dict[Any, tuple] = {}
        self._ts_memo: dict[Any, Any] = {}
        self._list_memo: dict[Any, list[int]] = {}
        self._shape_memo: dict[tuple, tuple] = {}
        # monotone shape-signature sequence; NOT reset by invalidate() so a
        # sig never aliases across reloads (downstream memos key on it)
        self._sig_seq = 0
        # block registry: every distinct candidate cell block gets a stable
        # uid at shape-build time; pack() assembles cand_* tensors with one
        # gather over a cached [n_blocks, K, J] stack instead of per-cell
        # Python work. Same scheme for scope-permission rows.
        self._block_uid: dict[int, int] = {}
        self._block_store: list[tuple] = []
        self._block_stacked: dict[tuple[int, int], tuple[int, list[np.ndarray]]] = {}
        self._sp_uid: dict[bytes, int] = {}
        self._sp_store: list[np.ndarray] = []
        self._sp_stacked: Optional[tuple[int, np.ndarray]] = None
        # scratch interner for predicate group keys (kept separate from the
        # device interner so grouping never grows the device string space)
        self._pred_scratch: dict[str, int] = {}
        # pred_id -> fastpred program (None = outside the fast grammar)
        self._fast_preds: dict[int, Any] = {}

    def invalidate(self) -> None:
        self._cand_cache.clear()
        self._pred_cache.clear()
        self._scope_cache.clear()
        self._exists_cache.clear()
        self._cell_cache.clear()
        self._accessors.clear()
        self._pred_accessors.clear()
        self._encode_cache.clear()
        self._ts_memo.clear()
        self._list_memo.clear()
        self._shape_memo.clear()
        self._pred_scratch.clear()
        self._block_uid.clear()
        self._block_store.clear()
        self._block_stacked.clear()
        self._sp_uid.clear()
        self._sp_store.clear()
        self._sp_stacked = None
        self._fast_preds.clear()

    def _get_all_scopes(self, kind: str, scope: str, name: str, version: str, lenient: bool):
        key = (kind, scope, name, version, lenient)
        hit = self._scope_cache.get(key)
        if hit is None:
            hit = self.lt.table.get_all_scopes(kind, scope, name, version, lenient)
            self._scope_cache[key] = hit
        return hit

    def _exists(self, kind: str, version: str, name: str, scopes: list[str]) -> bool:
        key = (kind, version, name, tuple(scopes))
        hit = self._exists_cache.get(key)
        if hit is None:
            idx = self.lt.table.idx
            if kind == KIND_PRINCIPAL:
                hit = idx.scoped_principal_exists(version, scopes)
            else:
                hit = idx.scoped_resource_exists(version, name, scopes)
            self._exists_cache[key] = hit
        return hit

    # -- candidate generation ---------------------------------------------

    def _candidates(
        self,
        pt: int,
        version: str,
        resource: str,
        chain: tuple[str, ...],
        action: str,
        role: str,
        pid: str,
        resource_scope: str,
    ) -> Optional[list[list[CandEntry]]]:
        """Candidates per depth for one (pt, action, role); None → oracle."""
        key = (pt, version, resource, chain, action, role, pid, resource_scope)
        hit = self._cand_cache.get(key, False)
        if hit is not False:
            return hit
        rt = self.lt.table
        kind = KIND_PRINCIPAL if pt == PT_PRINCIPAL else KIND_RESOURCE
        # parent roles expand against the input's resource scope, matching
        # check.go:221 (AddParentRoles([resourceScope], [role]))
        parent_roles = rt.idx.add_parent_roles([resource_scope], [role])
        out: list[list[CandEntry]] = []
        ok = True
        for depth, scope in enumerate(chain):
            if depth >= self.D:
                ok = False
                break
            rows = rt.idx.query(version, resource, scope, action, parent_roles, kind, pid)
            entries: list[CandEntry] = []
            for r in rows:
                e = self._lower_candidate(r, pt, depth)
                if e is None or e.needs_oracle:
                    ok = False
                entries.append(e)  # keep shape; caller bails on not ok
            out.append(entries)
        result = out if ok else None
        self._cand_cache[key] = result
        return result

    def _lower_candidate(self, r: RuleRow, pt: int, depth: int) -> Optional[CandEntry]:
        lt = self.lt
        lr = lt.rows.get(r.id) if r.id >= 0 else None
        if lr is not None and lr.row is r:
            # regular indexed row
            return CandEntry(
                cond_id=lr.cond_id,
                drcond_id=lr.drcond_id,
                effect=lr.effect_code,
                pt=pt,
                depth=depth,
                from_role_policy=r.from_role_policy,
                origin_fqn=r.origin_fqn,
                row=r,
                needs_oracle=lr.needs_oracle,
                has_output=r.emit_output is not None,
            )
        # synthetic bindings produced by Index.query
        if r.no_match_for_scope_permissions:
            return CandEntry(
                cond_id=-1, drcond_id=-1, effect=EFFECT_DENY_CODE, pt=pt, depth=depth,
                from_role_policy=True, origin_fqn=r.origin_fqn, row=r,
                needs_oracle=False, has_output=False,
            )
        if r.from_role_policy and r.id >= 0:
            lr = lt.rows.get(r.id)
            if lr is None:
                return None
            if r.effect == "EFFECT_DENY":
                # negated-condition synthetic deny
                return CandEntry(
                    cond_id=lr.negated_cond_id, drcond_id=-1, effect=EFFECT_DENY_CODE,
                    pt=pt, depth=depth, from_role_policy=True, origin_fqn=r.origin_fqn,
                    row=r, needs_oracle=lr.negated_cond_id >= 0 and lt.compiler.kernels[lr.negated_cond_id].emit is None,
                    has_output=r.emit_output is not None,
                )
            # no-effect output carrier
            return CandEntry(
                cond_id=-1, drcond_id=-1, effect=EFFECT_NONE, pt=pt, depth=depth,
                from_role_policy=True, origin_fqn=r.origin_fqn, row=r,
                needs_oracle=False, has_output=r.emit_output is not None,
            )
        return None

    # -- packing -----------------------------------------------------------

    def pack(self, inputs: list[T.CheckInput], params: T.EvalParams) -> PackedBatch:
        plans: list[InputPlan] = []
        # everything except the input reference depends only on the REQUEST
        # SHAPE — (principal id/scope/version, resource kind/scope/version,
        # roles, actions) — a handful of distinct shapes per corpus. The
        # shape memo carries the full per-input packing product (plan fields,
        # resolved candidate blocks, scope-permission row, K/J/D extents) so
        # the per-input loop is one tuple build + dict hit. This is a
        # shape-level memo, not a value-level one: it stays hot under
        # per-request-unique attribute values (the memo-cold benchmark).
        shape_memo = self._shape_memo
        if len(shape_memo) > 65536:
            # the shape memo anchors the block/sp registries (uids live in
            # its values) and the cell cache (block identity) — evict them
            # together, and ONLY between batches: a mid-batch clear would
            # invalidate uids already collected for earlier inputs of the
            # same pack() call. One batch may overshoot the cap by its own
            # input count; that's bounded and re-warms immediately.
            self._clear_shape_caches()
        lenient = params.lenient_scope_search
        ba_count = 0
        ba_counts: list[int] = []
        ba_action: list[str] = []
        uid_chunks: list[np.ndarray] = []
        cand_entries: list[list[list[Optional[CandEntry]]]] = []
        K_max, J_max, chain_max = 1, 1, 1
        sp_uids: list[int] = []
        plans_append = plans.append
        idx_principal = self.lt.table.idx.principal
        for inp in inputs:
            principal = inp.principal
            resource = inp.resource
            # principals with no principal policy anywhere canonicalize to
            # one shape: the id cannot influence any decision (the index has
            # no rows for it), so per-request-unique ids share the shape
            # memo, the assembly memo AND the jit variant instead of
            # rebuilding everything per request
            pid = principal.id if principal.id in idx_principal else ""
            sk = (
                pid, principal.scope, principal.policy_version,
                resource.kind, resource.scope, resource.policy_version,
                tuple(principal.roles), tuple(inp.actions), lenient,
                params.default_scope, params.default_policy_version,
            )
            hit = shape_memo.get(sk)
            if hit is None:
                hit = self._build_shape(inp, params, lenient, pid)
                shape_memo[sk] = hit
            (p_scopes, r_scopes, p_key, r_key, r_fqn, sp_exists, sr_exists,
             roles, trivial, oracle, blk_uids, blk_entries, uniq_actions,
             K_blk, J_blk, sp_uid, chain_len, sig) = hit
            bi = len(plans)
            n = 0
            if blk_uids is not None:
                n = len(uniq_actions)
                ba_action.extend(uniq_actions)
                uid_chunks.append(blk_uids)
                cand_entries.extend(blk_entries)
                if K_blk > K_max:
                    K_max = K_blk
                if J_blk > J_max:
                    J_max = J_blk
                if chain_len > chain_max:
                    chain_max = chain_len
            plans_append(InputPlan(
                input=inp,
                principal_scopes=p_scopes,
                resource_scopes=r_scopes,
                principal_policy_key=p_key,
                resource_policy_key=r_key,
                resource_policy_fqn=r_fqn,
                scoped_principal_exists=sp_exists,
                scoped_resource_exists=sr_exists,
                roles=roles,
                trivial=trivial,
                oracle=oracle,
                ba_range=(ba_count, ba_count + n),
                sig=sig,
            ))
            ba_counts.append(n)
            sp_uids.append(sp_uid)
            ba_count += n

        BA = ba_count
        # the depth axis buckets to the batch's real max scope-chain length
        # (pow2 so jit traces are reused), not the configured cap — shallow
        # fleets halve the lattice's per-depth loop
        D = min(_pow2(chain_max), self.D)
        K = min(_pow2(K_max), self.K)
        J = min(_pow2(J_max), self.J)
        if BA:
            ba_input = np.repeat(
                np.arange(len(plans), dtype=np.int32),
                np.asarray(ba_counts, dtype=np.int64),
            )
            all_uids = np.concatenate(uid_chunks)
            stacked = self._stacked_blocks(K, J)
            cand_cond = stacked[0][all_uids]
            cand_drcond = stacked[1][all_uids]
            cand_effect = stacked[2][all_uids]
            cand_pt = stacked[3][all_uids]
            cand_depth = stacked[4][all_uids]
            cand_valid = stacked[5][all_uids]
        else:
            ba_input = np.zeros(0, dtype=np.int32)
            cand_cond = np.full((0, K, J), -1, dtype=np.int32)
            cand_drcond = np.full((0, K, J), -1, dtype=np.int32)
            cand_effect = np.zeros((0, K, J), dtype=np.int8)
            cand_pt = np.zeros((0, K, J), dtype=np.int8)
            cand_depth = np.full((0, K, J), -1, dtype=np.int8)
            cand_valid = np.zeros((0, K, J), dtype=bool)

        # scope permissions per input [B, 2, D]: rows precomputed per shape,
        # assembled with one gather over the registered-row stack
        if plans:
            scope_sp = self._stacked_sp()[np.asarray(sp_uids, dtype=np.int64)][:, :, :D]
        else:
            scope_sp = np.zeros((0, 2, D), dtype=np.int8)

        columns = self._encode_columns(plans, params)
        return PackedBatch(
            plans=plans,
            columns=columns,
            ba_input=np.asarray(ba_input, dtype=np.int32),
            ba_action=ba_action,
            cand_cond=cand_cond,
            cand_drcond=cand_drcond,
            cand_effect=cand_effect,
            cand_pt=cand_pt,
            cand_depth=cand_depth,
            cand_valid=cand_valid,
            scope_sp=scope_sp,
            cand_entries=cand_entries,
            K=int(K),
            J=int(J),
            D=D,
        )

    def _clear_shape_caches(self) -> None:
        """Evict the shape memo and everything whose identity it anchors."""
        self._shape_memo.clear()
        self._cell_cache.clear()
        self._block_uid.clear()
        self._block_store.clear()
        self._block_stacked.clear()
        self._sp_uid.clear()
        self._sp_store.clear()
        self._sp_stacked = None

    def _register_block(self, blk: tuple) -> int:
        uid = self._block_uid.get(id(blk))
        if uid is None:
            uid = len(self._block_store)
            self._block_uid[id(blk)] = uid
            self._block_store.append(blk)
        return uid

    def _register_sp(self, sp_row: np.ndarray) -> int:
        # content-keyed: distinct scope-permission patterns are few, so the
        # store stays tiny no matter how many shapes register
        key = sp_row.tobytes()
        uid = self._sp_uid.get(key)
        if uid is None:
            uid = len(self._sp_store)
            self._sp_uid[key] = uid
            self._sp_store.append(sp_row)
        return uid

    def _stacked_blocks(self, K: int, J: int) -> list[np.ndarray]:
        """[n_blocks, K, J] stacks of every registered block, padded.

        Grows INCREMENTALLY per (K, J) bucket: new registrations append into
        capacity-doubled arrays (amortized O(new blocks), not O(all blocks)
        per batch). Buckets are few (pow2 K/J), but evict wholesale past a
        small cap so stale buckets don't pin old full-size stacks."""
        n = len(self._block_store)
        hit = self._block_stacked.get((K, J))
        if hit is not None and hit[0] == n:
            return [a[:n] for a in hit[1]]
        if hit is not None and hit[1][0].shape[0] >= n:
            start, arrays = hit[0], hit[1]
        else:
            cap = max(16, 1 << (n - 1).bit_length()) if n else 16
            arrays = [
                np.full((cap, K, J), -1, dtype=np.int32),
                np.full((cap, K, J), -1, dtype=np.int32),
                np.zeros((cap, K, J), dtype=np.int8),
                np.zeros((cap, K, J), dtype=np.int8),
                np.full((cap, K, J), -1, dtype=np.int8),
                np.zeros((cap, K, J), dtype=bool),
            ]
            if hit is not None:
                old_n = hit[0]
                for a, old in zip(arrays, hit[1]):
                    a[:old_n] = old[:old_n]
                start = old_n
            else:
                start = 0
        for i in range(start, n):
            blk = self._block_store[i]
            kk, jj = blk[0].shape
            # blocks larger than this batch's (K, J) bucket can never be
            # gathered by it (the bucket covers the batch max), so truncating
            # them in this stack is safe
            kk, jj = min(kk, K), min(jj, J)
            for a, src in zip(arrays, blk[:6]):
                a[i, :kk, :jj] = src[:kk, :jj]
        if len(self._block_stacked) > 8 and (K, J) not in self._block_stacked:
            self._block_stacked.clear()
        self._block_stacked[(K, J)] = (n, arrays)
        return [a[:n] for a in arrays]

    def _stacked_sp(self) -> np.ndarray:
        n = len(self._sp_store)
        hit = self._sp_stacked
        if hit is not None and hit[0] == n:
            return hit[1]
        stacked = np.stack(self._sp_store) if n else np.zeros((0, 2, self.D), dtype=np.int8)
        self._sp_stacked = (n, stacked)
        return stacked

    def _build_shape(self, inp: T.CheckInput, params: T.EvalParams, lenient: bool, pid: str) -> tuple:
        """Resolve the full packing product for one request shape: plan
        fields, candidate blocks per unique action, scope-permission row and
        K/J/D extents. Runs once per distinct shape; every input with the
        same shape reuses the result verbatim. ``pid`` is the CANONICAL
        principal id ("" when the id has no principal policy rows — see
        pack(); such ids cannot influence decisions)."""
        rt = self.lt.table
        principal_scope = T.effective_scope(inp.principal.scope, params)
        principal_version = T.effective_version(inp.principal.policy_version, params)
        resource_scope = T.effective_scope(inp.resource.scope, params)
        resource_version = T.effective_version(inp.resource.policy_version, params)
        p_scopes, p_key, _p_fqn = self._get_all_scopes(
            KIND_PRINCIPAL, principal_scope, pid, principal_version, lenient
        )
        r_scopes, r_key, r_fqn = self._get_all_scopes(
            KIND_RESOURCE, resource_scope, inp.resource.kind, resource_version, lenient
        )
        sp_exists = self._exists(KIND_PRINCIPAL, principal_version, "", p_scopes)
        sr_exists = self._exists(
            KIND_RESOURCE, resource_version, namer.sanitize(inp.resource.kind), r_scopes
        )
        roles = list(inp.principal.roles)
        trivial = (not p_scopes and not r_scopes) or (not sp_exists and not sr_exists)
        oracle = len(roles) > self.K or len(p_scopes) > self.D or len(r_scopes) > self.D

        # scope-permission row at the full configured depth; pack() slices
        # to the batch's bucketed D
        sp_row = np.zeros((2, self.D), dtype=np.int8)
        for pi, chain in ((PT_PRINCIPAL, p_scopes), (PT_RESOURCE, r_scopes)):
            for d, scope in enumerate(chain[: self.D]):
                sp_row[pi, d] = sp_code(rt.get_scope_scope_permissions(scope))

        shape_blocks: Optional[list[tuple]] = None
        uniq_actions: list[str] = []
        K_blk, J_blk = 1, 1
        chain_len = max(len(p_scopes), len(r_scopes), 1)
        if not trivial and not oracle:
            shape_blocks = []
            seen: set[str] = set()
            for a in inp.actions:
                if a in seen:
                    continue
                seen.add(a)
                blk = self._cell_block(
                    inp, pid, p_scopes, r_scopes, roles, a, resource_version, resource_scope
                )
                if blk is None:
                    oracle = True
                    shape_blocks = None
                    uniq_actions = []
                    break
                uniq_actions.append(a)
                shape_blocks.append(blk)
                K_blk = max(K_blk, blk[0].shape[0])
                J_blk = max(J_blk, blk[0].shape[1])
        if shape_blocks is not None:
            blk_uids = np.fromiter(
                (self._register_block(blk) for blk in shape_blocks),
                dtype=np.int64, count=len(shape_blocks),
            )
            blk_entries = [blk[6] for blk in shape_blocks]
        else:
            blk_uids = None
            blk_entries = None
        self._sig_seq += 1
        return (
            p_scopes, r_scopes, p_key, r_key, r_fqn, sp_exists, sr_exists,
            roles, trivial, oracle, blk_uids, blk_entries, uniq_actions,
            K_blk, J_blk, self._register_sp(sp_row),
            min(chain_len, self.D), self._sig_seq,
        )

    def _cell_block(
        self,
        inp: T.CheckInput,
        pid: str,
        p_scopes: list[str],
        r_scopes: list[str],
        roles: list[str],
        action: str,
        resource_version: str,
        resource_scope: str,
    ) -> Optional[tuple]:
        """Candidate cell for one (shape, action); memoized across shapes
        that share the dimension tuple. None → oracle fallback. ``pid`` is
        already canonical (see pack())."""
        cell_blocks = self._cell_cache
        pid_key = pid
        key = (
            resource_version, inp.resource.kind, tuple(p_scopes),
            tuple(r_scopes), tuple(roles), action, pid_key, resource_scope,
        )
        hit = cell_blocks.get(key, False)
        if hit is not False:
            return hit
        sanitized = namer.sanitize(inp.resource.kind)
        per_k_entries: list[list[CandEntry]] = []
        ok = True
        for k, role in enumerate(roles):
            entries: list[CandEntry] = []
            for pt, chain, qpid in (
                (PT_PRINCIPAL, tuple(p_scopes), pid),
                (PT_RESOURCE, tuple(r_scopes), ""),
            ):
                if pt == PT_PRINCIPAL and k > 0:
                    continue  # principal pass uses only the first role
                if pt == PT_PRINCIPAL and not qpid:
                    # canonical "" = this principal id has no rows anywhere
                    # (see pack()); an empty id would mean match-all to
                    # Index.query, so don't query at all
                    continue
                cands = self._candidates(
                    pt, resource_version, sanitized, chain, action, role, qpid, resource_scope
                )
                if cands is None:
                    ok = False
                    break
                for depth_entries in cands:
                    entries.extend(depth_entries)
            if not ok or len(entries) > self.J or any(e is None for e in entries):
                ok = False
                break
            per_k_entries.append(entries)
        if not ok:
            cell_blocks[key] = None
            return None
        K_used = len(per_k_entries)
        J_used = max((len(es) for es in per_k_entries), default=0)
        block = (
            np.full((K_used, J_used), -1, dtype=np.int32),  # cond
            np.full((K_used, J_used), -1, dtype=np.int32),  # drcond
            np.zeros((K_used, J_used), dtype=np.int8),  # effect
            np.zeros((K_used, J_used), dtype=np.int8),  # pt
            np.full((K_used, J_used), -1, dtype=np.int8),  # depth
            np.zeros((K_used, J_used), dtype=bool),  # valid
            per_k_entries,
        )
        for k, es in enumerate(per_k_entries):
            for j, e in enumerate(es):
                block[0][k, j] = e.cond_id
                block[1][k, j] = e.drcond_id
                block[2][k, j] = e.effect
                block[3][k, j] = e.pt
                block[4][k, j] = e.depth
                block[5][k, j] = True
        cell_blocks[key] = block
        return block

    # -- columns -----------------------------------------------------------

    def _input_view(self, inp: T.CheckInput) -> dict:
        aux = inp.aux_data or T.AuxData()
        jwt = {"jwt": aux.jwt}
        return {
            "aux_data": jwt,
            "principal": {
                "id": inp.principal.id,
                "roles": list(inp.principal.roles),
                "attr": inp.principal.attr,
                "policyVersion": inp.principal.policy_version,
                "scope": namer.scope_value(inp.principal.scope),
            },
            "resource": {
                "kind": inp.resource.kind,
                "id": inp.resource.id,
                "attr": inp.resource.attr,
                "policyVersion": inp.resource.policy_version,
                "scope": namer.scope_value(inp.resource.scope),
            },
            "auxData": jwt,
        }

    def _path_accessor(self, path: tuple[str, ...]):
        """Compile a fast value resolver for a column path. The overwhelmingly
        common shapes (principal/resource attr leaves and top-level fields)
        skip the generic dict walk."""
        fn = self._accessors.get(path)
        if fn is not None:
            return fn
        _MISSING = _MISSING_SENTINEL
        if len(path) == 3 and path[0] in ("aux_data", "auxData") and path[1] == "jwt":
            leaf = path[2]

            def fn(inp, leaf=leaf):  # type: ignore[misc]
                aux = inp.aux_data
                if aux is None:
                    return _MISSING
                return aux.jwt.get(leaf, _MISSING)

        elif len(path) == 3 and path[0] in ("principal", "resource") and path[1] == "attr":
            root, leaf = path[0], path[2]

            def fn(inp, root=root, leaf=leaf):  # type: ignore[misc]
                return getattr(inp, root).attr.get(leaf, _MISSING)

        elif (
            len(path) == 2
            and path[0] in ("principal", "resource")
            # only the wire-format field names; anything else (e.g. a
            # snake_case dataclass attribute) must behave as missing, like
            # the generic view walk does
            and path[1] in ("id", "kind", "roles", "attr", "policyVersion", "scope")
        ):
            root, leaf = path[0], path[1]
            if leaf == "scope":
                scope_value = namer.scope_value

                def fn(inp, root=root, scope_value=scope_value):  # type: ignore[misc]
                    return scope_value(getattr(inp, root).scope)

            else:
                attr_name = {"policyVersion": "policy_version"}.get(leaf, leaf)

                def fn(inp, root=root, attr_name=attr_name):  # type: ignore[misc]
                    return getattr(getattr(inp, root), attr_name, _MISSING)

        else:

            def fn(inp):  # type: ignore[misc]
                view = self._input_view(inp)
                return _walk_view(view, path)

        self._accessors[path] = fn
        return fn

    def _encode_columns(self, plans: list[InputPlan], params: T.EvalParams) -> ColumnBatch:
        from .condcompile import TAG_ERR

        from .. import native as native_mod
        from .columns import TAG_NUM

        B = len(plans)
        cb = ColumnBatch(size=B)
        interner = self.lt.interner
        paths = sorted(self.lt.paths)
        encode_cache = self._encode_cache
        native = native_mod.get()
        # filter once, not once per path
        active = [(bi, plan) for bi, plan in enumerate(plans) if not (plan.trivial or plan.oracle)]
        if native is not None and hasattr(native, "encode_column"):
            self._encode_columns_native(cb, plans, active, paths, native)
            self._encode_list_columns(cb, plans, active)
            self._encode_ts_columns(cb, plans, active, params)
            self._encode_preds(cb, plans, active, params)
            return cb
        for p in paths:
            t = np.zeros(B, dtype=np.int8)
            h = np.zeros(B, dtype=np.int32)
            l = np.zeros(B, dtype=np.int32)
            s = np.zeros(B, dtype=np.int32)
            nn = np.zeros(B, dtype=bool)
            accessor = self._path_accessor(p)
            trig = self.lt.fallback_tags.get(p)
            # float values batch through the native key encoder
            num_idx: list[int] = []
            num_vals: list[float] = []
            for bi, plan in active:
                v = accessor(plan.input)
                if v is _MISSING_SENTINEL:
                    continue  # TAG_MISSING zeros already in place
                if v is _ERR_SENTINEL:
                    t[bi] = TAG_ERR
                    continue
                if native is not None and type(v) is float:
                    t[bi] = TAG_NUM
                    num_idx.append(bi)
                    num_vals.append(v)
                    continue
                # cache encodings per concrete value; key includes the type so
                # True / 1.0 / 1 don't collide as dict keys
                try:
                    ck = (type(v), v)
                    enc = encode_cache.get(ck)
                except TypeError:
                    tag, hi, lo, sid, is_nan = encode_value(v, True, interner)
                else:
                    if enc is None:
                        tag, hi, lo, sid, is_nan = encode_value(v, True, interner)
                        if len(encode_cache) > 65536:
                            encode_cache.clear()
                        encode_cache[ck] = (tag, hi, lo, sid, is_nan)
                    else:
                        tag, hi, lo, sid, is_nan = enc
                t[bi], h[bi], l[bi], s[bi], nn[bi] = tag, hi, lo, sid, is_nan
                if trig and tag in trig:
                    plan.oracle = True
            if num_idx:
                arr = np.asarray(num_vals, dtype=np.float64)
                hi_b, lo_b, nan_b = native.encode_double_keys(arr.tobytes())
                idx = np.asarray(num_idx, dtype=np.int64)
                h[idx] = np.frombuffer(hi_b, dtype=np.int32)
                l[idx] = np.frombuffer(lo_b, dtype=np.int32)
                nn[idx] = np.frombuffer(nan_b, dtype=np.uint8).astype(bool)
            cb.tags[p], cb.his[p], cb.los[p], cb.sids[p], cb.nans[p] = t, h, l, s, nn

        self._encode_list_columns(cb, plans, active)
        self._encode_ts_columns(cb, plans, active, params)
        self._encode_preds(cb, plans, active, params)
        return cb

    def _encode_ts_columns(self, cb: ColumnBatch, plans, active, params) -> None:
        """Parsed-timestamp key columns for paths used inside timestamp(...)
        comparisons, plus the batch-constant now() key. Conversion is the CEL
        runtime's own timestamp() overload set (columns.timestamp_key), so
        device semantics match the oracle bit-exactly; unconvertible values
        carry state 2 (a CEL error on device)."""
        from .columns import timestamp_key

        ts_paths = self.lt.ts_paths
        if not ts_paths and not self.lt.uses_now:
            return
        B = cb.size
        memo = self._ts_memo
        for p in sorted(ts_paths):
            accessor = self._path_accessor(p)
            hi = np.zeros(B, dtype=np.int32)
            lo = np.zeros(B, dtype=np.int32)
            state = np.zeros(B, dtype=np.int8)
            for bi, plan in active:
                if plan.oracle:
                    continue
                v = accessor(plan.input)
                if v is _MISSING_SENTINEL:
                    continue  # state 0: the attribute access itself errors
                try:
                    mk = (type(v), v)
                    enc = memo.get(mk)
                except TypeError:
                    mk, enc = None, None
                if enc is None:
                    try:
                        enc = timestamp_key(v)
                    except Exception:  # noqa: BLE001 — CEL would error on this value
                        enc = "err"
                    if mk is not None:
                        _memo_put(memo, mk, enc)
                if enc == "err":
                    state[bi] = 2
                else:
                    hi[bi], lo[bi] = enc
                    state[bi] = 1
            cb.ts_his[p], cb.ts_los[p], cb.ts_states[p] = hi, lo, state
        now_fn = getattr(params, "now_fn", None)
        if now_fn is not None:
            now_val = now_fn()
        else:
            import datetime as _dt

            now_val = _dt.datetime.now(_dt.timezone.utc).isoformat()
        nh, nl = timestamp_key(now_val)
        cb.now_hi = np.asarray(nh, dtype=np.int32)
        cb.now_lo = np.asarray(nl, dtype=np.int32)

    def _encode_list_columns(self, cb: ColumnBatch, plans, active) -> None:
        """String-list membership columns: per path, pad each input's list of
        interned sids to the batch max length; non-lists / non-string
        elements error (state 2), missing attrs are state 0.

        Interned sid vectors memoize per concrete list value — request
        corpora repeat a small set of role/location lists, so the per-
        element intern loop runs once per distinct list, not per input."""
        B = cb.size
        interner = self.lt.interner
        memo = self._list_memo
        from .. import native as native_mod

        native = native_mod.get()
        use_native = native is not None and hasattr(native, "encode_list_column")
        for p in sorted(self.lt.list_paths):
            fused = self._fused_mode(p) if use_native else None
            if fused is not None:
                # oracle flags may have flipped during scalar encoding;
                # re-filter so oracled inputs don't intern into device space
                live = [(bi, plan) for bi, plan in active if not plan.oracle]
                nl = len(live)
                mode, root, leaf = fused
                lstate = np.zeros(nl, dtype=np.uint8)
                width, sids_bytes = native.encode_list_column(
                    [plan.input for _, plan in live], mode, root, leaf,
                    interner.ids, _MISSING_SENTINEL, memoryview(lstate),
                )
                arr = np.zeros((B, width), dtype=np.int32)
                state = np.zeros(B, dtype=np.int8)
                if nl:
                    ix = np.fromiter((bi for bi, _ in live), dtype=np.int64, count=nl)
                    mat = np.frombuffer(sids_bytes, dtype=np.int32).reshape(nl, width)
                    dicts = lstate == 3
                    if dicts.any():
                        for si in np.nonzero(dicts)[0]:
                            live[int(si)][1].oracle = True
                        lstate = np.where(dicts, 0, lstate)
                        mat = np.where(dicts[:, None], 0, mat)
                    arr[ix] = mat
                    state[ix] = lstate.astype(np.int8)
                cb.list_sids[p] = arr
                cb.list_states[p] = state
                continue
            accessor = self._path_accessor(p)
            per_input: list[Optional[list[int]]] = [None] * B
            state = np.zeros(B, dtype=np.int8)
            max_len = 1
            for bi, plan in active:
                if plan.oracle:
                    continue
                v = accessor(plan.input)
                if v is _MISSING_SENTINEL:
                    continue  # state 0
                if isinstance(v, dict):
                    # CEL `in` over a map is KEY membership — different
                    # semantics; route to the oracle like scalar-path
                    # fallback tags do
                    plan.oracle = True
                    continue
                if not isinstance(v, list):
                    state[bi] = 2
                    continue
                try:
                    mk = tuple(v)
                    sids = memo.get(mk)
                except TypeError:
                    mk, sids = None, None
                if sids is None:
                    sids = []
                    for el in v:
                        if isinstance(el, str):
                            sids.append(interner.intern(el))
                        else:
                            # a non-string element can never equal the string
                            # constant; slot 0 (reserved) never matches
                            sids.append(0)
                    if mk is not None:
                        _memo_put(memo, mk, sids)
                state[bi] = 1
                per_input[bi] = sids
                if len(sids) > max_len:
                    max_len = len(sids)
            # bucket the list axis so jit traces are reused across batches
            # with different max lengths
            max_len = _pow2(max(max_len, 4))
            arr = np.zeros((B, max_len), dtype=np.int32)
            for bi, sids in enumerate(per_input):
                if sids:
                    arr[bi, : len(sids)] = sids
            cb.list_sids[p] = arr
            cb.list_states[p] = state

    def _encode_preds(self, cb: ColumnBatch, plans, active, params) -> None:
        B = cb.size
        preds = self.lt.compiler.preds
        if not preds:
            return
        from .. import native as native_mod

        live = [(bi, plan) for bi, plan in active if not plan.oracle]
        out = {
            spec.pred_id: (np.zeros(B, dtype=bool), np.zeros(B, dtype=bool))
            for spec in preds
        }

        # Closed-form vectorized predicates first (fastpred): no activation
        # objects, no interpreter, no value-combination grouping — a
        # memo-cold batch with globally unique attributes costs one Python
        # loop per AST op instead of a full CEL evaluation per input.
        fast_specs: list[tuple[Any, Any]] = []
        gen_specs: list = []
        for spec in preds:
            prog = self._fast_pred_prog(spec)
            if prog is not None:
                fast_specs.append((spec, prog))
            else:
                gen_specs.append(spec)
        if fast_specs and live:
            n = len(live)
            gathered: dict[tuple[str, ...], list] = {}
            for _, prog in fast_specs:
                for p in prog.paths:
                    if p not in gathered:
                        acc = self._path_accessor(p)
                        gathered[p] = [acc(plan.input) for _, plan in live]
            bis = np.fromiter((bi for bi, _ in live), dtype=np.int64, count=n)
            for spec, prog in fast_specs:
                v_list, e_list = prog.eval(gathered, n)
                vals, errs = out[spec.pred_id]
                vals[bis] = v_list
                errs[bis] = e_list
        preds = gen_specs
        if not preds:
            for spec_id, (vals, errs) in out.items():
                cb.pred_vals[spec_id] = vals
                cb.pred_errs[spec_id] = errs
            return

        # Vectorized grouping: encode every referenced path's value to its
        # canonical (tag, hi, lo, sid) key columns, group the batch with one
        # np.unique over the key matrix, and evaluate each predicate ONCE per
        # distinct value combination. Inputs carrying container values
        # (TAG_OTHER collapses distinct lists/maps) and time-dependent specs
        # drop to per-input evaluation; everything else is O(unique combos).
        native = native_mod.get()
        group_specs = [s for s in preds if not s.time_dependent]
        grouped_rows: Optional[np.ndarray] = None
        if native is not None and hasattr(native, "encode_attr_column") and group_specs and len(live) >= 32:
            paths = sorted({p for spec in group_specs for p in spec.ref_paths})
            modes = [self._fused_mode(p) for p in paths]
            if all(m is not None for m in modes):
                n = len(live)
                inputs_list = [plan.input for _, plan in live]
                scratch = self._pred_scratch
                if len(scratch) > 65536:
                    scratch.clear()
                cols: list[np.ndarray] = []
                groupable = np.ones(n, dtype=bool)
                for (mode, root, leaf) in modes:  # type: ignore[misc]
                    t = np.zeros(n, dtype=np.uint8)
                    h = np.zeros(n, dtype=np.int32)
                    l = np.zeros(n, dtype=np.int32)
                    s = np.zeros(n, dtype=np.int32)
                    nn = np.zeros(n, dtype=np.uint8)
                    st = np.zeros(n, dtype=np.uint8)
                    native.encode_attr_column(
                        inputs_list, mode, root, leaf, scratch,
                        _MISSING_SENTINEL, _ERR_SENTINEL,
                        memoryview(t), memoryview(h), memoryview(l),
                        memoryview(s), memoryview(nn), memoryview(st),
                    )
                    groupable &= t != TAG_OTHER  # containers don't key
                    # ints the double key can't represent exactly never
                    # group; the subtype column keeps int 1 and double 1.0
                    # (CEL-distinct) in separate groups
                    groupable &= st != 3
                    cols.extend((t.astype(np.int32), h, l, s, nn.astype(np.int32), st.astype(np.int32)))
                key_mat = np.ascontiguousarray(np.stack(cols, axis=1), dtype=np.int32)
                g_idx = np.nonzero(groupable)[0]
                if g_idx.size:
                    # group by raw row bytes with one dict pass — O(n) hashing
                    # beats np.unique's O(n log n) argsort on every batch
                    rows = np.ascontiguousarray(key_mat[g_idx])
                    row_w = rows.shape[1] * 4
                    buf = rows.tobytes()
                    seen: dict[bytes, int] = {}
                    n_g = g_idx.size
                    inverse = np.empty(n_g, dtype=np.int64)
                    rep: list[int] = []
                    for i in range(n_g):
                        rb = buf[i * row_w : (i + 1) * row_w]
                        u = seen.get(rb)
                        if u is None:
                            u = len(rep)
                            seen[rb] = u
                            rep.append(i)
                        inverse[i] = u
                    bis = np.fromiter(
                        (live[int(i)][0] for i in g_idx), dtype=np.int64, count=n_g
                    )
                    n_u = len(rep)
                    for spec in group_specs:
                        vals, errs = out[spec.pred_id]
                        uv = np.empty(n_u, dtype=bool)
                        ue = np.empty(n_u, dtype=bool)
                        for u in range(n_u):
                            _, plan_rep = live[int(g_idx[rep[u]])]
                            uv[u], ue[u] = self._eval_pred(spec, plan_rep, params)
                        vals[bis] = uv[inverse]
                        errs[bis] = ue[inverse]
                    grouped_rows = groupable

        for si, (bi, plan) in enumerate(live):
            is_grouped = grouped_rows is not None and grouped_rows[si]
            for spec in preds:
                if is_grouped and not spec.time_dependent:
                    continue
                vals, errs = out[spec.pred_id]
                vals[bi], errs[bi] = self._eval_pred(spec, plan, params)
        for spec_id, (vals, errs) in out.items():
            cb.pred_vals[spec_id] = vals
            cb.pred_errs[spec_id] = errs

    def _fast_pred_prog(self, spec):
        """Compile-once cache of fastpred programs (None = generic path)."""
        hit = self._fast_preds.get(spec.pred_id, _MISSING_SENTINEL)
        if hit is not _MISSING_SENTINEL:
            return hit
        from . import fastpred

        fastpred.configure(_MISSING_SENTINEL, _ERR_SENTINEL)
        prog = fastpred.compile_fast_pred(spec)
        self._fast_preds[spec.pred_id] = prog
        return prog

    def _fused_mode(self, path: tuple[str, ...]) -> Optional[tuple[int, str, str]]:
        """(mode, root, leaf) for paths the C fused gather+encode handles;
        None → Python gather. Mirrors _path_accessor's fast shapes (scope is
        excluded: it needs namer.scope_value)."""
        if len(path) == 3 and path[0] in ("aux_data", "auxData") and path[1] == "jwt":
            return (1, "aux_data", path[2])
        if len(path) == 3 and path[0] in ("principal", "resource") and path[1] == "attr":
            return (0, path[0], path[2])
        if (
            len(path) == 2
            and path[0] in ("principal", "resource")
            and path[1] in ("id", "kind", "roles", "attr", "policyVersion")
        ):
            leaf = {"policyVersion": "policy_version"}.get(path[1], path[1])
            return (2, path[0], leaf)
        return None

    def _encode_columns_native(self, cb: ColumnBatch, plans, active, paths, native) -> None:
        """Whole-column encoding in C: for the common path shapes the value
        gather (attribute access on input objects) AND the type dispatch +
        key/interning loop both run natively (encode_attr_column); other
        paths gather values in Python and encode via encode_column."""
        B = cb.size
        interner = self.lt.interner
        all_active = len(active) == B
        fused_ok = hasattr(native, "encode_attr_column")
        # only ACTIVE inputs are gathered/encoded: trivial/oracle inputs stay
        # TAG_MISSING and must not intern their strings into the device
        # string space
        act_inputs = None
        act_ix = None
        if fused_ok:
            if all_active:
                act_inputs = [plan.input for plan in plans]
            else:
                act_inputs = [plan.input for _, plan in active]
                act_ix = np.fromiter((bi for bi, _ in active), dtype=np.int64, count=len(active))
        na = len(active)

        # one C pass over the batch for every fused path at once: the
        # per-input attribute resolution (principal/resource objects, attr
        # and jwt dicts) is shared by all P columns instead of repeated P
        # times (encode_attr_columns_multi)
        done: set = set()
        if fused_ok and hasattr(native, "encode_attr_columns_multi") and act_inputs:
            fused_paths = [p for p in paths if self._fused_mode(p) is not None]
            if fused_paths:
                P = len(fused_paths)
                MT = np.zeros((P, na), dtype=np.uint8)
                MH = np.zeros((P, na), dtype=np.int32)
                ML = np.zeros((P, na), dtype=np.int32)
                MS = np.zeros((P, na), dtype=np.int32)
                MN = np.zeros((P, na), dtype=np.uint8)
                native.encode_attr_columns_multi(
                    act_inputs,
                    [self._fused_mode(p) for p in fused_paths],
                    interner.ids, _MISSING_SENTINEL, _ERR_SENTINEL,
                    memoryview(MT), memoryview(MH), memoryview(ML),
                    memoryview(MS), memoryview(MN),
                )
                for pi, p in enumerate(fused_paths):
                    if all_active:
                        t, h, l, s, nn = MT[pi], MH[pi], ML[pi], MS[pi], MN[pi]
                    else:
                        t = np.zeros(B, dtype=np.uint8)
                        h = np.zeros(B, dtype=np.int32)
                        l = np.zeros(B, dtype=np.int32)
                        s = np.zeros(B, dtype=np.int32)
                        nn = np.zeros(B, dtype=np.uint8)
                        t[act_ix] = MT[pi]
                        h[act_ix] = MH[pi]
                        l[act_ix] = ML[pi]
                        s[act_ix] = MS[pi]
                        nn[act_ix] = MN[pi]
                    self._store_scalar_column(cb, plans, p, t, h, l, s, nn)
                    done.add(p)

        for p in paths:
            if p in done:
                continue
            t = np.zeros(B, dtype=np.uint8)
            h = np.zeros(B, dtype=np.int32)
            l = np.zeros(B, dtype=np.int32)
            s = np.zeros(B, dtype=np.int32)
            nn = np.zeros(B, dtype=np.uint8)
            fused = self._fused_mode(p) if fused_ok else None
            if fused is not None:
                mode, root, leaf = fused
                if act_ix is None:
                    native.encode_attr_column(
                        act_inputs, mode, root, leaf,
                        interner.ids, _MISSING_SENTINEL, _ERR_SENTINEL,
                        memoryview(t), memoryview(h), memoryview(l), memoryview(s), memoryview(nn),
                    )
                else:
                    ct = np.zeros(na, dtype=np.uint8)
                    ch = np.zeros(na, dtype=np.int32)
                    cl = np.zeros(na, dtype=np.int32)
                    cs = np.zeros(na, dtype=np.int32)
                    cn = np.zeros(na, dtype=np.uint8)
                    native.encode_attr_column(
                        act_inputs, mode, root, leaf,
                        interner.ids, _MISSING_SENTINEL, _ERR_SENTINEL,
                        memoryview(ct), memoryview(ch), memoryview(cl), memoryview(cs), memoryview(cn),
                    )
                    t[act_ix] = ct
                    h[act_ix] = ch
                    l[act_ix] = cl
                    s[act_ix] = cs
                    nn[act_ix] = cn
            else:
                accessor = self._path_accessor(p)
                if all_active:
                    values = [accessor(plan.input) for plan in plans]
                else:
                    values = [_MISSING_SENTINEL] * B
                    for bi, plan in active:
                        values[bi] = accessor(plan.input)
                native.encode_column(
                    values, interner.ids, _MISSING_SENTINEL, _ERR_SENTINEL,
                    memoryview(t), memoryview(h), memoryview(l), memoryview(s), memoryview(nn),
                )
            self._store_scalar_column(cb, plans, p, t, h, l, s, nn)

    def _store_scalar_column(self, cb: ColumnBatch, plans, p, t, h, l, s, nn) -> None:
        """Fallback-tag oracle routing + dtype-normalized store of one
        encoded scalar column."""
        trig = self.lt.fallback_tags.get(p)
        if trig:
            bad = np.isin(t, np.fromiter(trig, dtype=np.uint8))
            if bad.any():
                for bi in np.nonzero(bad)[0]:
                    plan = plans[int(bi)]
                    if not (plan.trivial or plan.oracle):
                        plan.oracle = True
        cb.tags[p] = t.astype(np.int8)
        cb.his[p], cb.los[p], cb.sids[p] = h, l, s
        cb.nans[p] = nn.astype(bool)

    def _pred_key_accessors(self, spec):
        accs = self._pred_accessors.get(spec.pred_id)
        if accs is None:
            accs = [self._path_accessor(p) for p in spec.ref_paths]
            self._pred_accessors[spec.pred_id] = accs
        return accs

    def _eval_pred(self, spec, plan: InputPlan, params: T.EvalParams) -> tuple[bool, bool]:
        cache_key = None
        if not spec.time_dependent:
            try:
                vals = []
                for acc in self._pred_key_accessors(spec):
                    v = acc(plan.input)
                    # typed scalars pass through (True/1/1.0 must not
                    # collide); containers freeze
                    if v is None or type(v) in (str, bool, int, float):
                        vals.append((type(v), v) if type(v) in (bool, int, float) else v)
                    else:
                        vals.append(_freeze(v))
                cache_key = (spec.pred_id, tuple(vals))
            except TypeError:
                cache_key = None
        if cache_key is not None:
            hit = self._pred_cache.get(cache_key)
            if hit is not None:
                return hit
        request, principal, resource = build_request_messages(plan.input)
        ec = EvalContext(params, request, principal, resource)

        def act_factory(pparams):
            variables = ec.evaluate_variables(pparams.constants, pparams.ordered_variables)
            return ec.activation(pparams.constants, variables)

        result = evaluate_pred_host(spec, plan.input, act_factory)
        if cache_key is not None:
            self._pred_cache[cache_key] = result
        return result


class _Sentinel:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


_MISSING_SENTINEL = _Sentinel("missing")
_ERR_SENTINEL = _Sentinel("err")


def _walk_view(view: dict, path: tuple[str, ...]):
    """Generic path walk distinguishing leaf-missing from intermediate
    failures (has() semantics — see condcompile TAG_ERR)."""
    cur: Any = view
    for i, seg in enumerate(path):
        if isinstance(cur, dict):
            if seg not in cur:
                return _MISSING_SENTINEL if i == len(path) - 1 else _ERR_SENTINEL
            cur = cur[seg]
        else:
            return _ERR_SENTINEL
    return cur


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _freeze(v: Any):
    """Hashable cache key preserving CEL type distinctions: True/1/1.0 are
    equal as Python dict keys but NOT as CEL values, so scalars carry a type
    tag at every nesting level."""
    if isinstance(v, (tuple, list)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((str(k), _freeze(x)) for k, x in v.items()))
    if isinstance(v, (bool, int, float)):
        return (type(v).__name__, v)
    return v
