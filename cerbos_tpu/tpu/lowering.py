"""Rule table → lowered static tables for the device evaluator.

Produces per-row static metadata (effect codes, policy kinds, condition ids)
and the compiled condition kernel set. Role-policy rows additionally get
pre-negated condition ids for query-time synthetic DENYs (the reference
builds those bindings on the fly, index.go:472-509; here the negation is
interned once at lowering).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..compile import CompiledCondition
from ..ruletable.rows import KIND_PRINCIPAL, RuleRow
from ..ruletable.table import RuleTable
from ..policy.model import (
    SCOPE_PERMISSIONS_OVERRIDE_PARENT,
    SCOPE_PERMISSIONS_REQUIRE_PARENTAL_CONSENT,
)
from .columns import StringInterner
from .condcompile import ConditionSetCompiler

EFFECT_NONE = 0
EFFECT_ALLOW_CODE = 1
EFFECT_DENY_CODE = 2

SP_UNSPECIFIED = 0
SP_OVERRIDE = 1
SP_RPC = 2


def sp_code(sp: str) -> int:
    if sp == SCOPE_PERMISSIONS_OVERRIDE_PARENT:
        return SP_OVERRIDE
    if sp == SCOPE_PERMISSIONS_REQUIRE_PARENTAL_CONSENT:
        return SP_RPC
    return SP_UNSPECIFIED


@dataclass
class LoweredRow:
    row: RuleRow
    cond_id: int
    drcond_id: int
    effect_code: int
    is_principal: bool
    needs_oracle: bool
    # role-policy rows only: condition id of none(condition) for synthetic denies
    negated_cond_id: int = -1


@dataclass
class LoweredTable:
    table: RuleTable
    compiler: ConditionSetCompiler
    interner: StringInterner
    rows: dict[int, LoweredRow] = field(default_factory=dict)  # by RuleRow.id
    paths: set[tuple[str, ...]] = field(default_factory=set)
    list_paths: set[tuple[str, ...]] = field(default_factory=set)
    ts_paths: set[tuple[str, ...]] = field(default_factory=set)
    uses_now: bool = False
    fallback_tags: dict[tuple[str, ...], frozenset[int]] = field(default_factory=dict)
    dr_cond_ids: dict[int, int] = field(default_factory=dict)  # id(CompiledDerivedRole) -> cond id
    dr_cond_id_arr: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.int64))
    has_outputs: bool = False

    def refresh(self) -> None:
        """(Re)lower all rows currently in the index. Called at build and on
        storage reload events (the re-lower + device swap hook)."""
        self.rows.clear()
        for row in self.table.idx.get_all_rows():
            self.rows[row.id] = self._lower_row(row)
        self.has_outputs = any(lr.row.emit_output is not None for lr in self.rows.values())
        # derived-role conditions get kernels too, so effectiveDerivedRoles
        # can be read off the device sat matrix instead of host CEL re-eval
        self.dr_cond_ids = {}
        for drs in self.table.policy_derived_roles.values():
            for dr in drs.values():
                if dr.condition is not None:
                    self.dr_cond_ids[id(dr)] = self.compiler.cond_id(dr.condition, dr.params)
        # ndarray form for the per-batch active-mask (hot path)
        self.dr_cond_id_arr = np.asarray(
            [c for c in self.dr_cond_ids.values() if c >= 0], dtype=np.int64
        )
        self._collect_paths()

    def _lower_row(self, row: RuleRow) -> LoweredRow:
        cond_id = self.compiler.cond_id(row.condition, row.params)
        drcond_id = self.compiler.cond_id(row.derived_role_condition, row.derived_role_params)
        needs_oracle = False
        for cid in (cond_id, drcond_id):
            if cid >= 0 and self.compiler.kernels[cid].emit is None:
                needs_oracle = True
        effect_code = EFFECT_NONE
        if row.effect == "EFFECT_ALLOW":
            effect_code = EFFECT_ALLOW_CODE
        elif row.effect == "EFFECT_DENY":
            effect_code = EFFECT_DENY_CODE
        negated_cond_id = -1
        if row.allow_actions is not None and row.condition is not None:
            neg = CompiledCondition(kind="none", children=(row.condition,))
            negated_cond_id = self.compiler.cond_id(neg, row.params)
            if self.compiler.kernels[negated_cond_id].emit is None:
                needs_oracle = True
        return LoweredRow(
            row=row,
            cond_id=cond_id,
            drcond_id=drcond_id,
            effect_code=effect_code,
            is_principal=row.policy_kind == KIND_PRINCIPAL,
            needs_oracle=needs_oracle,
            negated_cond_id=negated_cond_id,
        )

    def _collect_paths(self) -> None:
        self.paths.clear()
        self.list_paths.clear()
        self.ts_paths.clear()
        self.fallback_tags.clear()
        self.uses_now = False
        for k in self.compiler.kernels:
            self.paths |= k.paths
            self.list_paths |= k.list_paths
            self.ts_paths |= k.ts_paths
            self.uses_now = self.uses_now or k.uses_now
            for p, tags in k.fallback_tags.items():
                self.fallback_tags[p] = self.fallback_tags.get(p, frozenset()) | tags
            for spec in k.preds:
                # predicate columns resolve their own paths on the host
                pass


def lower_table(rt: RuleTable, globals_: Optional[dict[str, Any]] = None) -> LoweredTable:
    interner = StringInterner()
    compiler = ConditionSetCompiler(globals_ or {}, interner)
    lt = LoweredTable(table=rt, compiler=compiler, interner=interner)
    lt.refresh()
    return lt
