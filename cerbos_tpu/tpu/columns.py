"""SoA attribute columns and exact-parity scalar encodings.

Doubles are encoded as order-preserving (hi, lo) int32 pairs so the device
can compare them bit-exactly without f64 arithmetic (TPUs emulate f64; the
sortable-key trick keeps comparisons in native i32). Strings are interned to
batch-local i32 ids (equality-only). Each referenced attribute path becomes
one column set: tag, hi, lo, sid, nan.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

TAG_MISSING = 0
TAG_NULL = 1
TAG_BOOL = 2
TAG_NUM = 3
TAG_STR = 4
TAG_OTHER = 5


def double_key(v: float) -> int:
    """Map a double to a uint64 preserving order (NaN excluded).

    -0.0 normalizes to 0.0 first: CEL compares them equal, so they must
    encode to the same key.
    """
    v = float(v)
    if v == 0.0:
        v = 0.0
    (bits,) = struct.unpack("<Q", struct.pack("<d", v))
    if bits & (1 << 63):
        return (~bits) & ((1 << 64) - 1)
    return bits | (1 << 63)


def split_key(key: int) -> tuple[int, int]:
    """uint64 sortable key → sign-biased (hi, lo) int32 pair.

    Each 32-bit word is XORed with 0x80000000 before reinterpreting as
    signed, so plain *signed* int32 comparison on device preserves the
    unsigned key order (device kernels compare hi then lo as signed ints).
    """
    hi = ((key >> 32) & 0xFFFFFFFF) ^ 0x80000000
    lo = (key & 0xFFFFFFFF) ^ 0x80000000
    if hi >= 1 << 31:
        hi -= 1 << 32
    if lo >= 1 << 31:
        lo -= 1 << 32
    return hi, lo


_TS_EPOCH = None


def _days_from_civil(y: int, m: int, d: int) -> int:
    """Days since 1970-01-01 for a proleptic-Gregorian civil date
    (Howard Hinnant's civil_from_days inverse — pure int arithmetic)."""
    y -= m <= 2
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m - 3 if m > 2 else m + 9) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def _fast_iso_key(s: str) -> "Optional[tuple[int, int]]":
    """Direct key for the exact 'YYYY-MM-DDTHH:MM:SSZ' form — the dominant
    shape in request corpora. None (caller falls back to the CEL
    conversion) for anything else, INCLUDING values the CEL function would
    reject, so error behavior is identical. Equivalence with the generic
    path is pinned by tests/test_fastpred.py::test_fast_iso_key."""
    if (
        len(s) != 20
        or not s.isascii()
        or s[4] != "-" or s[7] != "-" or s[10] != "T"
        or s[13] != ":" or s[16] != ":" or s[19] != "Z"
    ):
        return None
    ys, mos, ds, hs, mis, ss = s[0:4], s[5:7], s[8:10], s[11:13], s[14:16], s[17:19]
    if not (
        ys.isdigit() and mos.isdigit() and ds.isdigit()
        and hs.isdigit() and mis.isdigit() and ss.isdigit()
    ):
        return None
    y, mo, d = int(ys), int(mos), int(ds)
    h, mi, sec = int(hs), int(mis), int(ss)
    if not (1 <= y <= 9999 and 1 <= mo <= 12 and h < 24 and mi < 60 and sec < 60):
        return None
    dim = _DAYS_IN_MONTH[mo - 1]
    if mo == 2 and (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)):
        dim = 29
    if not (1 <= d <= dim):
        return None
    micros = (_days_from_civil(y, mo, d) * 86400 + h * 3600 + mi * 60 + sec) * 1_000_000
    return split_key((micros + (1 << 63)) & ((1 << 64) - 1))


def timestamp_key(v: Any) -> tuple[int, int]:
    """CEL-convertible timestamp value → order-preserving (hi, lo) i32 pair.

    Uses the same conversion as the CEL runtime's ``timestamp()`` overloads
    (str RFC3339 / int epoch-seconds / Timestamp), then maps exact epoch
    MICROseconds (int arithmetic — no float rounding at far dates) onto the
    signed-biased key space device kernels compare. Raises on anything the
    CEL function would reject."""
    global _TS_EPOCH
    import datetime as _dt

    if type(v) is str:
        k = _fast_iso_key(v)
        if k is not None:
            return k

    from ..cel.stdlib import _to_timestamp

    ts = _to_timestamp(v)
    if _TS_EPOCH is None:
        _TS_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
    micros = (ts - _TS_EPOCH) // _dt.timedelta(microseconds=1)
    return split_key((micros + (1 << 63)) & ((1 << 64) - 1))


class StringInterner:
    """Batch-local string → i32 id (0 reserved for 'absent')."""

    def __init__(self) -> None:
        self.ids: dict[str, int] = {}

    def intern(self, s: str) -> int:
        i = self.ids.get(s)
        if i is None:
            i = len(self.ids) + 1
            self.ids[s] = i
        return i


@dataclass
class ColumnBatch:
    """Encoded columns for one batch: path → arrays of shape [B]."""

    size: int
    tags: dict[tuple, np.ndarray] = field(default_factory=dict)
    his: dict[tuple, np.ndarray] = field(default_factory=dict)
    los: dict[tuple, np.ndarray] = field(default_factory=dict)
    sids: dict[tuple, np.ndarray] = field(default_factory=dict)
    nans: dict[tuple, np.ndarray] = field(default_factory=dict)
    # host-evaluated predicate columns: pred_id -> (val[B], err[B])
    pred_vals: dict[int, np.ndarray] = field(default_factory=dict)
    pred_errs: dict[int, np.ndarray] = field(default_factory=dict)
    # string-list membership columns: path -> sids [B, L] / state [B]
    # (state 0=missing, 1=ok, 2=error)
    list_sids: dict[tuple, np.ndarray] = field(default_factory=dict)
    list_states: dict[tuple, np.ndarray] = field(default_factory=dict)
    # parsed-timestamp columns for paths used inside timestamp(...) calls:
    # path -> key halves [B] + state [B] (0=missing, 1=ok, 2=error)
    ts_his: dict[tuple, np.ndarray] = field(default_factory=dict)
    ts_los: dict[tuple, np.ndarray] = field(default_factory=dict)
    ts_states: dict[tuple, np.ndarray] = field(default_factory=dict)
    # request-stable now() as a batch-constant key (0-d arrays: value varies
    # per batch without retriggering jit tracing)
    now_hi: np.ndarray = field(default_factory=lambda: np.zeros((), dtype=np.int32))
    now_lo: np.ndarray = field(default_factory=lambda: np.zeros((), dtype=np.int32))


def resolve_path(input_obj: Any, path: tuple[str, ...]) -> tuple[bool, Any]:
    """Walk a path (e.g. ('resource','attr','status')) through a CheckInput.

    Returns (present, value). Intermediate misses → absent.
    """
    cur: Any = input_obj
    for seg in path:
        if isinstance(cur, dict):
            if seg not in cur:
                return False, None
            cur = cur[seg]
        else:
            if not hasattr(cur, seg):
                return False, None
            cur = getattr(cur, seg)
    return True, cur


def encode_value(v: Any, present: bool, interner: StringInterner) -> tuple[int, int, int, int, bool]:
    """→ (tag, hi, lo, sid, is_nan)."""
    if not present:
        return TAG_MISSING, 0, 0, 0, False
    if v is None:
        return TAG_NULL, 0, 0, 0, False
    if isinstance(v, bool):
        return TAG_BOOL, 1 if v else 0, 0, 0, False
    if isinstance(v, (int, float)):
        f = float(v)
        if f != f:
            return TAG_NUM, 0, 0, 0, True
        hi, lo = split_key(double_key(f))
        return TAG_NUM, hi, lo, 0, False
    if isinstance(v, str):
        return TAG_STR, 0, 0, interner.intern(v), False
    return TAG_OTHER, 0, 0, 0, False
