"""Observability: structured logging, spans, runtime level switching.

Behavioral reference: internal/observability — zap structured logging with
named loggers and SIGUSR1/SIGUSR2 runtime level toggling
(logging/signal.go), span instrumentation at every layer (tracing.StartSpan),
OTLP export configured from OTEL_* env vars. Without egress, spans export to
the structured log (an OTLP exporter slots into SpanExporter when the
collector is reachable); metrics are served by the HTTP listener at
/_cerbos/metrics.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out, default=str)


def init_logging(level: str = "info", fmt: str = "json") -> None:
    root = logging.getLogger("cerbos_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s"))
    root.handlers[:] = [handler]

    # SIGUSR1 raises verbosity, SIGUSR2 restores it (ref: logging/signal.go)
    if hasattr(signal, "SIGUSR1"):
        base_level = root.level

        def to_debug(_sig, _frm):
            root.setLevel(logging.DEBUG)

        def restore(_sig, _frm):
            root.setLevel(base_level)

        with contextlib.suppress(ValueError):  # non-main threads can't set handlers
            signal.signal(signal.SIGUSR1, to_debug)
            signal.signal(signal.SIGUSR2, restore)


# ---------------------------------------------------------------------------
# spans


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    parent_id: str = ""
    start: float = field(default_factory=time.perf_counter)
    attributes: dict[str, Any] = field(default_factory=dict)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value


class SpanExporter:
    """Export finished spans; the default sink is the debug log. An OTLP
    exporter implements the same single-method interface."""

    def export(self, span: Span, duration_ms: float) -> None:
        logging.getLogger("cerbos_tpu.tracing").debug(
            "span %s", span.name,
            extra={"fields": {"traceId": span.trace_id, "spanId": span.span_id,
                              "parentId": span.parent_id, "durationMs": round(duration_ms, 3),
                              **span.attributes}},
        )


class OTLPSpanExporter(SpanExporter):
    """OTLP/HTTP JSON exporter (ref: internal/observability/otel/{otel,traces}.go
    — the reference configures OTLP from standard OTEL_* env vars; same here:
    OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_SERVICE_NAME). Spans batch in memory
    and flush to {endpoint}/v1/traces on a background thread; export failures
    drop the batch (observability must never block the request path)."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        flush_interval_s: float = 5.0,
        max_batch: int = 512,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.max_batch = max_batch
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._interval = flush_interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-exporter")
        self._thread.start()

    def export(self, span: Span, duration_ms: float) -> None:
        now_ns = time.time_ns()
        otlp_span = {
            "traceId": span.trace_id[:32].ljust(32, "0"),
            "spanId": span.span_id[:16].ljust(16, "0"),
            "parentSpanId": span.parent_id[:16].ljust(16, "0") if span.parent_id else "",
            "name": span.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(now_ns - int(duration_ms * 1e6)),
            "endTimeUnixNano": str(now_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}} for k, v in span.attributes.items()
            ],
        }
        with self._lock:
            self._buf.append(otlp_span)
            if len(self._buf) > self.max_batch * 4:
                del self._buf[: -self.max_batch]  # bounded: drop oldest

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch:]
        payload = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeSpans": [{"scope": {"name": "cerbos_tpu"}, "spans": batch}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001  (collector down: drop, don't block)
            logging.getLogger("cerbos_tpu.tracing").debug("otlp export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        # drain everything still buffered, one batch per flush
        while True:
            with self._lock:
                if not self._buf:
                    return
            self.flush()


def init_otlp_from_env() -> bool:
    """Ref: otel.go — standard env wiring. Returns True when enabled."""
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return False
    set_exporter(
        OTLPSpanExporter(endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu"))
    )
    return True


_exporter: SpanExporter = SpanExporter()
_current: dict[int, Span] = {}  # thread id -> active span


def set_exporter(exporter: SpanExporter) -> None:
    global _exporter
    _exporter = exporter


def close_exporter() -> None:
    """Drain + stop the active exporter if it supports it (shutdown path)."""
    close = getattr(_exporter, "close", None)
    if close is not None:
        close()


@contextlib.contextmanager
def start_span(name: str, **attributes: Any) -> Iterator[Span]:
    tid = threading.get_ident()
    parent = _current.get(tid)
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex,
        parent_id=parent.span_id if parent else "",
        attributes=dict(attributes),
    )
    _current[tid] = span
    try:
        yield span
    finally:
        if parent is None:
            _current.pop(tid, None)
        else:
            _current[tid] = parent
        _exporter.export(span, (time.perf_counter() - span.start) * 1000)


class OTLPMetricsExporter:
    """OTLP/HTTP JSON metrics exporter (ref: internal/observability/metrics —
    the reference exports OTel metrics; Prometheus scrape stays at
    /_cerbos/metrics, this pushes the same series to an OTLP collector).
    Metric sources are callables returning {name: value}; gauges snapshot on
    a background interval and POST to {endpoint}/v1/metrics. Export failures
    drop the snapshot — metrics must never block serving."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        interval_s: float = 15.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self._sources: list[Any] = []
        self._stop = threading.Event()
        self._interval = interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-metrics")
        self._thread.start()

    def add_source(self, fn) -> None:
        self._sources.append(fn)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def collect(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for fn in list(self._sources):
            try:
                out.update(fn())
            except Exception:  # noqa: BLE001
                logging.getLogger("cerbos_tpu.metrics").debug("metrics source failed", exc_info=True)
        return out

    def flush(self) -> None:
        series = self.collect()
        if not series:
            return
        now_ns = str(time.time_ns())
        metrics = [
            {
                "name": name,
                "gauge": {"dataPoints": [{"asDouble": float(v), "timeUnixNano": now_ns}]},
            }
            for name, v in sorted(series.items())
        ]
        payload = json.dumps(
            {
                "resourceMetrics": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeMetrics": [{"scope": {"name": "cerbos_tpu"}, "metrics": metrics}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/metrics",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001
            logging.getLogger("cerbos_tpu.metrics").debug("otlp metrics export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        self.flush()


# ---------------------------------------------------------------------------
# metrics registry (Prometheus text exposition)


class Counter:
    """Monotonic counter; rendered as a Prometheus ``counter``."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> list[str]:
        return [f"# TYPE {self.name} counter", f"{self.name} {_fmt(self._value)}"]

    def series(self) -> dict[str, float]:
        return {self.name: self._value}


class Gauge:
    """Point-in-time value; ``track_max`` also exports ``<name>_peak``."""

    __slots__ = ("name", "help", "_value", "_peak", "track_max", "_lock")

    def __init__(self, name: str, help: str = "", track_max: bool = False):
        self.name = name
        self.help = help
        self._value = 0.0
        self._peak = 0.0
        self.track_max = track_max
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._peak:
                self._peak = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            if self._value > self._peak:
                self._peak = self._value

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    @property
    def peak(self) -> float:
        return self._peak

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} gauge", f"{self.name} {_fmt(self._value)}"]
        if self.track_max:
            out += [f"# TYPE {self.name}_peak gauge", f"{self.name}_peak {_fmt(self._peak)}"]
        return out

    def series(self) -> dict[str, float]:
        out = {self.name: self._value}
        if self.track_max:
            out[f"{self.name}_peak"] = self._peak
        return out


class Histogram:
    """Fixed-bucket histogram with Prometheus cumulative-bucket exposition."""

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, help: str = "", buckets: Optional[list[float]] = None):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets or [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0])
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def render(self) -> list[str]:
        out = [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            out.append(f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {self._count}')
        out.append(f"{self.name}_sum {_fmt(self._sum)}")
        out.append(f"{self.name}_count {self._count}")
        return out

    def series(self) -> dict[str, float]:
        return {f"{self.name}_sum": self._sum, f"{self.name}_count": float(self._count)}


class CounterVec:
    """Counter with one label dimension; each label value gets a child
    series rendered as ``name{label="value"} n``. ``value`` sums all
    children so callers that read the unlabeled total (back-compat with
    the plain Counter this may replace) keep working."""

    __slots__ = ("name", "help", "label", "_children", "_lock")

    def __init__(self, name: str, help: str = "", label: str = "reason"):
        self.name = name
        self.help = help
        self.label = label
        self._children: dict[str, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: str = "", n: float = 1.0) -> None:
        with self._lock:
            self._children[value] = self._children.get(value, 0.0) + n

    def get(self, value: str = "") -> float:
        with self._lock:
            return self._children.get(value, 0.0)

    @property
    def value(self) -> float:
        with self._lock:
            return sum(self._children.values())

    def render(self) -> list[str]:
        with self._lock:
            children = sorted(self._children.items())
        out = [f"# TYPE {self.name} counter"]
        if not children:
            out.append(f"{self.name} 0")
        for label_value, v in children:
            out.append(f'{self.name}{{{self.label}="{label_value}"}} {_fmt(v)}')
        return out

    def series(self) -> dict[str, float]:
        with self._lock:
            children = dict(self._children)
        return {f"{self.name}_{lv}" if lv else self.name: v for lv, v in children.items()}


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricsRegistry:
    """Process-wide named metrics; get-or-create so forked workers and
    re-initialized cores share one instrument per name."""

    def __init__(self):
        self._metrics: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help))

    def counter_vec(self, name: str, help: str = "", label: str = "reason") -> CounterVec:
        with self._lock:
            m = self._metrics.get(name)
            if isinstance(m, Counter):
                # a plain Counter was registered under this name first (e.g.
                # a reader touched it before the owner): upgrade in place,
                # preserving the accumulated total under the empty label
                vec = CounterVec(name, help or m.help, label=label)
                if m.value:
                    vec.inc("", m.value)
                self._metrics[name] = vec
                return vec
            if m is None:
                m = CounterVec(name, help, label=label)
                self._metrics[name] = m
            return m

    def gauge(self, name: str, help: str = "", track_max: bool = False) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help, track_max=track_max))

    def histogram(self, name: str, help: str = "", buckets: Optional[list[float]] = None) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help, buckets=buckets))

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: list[str] = []
        for _, m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict[str, float]:
        """Flat gauge view for the OTLP metrics exporter sources."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, float] = {}
        for m in metrics:
            out.update(m.series())
        return out


_registry = MetricsRegistry()


def metrics() -> MetricsRegistry:
    return _registry


_metrics_exporter: "OTLPMetricsExporter | None" = None


def init_otlp_metrics_from_env() -> "OTLPMetricsExporter | None":
    """OTEL_EXPORTER_OTLP_METRICS_ENDPOINT / OTEL_EXPORTER_OTLP_ENDPOINT."""
    global _metrics_exporter
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return None
    _metrics_exporter = OTLPMetricsExporter(
        endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu")
    )
    return _metrics_exporter


def metrics_exporter() -> "OTLPMetricsExporter | None":
    return _metrics_exporter


def close_metrics_exporter() -> None:
    global _metrics_exporter
    if _metrics_exporter is not None:
        _metrics_exporter.close()
        _metrics_exporter = None
