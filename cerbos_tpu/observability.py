"""Observability: structured logging, spans, runtime level switching.

Behavioral reference: internal/observability — zap structured logging with
named loggers and SIGUSR1/SIGUSR2 runtime level toggling
(logging/signal.go), span instrumentation at every layer (tracing.StartSpan),
OTLP export configured from OTEL_* env vars. Without egress, spans export to
the structured log (an OTLP exporter slots into SpanExporter when the
collector is reachable); metrics are served by the HTTP listener at
/_cerbos/metrics.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out, default=str)


def init_logging(level: str = "info", fmt: str = "json") -> None:
    root = logging.getLogger("cerbos_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s"))
    root.handlers[:] = [handler]

    # SIGUSR1 raises verbosity, SIGUSR2 restores it (ref: logging/signal.go)
    if hasattr(signal, "SIGUSR1"):
        base_level = root.level

        def to_debug(_sig, _frm):
            root.setLevel(logging.DEBUG)

        def restore(_sig, _frm):
            root.setLevel(base_level)

        with contextlib.suppress(ValueError):  # non-main threads can't set handlers
            signal.signal(signal.SIGUSR1, to_debug)
            signal.signal(signal.SIGUSR2, restore)


# ---------------------------------------------------------------------------
# spans


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    parent_id: str = ""
    start: float = field(default_factory=time.perf_counter)
    attributes: dict[str, Any] = field(default_factory=dict)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value


class SpanExporter:
    """Export finished spans; the default sink is the debug log. An OTLP
    exporter implements the same single-method interface."""

    def export(self, span: Span, duration_ms: float) -> None:
        logging.getLogger("cerbos_tpu.tracing").debug(
            "span %s", span.name,
            extra={"fields": {"traceId": span.trace_id, "spanId": span.span_id,
                              "parentId": span.parent_id, "durationMs": round(duration_ms, 3),
                              **span.attributes}},
        )


class OTLPSpanExporter(SpanExporter):
    """OTLP/HTTP JSON exporter (ref: internal/observability/otel/{otel,traces}.go
    — the reference configures OTLP from standard OTEL_* env vars; same here:
    OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_SERVICE_NAME). Spans batch in memory
    and flush to {endpoint}/v1/traces on a background thread; export failures
    drop the batch (observability must never block the request path)."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        flush_interval_s: float = 5.0,
        max_batch: int = 512,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.max_batch = max_batch
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._interval = flush_interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-exporter")
        self._thread.start()

    def export(self, span: Span, duration_ms: float) -> None:
        now_ns = time.time_ns()
        otlp_span = {
            "traceId": span.trace_id[:32].ljust(32, "0"),
            "spanId": span.span_id[:16].ljust(16, "0"),
            "parentSpanId": span.parent_id[:16].ljust(16, "0") if span.parent_id else "",
            "name": span.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(now_ns - int(duration_ms * 1e6)),
            "endTimeUnixNano": str(now_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}} for k, v in span.attributes.items()
            ],
        }
        with self._lock:
            self._buf.append(otlp_span)
            if len(self._buf) > self.max_batch * 4:
                del self._buf[: -self.max_batch]  # bounded: drop oldest

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch:]
        payload = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeSpans": [{"scope": {"name": "cerbos_tpu"}, "spans": batch}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001  (collector down: drop, don't block)
            logging.getLogger("cerbos_tpu.tracing").debug("otlp export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        # drain everything still buffered, one batch per flush
        while True:
            with self._lock:
                if not self._buf:
                    return
            self.flush()


def init_otlp_from_env() -> bool:
    """Ref: otel.go — standard env wiring. Returns True when enabled."""
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return False
    set_exporter(
        OTLPSpanExporter(endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu"))
    )
    return True


_exporter: SpanExporter = SpanExporter()
_current: dict[int, Span] = {}  # thread id -> active span


def set_exporter(exporter: SpanExporter) -> None:
    global _exporter
    _exporter = exporter


def close_exporter() -> None:
    """Drain + stop the active exporter if it supports it (shutdown path)."""
    close = getattr(_exporter, "close", None)
    if close is not None:
        close()


@contextlib.contextmanager
def start_span(name: str, **attributes: Any) -> Iterator[Span]:
    tid = threading.get_ident()
    parent = _current.get(tid)
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex,
        parent_id=parent.span_id if parent else "",
        attributes=dict(attributes),
    )
    _current[tid] = span
    try:
        yield span
    finally:
        if parent is None:
            _current.pop(tid, None)
        else:
            _current[tid] = parent
        _exporter.export(span, (time.perf_counter() - span.start) * 1000)


class OTLPMetricsExporter:
    """OTLP/HTTP JSON metrics exporter (ref: internal/observability/metrics —
    the reference exports OTel metrics; Prometheus scrape stays at
    /_cerbos/metrics, this pushes the same series to an OTLP collector).
    Metric sources are callables returning {name: value}; gauges snapshot on
    a background interval and POST to {endpoint}/v1/metrics. Export failures
    drop the snapshot — metrics must never block serving."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        interval_s: float = 15.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self._sources: list[Any] = []
        self._stop = threading.Event()
        self._interval = interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-metrics")
        self._thread.start()

    def add_source(self, fn) -> None:
        self._sources.append(fn)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def collect(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for fn in list(self._sources):
            try:
                out.update(fn())
            except Exception:  # noqa: BLE001
                logging.getLogger("cerbos_tpu.metrics").debug("metrics source failed", exc_info=True)
        return out

    def flush(self) -> None:
        series = self.collect()
        if not series:
            return
        now_ns = str(time.time_ns())
        metrics = [
            {
                "name": name,
                "gauge": {"dataPoints": [{"asDouble": float(v), "timeUnixNano": now_ns}]},
            }
            for name, v in sorted(series.items())
        ]
        payload = json.dumps(
            {
                "resourceMetrics": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeMetrics": [{"scope": {"name": "cerbos_tpu"}, "metrics": metrics}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/metrics",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001
            logging.getLogger("cerbos_tpu.metrics").debug("otlp metrics export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        self.flush()


_metrics_exporter: "OTLPMetricsExporter | None" = None


def init_otlp_metrics_from_env() -> "OTLPMetricsExporter | None":
    """OTEL_EXPORTER_OTLP_METRICS_ENDPOINT / OTEL_EXPORTER_OTLP_ENDPOINT."""
    global _metrics_exporter
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return None
    _metrics_exporter = OTLPMetricsExporter(
        endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu")
    )
    return _metrics_exporter


def metrics_exporter() -> "OTLPMetricsExporter | None":
    return _metrics_exporter


def close_metrics_exporter() -> None:
    global _metrics_exporter
    if _metrics_exporter is not None:
        _metrics_exporter.close()
        _metrics_exporter = None
