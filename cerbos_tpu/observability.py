"""Observability: structured logging, spans, runtime level switching.

Behavioral reference: internal/observability — zap structured logging with
named loggers and SIGUSR1/SIGUSR2 runtime level toggling
(logging/signal.go), span instrumentation at every layer (tracing.StartSpan),
OTLP export configured from OTEL_* env vars. Without egress, spans export to
the structured log (an OTLP exporter slots into SpanExporter when the
collector is reachable); metrics are served by the HTTP listener at
/_cerbos/metrics.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out, default=str)


def init_logging(level: str = "info", fmt: str = "json") -> None:
    root = logging.getLogger("cerbos_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s"))
    root.handlers[:] = [handler]

    # SIGUSR1 raises verbosity, SIGUSR2 restores it (ref: logging/signal.go)
    if hasattr(signal, "SIGUSR1"):
        base_level = root.level

        def to_debug(_sig, _frm):
            root.setLevel(logging.DEBUG)

        def restore(_sig, _frm):
            root.setLevel(base_level)

        with contextlib.suppress(ValueError):  # non-main threads can't set handlers
            signal.signal(signal.SIGUSR1, to_debug)
            signal.signal(signal.SIGUSR2, restore)


# ---------------------------------------------------------------------------
# spans


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    parent_id: str = ""
    start: float = field(default_factory=time.perf_counter)
    attributes: dict[str, Any] = field(default_factory=dict)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value


class SpanExporter:
    """Export finished spans; the default sink is the debug log. An OTLP
    exporter implements the same single-method interface."""

    def export(self, span: Span, duration_ms: float) -> None:
        logging.getLogger("cerbos_tpu.tracing").debug(
            "span %s", span.name,
            extra={"fields": {"traceId": span.trace_id, "spanId": span.span_id,
                              "parentId": span.parent_id, "durationMs": round(duration_ms, 3),
                              **span.attributes}},
        )


_exporter: SpanExporter = SpanExporter()
_current: dict[int, Span] = {}  # thread id -> active span


def set_exporter(exporter: SpanExporter) -> None:
    global _exporter
    _exporter = exporter


@contextlib.contextmanager
def start_span(name: str, **attributes: Any) -> Iterator[Span]:
    import threading

    tid = threading.get_ident()
    parent = _current.get(tid)
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex,
        parent_id=parent.span_id if parent else "",
        attributes=dict(attributes),
    )
    _current[tid] = span
    try:
        yield span
    finally:
        if parent is None:
            _current.pop(tid, None)
        else:
            _current[tid] = parent
        _exporter.export(span, (time.perf_counter() - span.start) * 1000)
