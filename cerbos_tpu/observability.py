"""Observability: structured logging, spans, runtime level switching.

Behavioral reference: internal/observability — zap structured logging with
named loggers and SIGUSR1/SIGUSR2 runtime level toggling
(logging/signal.go), span instrumentation at every layer (tracing.StartSpan),
OTLP export configured from OTEL_* env vars. Without egress, spans export to
the structured log (an OTLP exporter slots into SpanExporter when the
collector is reachable); metrics are served by the HTTP listener at
/_cerbos/metrics.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out, default=str)


def init_logging(level: str = "info", fmt: str = "json") -> None:
    root = logging.getLogger("cerbos_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s"))
    root.handlers[:] = [handler]

    # SIGUSR1 raises verbosity, SIGUSR2 restores it (ref: logging/signal.go)
    if hasattr(signal, "SIGUSR1"):
        base_level = root.level

        def to_debug(_sig, _frm):
            root.setLevel(logging.DEBUG)

        def restore(_sig, _frm):
            root.setLevel(base_level)

        with contextlib.suppress(ValueError):  # non-main threads can't set handlers
            signal.signal(signal.SIGUSR1, to_debug)
            signal.signal(signal.SIGUSR2, restore)


# ---------------------------------------------------------------------------
# spans


def new_trace_id() -> str:
    """A proper W3C trace id: 32 lowercase hex chars, never all-zero."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A proper W3C span id: 16 lowercase hex chars, never all-zero."""
    return os.urandom(8).hex()


@dataclass(frozen=True)
class SpanContext:
    """Detachable identity of a span: everything needed to parent or link a
    span created on another thread (the batcher hop) or emitted by a remote
    caller (W3C ``traceparent``)."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


_TRACEPARENT_RX = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})-"
    r"(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """W3C trace-context ``traceparent`` → SpanContext, or None when the
    header is absent or malformed (per spec, a bad header is ignored and the
    receiver starts a fresh trace)."""
    if not header:
        return None
    m = _TRACEPARENT_RX.match(header.strip().lower())
    if m is None or m.group("version") == "ff":
        return None
    trace_id, span_id = m.group("trace_id"), m.group("span_id")
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, sampled=bool(int(m.group("flags"), 16) & 0x01))


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_id: str = ""
    start: float = field(default_factory=time.perf_counter)
    # wall-clock capture at span START so a late-flushed OTLP export carries
    # the true start time instead of deriving it backwards from export time
    start_wall_ns: int = field(default_factory=time.time_ns)
    attributes: dict[str, Any] = field(default_factory=dict)
    links: list[SpanContext] = field(default_factory=list)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_link(self, ctx: SpanContext) -> None:
        self.links.append(ctx)

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)


class SpanExporter:
    """Export finished spans; the default sink is the debug log. An OTLP
    exporter implements the same single-method interface."""

    def export(self, span: Span, duration_ms: float) -> None:
        logging.getLogger("cerbos_tpu.tracing").debug(
            "span %s", span.name,
            extra={"fields": {"traceId": span.trace_id, "spanId": span.span_id,
                              "parentId": span.parent_id, "durationMs": round(duration_ms, 3),
                              **span.attributes}},
        )


class OTLPSpanExporter(SpanExporter):
    """OTLP/HTTP JSON exporter (ref: internal/observability/otel/{otel,traces}.go
    — the reference configures OTLP from standard OTEL_* env vars; same here:
    OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_SERVICE_NAME). Spans batch in memory
    and flush to {endpoint}/v1/traces on a background thread; export failures
    drop the batch (observability must never block the request path)."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        flush_interval_s: float = 5.0,
        max_batch: int = 512,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.max_batch = max_batch
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._interval = flush_interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-exporter")
        self._thread.start()

    def export(self, span: Span, duration_ms: float) -> None:
        # ids are generated as proper 32/16-hex W3C ids at span creation;
        # export them verbatim (padding short ids here would fabricate ids
        # that collide across spans), and timestamps come from the span's
        # wall-clock START capture, not from flush time
        start_ns = span.start_wall_ns
        otlp_span = {
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "parentSpanId": span.parent_id,
            "name": span.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + int(duration_ms * 1e6)),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}} for k, v in span.attributes.items()
            ],
        }
        if span.links:
            otlp_span["links"] = [
                {"traceId": l.trace_id, "spanId": l.span_id} for l in span.links
            ]
        with self._lock:
            self._buf.append(otlp_span)
            if len(self._buf) > self.max_batch * 4:
                del self._buf[: -self.max_batch]  # bounded: drop oldest

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch:]
        payload = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeSpans": [{"scope": {"name": "cerbos_tpu"}, "spans": batch}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001  (collector down: drop, don't block)
            logging.getLogger("cerbos_tpu.tracing").debug("otlp export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        # drain everything still buffered, one batch per flush
        while True:
            with self._lock:
                if not self._buf:
                    return
            self.flush()


def init_otlp_from_env() -> bool:
    """Ref: otel.go — standard env wiring. Returns True when enabled."""
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return False
    set_exporter(
        OTLPSpanExporter(endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu"))
    )
    return True


_exporter: SpanExporter = SpanExporter()
_current: dict[int, Span] = {}  # thread id -> active span


def set_exporter(exporter: SpanExporter) -> None:
    global _exporter
    _exporter = exporter


def close_exporter() -> None:
    """Drain + stop the active exporter if it supports it (shutdown path)."""
    close = getattr(_exporter, "close", None)
    if close is not None:
        close()


def current_span_context() -> Optional[SpanContext]:
    """Detach the active span's identity so another thread can parent or
    link to it (span parenting via ``_current`` is thread-local; the batcher
    hop carries this snapshot in ``_Pending`` instead)."""
    span = _current.get(threading.get_ident())
    return span.context if span is not None else None


@contextlib.contextmanager
def start_span(
    name: str,
    parent: "SpanContext | Span | None" = None,
    links: Optional[list[SpanContext]] = None,
    **attributes: Any,
) -> Iterator[Span]:
    """Open a span. Parenting is thread-local by default; pass ``parent=`` —
    a SpanContext detached via :func:`current_span_context` or parsed from a
    remote ``traceparent`` — to join a trace across a thread hop or an RPC
    boundary. ``links=`` attaches non-parent causal references (a device
    batch links every co-batched request's trace)."""
    tid = threading.get_ident()
    prev = _current.get(tid)
    eff_parent: "SpanContext | Span | None" = parent if parent is not None else prev
    span = Span(
        name=name,
        trace_id=eff_parent.trace_id if eff_parent else new_trace_id(),
        parent_id=eff_parent.span_id if eff_parent else "",
        attributes=dict(attributes),
        links=list(links or ()),
    )
    _current[tid] = span
    try:
        yield span
    finally:
        if prev is None:
            _current.pop(tid, None)
        else:
            _current[tid] = prev
        _exporter.export(span, (time.perf_counter() - span.start) * 1000)


def export_span(
    name: str,
    parent: Optional[SpanContext],
    start_wall_ns: int,
    duration_s: float,
    links: Optional[list[SpanContext]] = None,
    **attributes: Any,
) -> Span:
    """Synthesize and export a span for an interval measured elsewhere (the
    in-flight device window has no thread executing it; the batcher stamps
    its start/end around submit/collect instead)."""
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else new_trace_id(),
        parent_id=parent.span_id if parent else "",
        start_wall_ns=start_wall_ns,
        attributes=dict(attributes),
        links=list(links or ()),
    )
    _exporter.export(span, duration_s * 1000)
    return span


class OTLPMetricsExporter:
    """OTLP/HTTP JSON metrics exporter (ref: internal/observability/metrics —
    the reference exports OTel metrics; Prometheus scrape stays at
    /_cerbos/metrics, this pushes the same series to an OTLP collector).
    Metric sources are callables returning {name: value}; gauges snapshot on
    a background interval and POST to {endpoint}/v1/metrics. Export failures
    drop the snapshot — metrics must never block serving."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "cerbos-tpu",
        interval_s: float = 15.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self._sources: list[Any] = []
        self._stop = threading.Event()
        self._interval = interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="otlp-metrics")
        self._thread.start()

    def add_source(self, fn) -> None:
        self._sources.append(fn)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def collect(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for fn in list(self._sources):
            try:
                out.update(fn())
            except Exception:  # noqa: BLE001
                logging.getLogger("cerbos_tpu.metrics").debug("metrics source failed", exc_info=True)
        return out

    def flush(self) -> None:
        series = self.collect()
        if not series:
            return
        now_ns = str(time.time_ns())
        metrics = [
            {
                "name": name,
                "gauge": {"dataPoints": [{"asDouble": float(v), "timeUnixNano": now_ns}]},
            }
            for name, v in sorted(series.items())
        ]
        payload = json.dumps(
            {
                "resourceMetrics": [
                    {
                        "resource": {
                            "attributes": [
                                {"key": "service.name", "value": {"stringValue": self.service_name}}
                            ]
                        },
                        "scopeMetrics": [{"scope": {"name": "cerbos_tpu"}, "metrics": metrics}],
                    }
                ]
            }
        ).encode()
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v1/metrics",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001
            logging.getLogger("cerbos_tpu.metrics").debug("otlp metrics export failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        self.flush()


# ---------------------------------------------------------------------------
# metrics registry (Prometheus text exposition)


class Counter:
    """Monotonic counter; rendered as a Prometheus ``counter``."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> list[str]:
        with self._lock:
            v = self._value
        return [f"# TYPE {self.name} counter", f"{self.name} {_fmt(v)}"]

    def series(self) -> dict[str, float]:
        with self._lock:
            return {self.name: self._value}


class Gauge:
    """Point-in-time value; ``track_max`` also exports ``<name>_peak``."""

    __slots__ = ("name", "help", "_value", "_peak", "track_max", "_lock")

    def __init__(self, name: str, help: str = "", track_max: bool = False):
        self.name = name
        self.help = help
        self._value = 0.0
        self._peak = 0.0
        self.track_max = track_max
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._peak:
                self._peak = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            if self._value > self._peak:
                self._peak = self._value

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    @property
    def peak(self) -> float:
        return self._peak

    def render(self) -> list[str]:
        with self._lock:  # value/peak must come from one consistent snapshot
            v, peak = self._value, self._peak
        out = [f"# TYPE {self.name} gauge", f"{self.name} {_fmt(v)}"]
        if self.track_max:
            out += [f"# TYPE {self.name}_peak gauge", f"{self.name}_peak {_fmt(peak)}"]
        return out

    def series(self) -> dict[str, float]:
        with self._lock:
            v, peak = self._value, self._peak
        out = {self.name: v}
        if self.track_max:
            out[f"{self.name}_peak"] = peak
        return out


class Histogram:
    """Fixed-bucket histogram with Prometheus cumulative-bucket exposition."""

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, help: str = "", buckets: Optional[list[float]] = None):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets or [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0])
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> tuple[list[int], float, int]:
        """(bucket counts, sum, count) captured under the lock — a render
        racing observe() must never expose cumulative buckets that don't sum
        to ``_count``."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def percentile(self, p: float) -> float:
        """Estimate the p-quantile (0..1) by linear interpolation within the
        owning bucket; the +Inf bucket clamps to the largest finite bound."""
        counts, _, count = self.snapshot()
        if count == 0:
            return 0.0
        rank = p * count
        cum = 0
        lo = 0.0
        for i, b in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                frac = (rank - prev) / counts[i] if counts[i] else 0.0
                return lo + (b - lo) * frac
            lo = b
        return self.buckets[-1] if self.buckets else 0.0

    def render(self, label: str = "") -> list[str]:
        counts, total, count = self.snapshot()
        sep = "," if label else ""
        out = [] if label else [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            out.append(f'{self.name}_bucket{{{label}{sep}le="{_fmt(b)}"}} {cum}')
        out.append(f'{self.name}_bucket{{{label}{sep}le="+Inf"}} {count}')
        suffix = f"{{{label}}}" if label else ""
        out.append(f"{self.name}_sum{suffix} {_fmt(total)}")
        out.append(f"{self.name}_count{suffix} {count}")
        return out

    def series(self) -> dict[str, float]:
        _, total, count = self.snapshot()
        return {f"{self.name}_sum": total, f"{self.name}_count": float(count)}


def _label_expr(label, key) -> str:
    """Render a label expression for a vec child. ``label`` is a name or a
    tuple of names (multi-dimension vecs, e.g. ``("stage", "shard")``);
    ``key`` is the matching value or tuple of values."""
    if isinstance(label, tuple):
        vals = key if isinstance(key, tuple) else (key,)
        return ",".join(f'{ln}="{lv}"' for ln, lv in zip(label, vals))
    return f'{label}="{key}"'


def _series_suffix(key) -> str:
    if isinstance(key, tuple):
        return "_".join(str(k) for k in key)
    return str(key)


class CounterVec:
    """Counter with one or more label dimensions; each label value (or value
    tuple, when ``label`` is a tuple of names) gets a child series rendered
    as ``name{label="value"} n``. ``value`` sums all children so callers
    that read the unlabeled total (back-compat with the plain Counter this
    may replace) keep working."""

    __slots__ = ("name", "help", "label", "_children", "_lock")

    def __init__(self, name: str, help: str = "", label: str = "reason"):
        self.name = name
        self.help = help
        self.label = label
        self._children: dict[str, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: str = "", n: float = 1.0) -> None:
        with self._lock:
            self._children[value] = self._children.get(value, 0.0) + n

    def get(self, value: str = "") -> float:
        with self._lock:
            return self._children.get(value, 0.0)

    @property
    def value(self) -> float:
        with self._lock:
            return sum(self._children.values())

    def render(self) -> list[str]:
        with self._lock:
            children = sorted(self._children.items(), key=lambda kv: str(kv[0]))
        out = [f"# TYPE {self.name} counter"]
        if not children:
            out.append(f"{self.name} 0")
        for label_value, v in children:
            out.append(f'{self.name}{{{_label_expr(self.label, label_value)}}} {_fmt(v)}')
        return out

    def series(self) -> dict[str, float]:
        with self._lock:
            children = dict(self._children)
        return {
            f"{self.name}_{_series_suffix(lv)}" if lv else self.name: v
            for lv, v in children.items()
        }


class GaugeVec:
    """Gauge with one label dimension; each label value gets a child Gauge
    rendered as ``name{label="value"} v``. ``labels()`` hands the caller the
    child Gauge itself, so hot paths bind once and then use the plain Gauge
    surface (``set``/``inc``/``value``/``peak``). Used for the per-shard
    occupancy/inflight/breaker-state series."""

    __slots__ = ("name", "help", "label", "track_max", "_children", "_lock")

    def __init__(self, name: str, help: str = "", label: str = "shard", track_max: bool = False):
        self.name = name
        self.help = help
        self.label = label
        self.track_max = track_max
        self._children: dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def labels(self, value: str) -> Gauge:
        with self._lock:
            child = self._children.get(value)
            if child is None:
                child = Gauge(self.name, self.help, track_max=self.track_max)
                self._children[value] = child
            return child

    def set(self, value: str, v: float) -> None:
        self.labels(value).set(v)

    def get(self, value: str) -> float:
        with self._lock:
            child = self._children.get(value)
        return child.value if child is not None else 0.0

    @property
    def value(self) -> float:
        """Sum over children — a read-alias for callers holding the name
        from before a Gauge→GaugeVec upgrade."""
        with self._lock:
            children = list(self._children.values())
        return sum(c.value for c in children)

    def render(self) -> list[str]:
        with self._lock:
            children = sorted(self._children.items(), key=lambda kv: str(kv[0]))
        out = [f"# TYPE {self.name} gauge"]
        peaks: list[str] = []
        for label_value, child in children:
            with child._lock:
                v, peak = child._value, child._peak
            expr = _label_expr(self.label, label_value)
            out.append(f"{self.name}{{{expr}}} {_fmt(v)}")
            if self.track_max:
                peaks.append(f"{self.name}_peak{{{expr}}} {_fmt(peak)}")
        if peaks:
            out.append(f"# TYPE {self.name}_peak gauge")
            out.extend(peaks)
        return out

    def series(self) -> dict[str, float]:
        with self._lock:
            children = sorted(self._children.items(), key=lambda kv: str(kv[0]))
        out: dict[str, float] = {}
        for label_value, child in children:
            with child._lock:
                v, peak = child._value, child._peak
            suffix = _series_suffix(label_value)
            out[f"{self.name}_{suffix}" if suffix else self.name] = v
            if self.track_max:
                out[f"{self.name}_peak_{suffix}" if suffix else f"{self.name}_peak"] = peak
        return out


class HistogramVec:
    """Histogram with one or more label dimensions; each label value (or
    value tuple, when ``label`` is a tuple of names like
    ``("stage", "shard")``) gets a child Histogram rendered as
    ``name_bucket{label="value",le="..."}``. Used for the per-stage
    device-path latency series so Grafana can do
    ``histogram_quantile(..., sum by (le, stage))`` over one instrument."""

    __slots__ = ("name", "help", "label", "buckets", "_children", "_lock")

    def __init__(
        self,
        name: str,
        help: str = "",
        label: str = "stage",
        buckets: Optional[list[float]] = None,
    ):
        self.name = name
        self.help = help
        self.label = label
        self.buckets = buckets
        self._children: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def labels(self, value: str) -> Histogram:
        with self._lock:
            child = self._children.get(value)
            if child is None:
                child = Histogram(self.name, self.help, buckets=self.buckets)
                self._children[value] = child
            return child

    def observe(self, value: str, v: float) -> None:
        self.labels(value).observe(v)

    def render(self) -> list[str]:
        with self._lock:
            children = sorted(self._children.items(), key=lambda kv: str(kv[0]))
        out = [f"# TYPE {self.name} histogram"]
        for label_value, child in children:
            out.extend(child.render(label=_label_expr(self.label, label_value)))
        return out

    def series(self) -> dict[str, float]:
        with self._lock:
            children = sorted(self._children.items(), key=lambda kv: str(kv[0]))
        out: dict[str, float] = {}
        for label_value, child in children:
            _, total, count = child.snapshot()
            suffix = _series_suffix(label_value)
            out[f"{self.name}_{suffix}_sum"] = total
            out[f"{self.name}_{suffix}_count"] = float(count)
        return out


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricsRegistry:
    """Process-wide named metrics; get-or-create so forked workers and
    re-initialized cores share one instrument per name."""

    def __init__(self):
        self._metrics: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory, want: tuple = (), help: str = ""):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif want and not isinstance(m, want):
                # one name must never serve two instrument types: the second
                # registrant would silently read/write the wrong semantics
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {want[0].__name__}"
                )
            elif help and not m.help:
                # a reader may have touched the name first with no help text;
                # the owning registration backfills it
                m.help = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        # CounterVec is an allowed read-alias: its .value sums all children,
        # so code holding the unlabeled total keeps working after an upgrade
        return self._get_or_create(
            name, lambda: Counter(name, help), want=(Counter, CounterVec), help=help
        )

    def counter_vec(self, name: str, help: str = "", label: str = "reason") -> CounterVec:
        with self._lock:
            m = self._metrics.get(name)
            if isinstance(m, Counter):
                # a plain Counter was registered under this name first (e.g.
                # a reader touched it before the owner): upgrade in place,
                # preserving the accumulated total under the empty label
                vec = CounterVec(name, help or m.help, label=label)
                if m.value:
                    vec.inc("", m.value)
                self._metrics[name] = vec
                return vec
            if m is None:
                m = CounterVec(name, help, label=label)
                self._metrics[name] = m
            elif not isinstance(m, CounterVec):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}, not CounterVec"
                )
            elif help and not m.help:
                m.help = help
            return m

    def gauge(self, name: str, help: str = "", track_max: bool = False) -> Gauge:
        # GaugeVec is an allowed read-alias: its .value sums all children,
        # so code holding the unlabeled total keeps working after an upgrade
        return self._get_or_create(
            name,
            lambda: Gauge(name, help, track_max=track_max),
            want=(Gauge, GaugeVec),
            help=help,
        )

    def gauge_vec(
        self, name: str, help: str = "", label: str = "shard", track_max: bool = False
    ) -> GaugeVec:
        with self._lock:
            m = self._metrics.get(name)
            if isinstance(m, Gauge):
                # a plain Gauge was registered under this name first (e.g. a
                # reader touched it before the owner): upgrade in place,
                # preserving the current value under the empty label
                vec = GaugeVec(name, help or m.help, label=label, track_max=track_max)
                if m.value or m.peak:
                    child = vec.labels("")
                    child.set(m.value)
                self._metrics[name] = vec
                return vec
            if m is None:
                m = GaugeVec(name, help, label=label, track_max=track_max)
                self._metrics[name] = m
            elif not isinstance(m, GaugeVec):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}, not GaugeVec"
                )
            elif help and not m.help:
                m.help = help
            return m

    def histogram(self, name: str, help: str = "", buckets: Optional[list[float]] = None) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help, buckets=buckets), want=(Histogram,), help=help
        )

    def histogram_vec(
        self,
        name: str,
        help: str = "",
        label: str = "stage",
        buckets: Optional[list[float]] = None,
    ) -> HistogramVec:
        return self._get_or_create(
            name,
            lambda: HistogramVec(name, help, label=label, buckets=buckets),
            want=(HistogramVec,),
            help=help,
        )

    def instruments(self) -> dict[str, Any]:
        """Snapshot of name → instrument (the metrics-lint walk)."""
        with self._lock:
            return dict(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: list[str] = []
        for _, m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict[str, float]:
        """Flat gauge view for the OTLP metrics exporter sources."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, float] = {}
        for m in metrics:
            out.update(m.series())
        return out


_registry = MetricsRegistry()


def metrics() -> MetricsRegistry:
    return _registry


_SAMPLE_LINE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})?\s+(\S+)$")
_FAMILY_COMMENT = re.compile(r"^# (TYPE|HELP) (\S+)")


def relabel_metrics_text(text: str, label: str, value: str) -> str:
    """Inject ``label="value"`` into every sample of a Prometheus text
    exposition. Worker pools use this to stamp each process's scrape with
    its identity: a scrape against the shared SO_REUSEPORT port lands on a
    random sibling, and without the label its series would silently alias
    the others' (docs/OBSERVABILITY.md, pooled scrape semantics)."""
    esc = value.replace("\\", "\\\\").replace('"', '\\"')
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_LINE.match(line)
        if m is None:
            out.append(line)
            continue
        name, labels, val = m.groups()
        inner = labels[1:-1] if labels else ""
        merged = f'{label}="{esc}"' + (f",{inner}" if inner else "")
        out.append(f"{name}{{{merged}}} {val}")
    return "\n".join(out) + ("\n" if out else "")


def merge_metrics_texts(primary: str, *others: str) -> str:
    """Concatenate Prometheus text expositions, dropping ``# TYPE``/``# HELP``
    lines for families the earlier texts already declared (duplicate family
    metadata is invalid exposition). Samples are never dropped — callers must
    have disambiguated them with :func:`relabel_metrics_text` first."""
    seen: set[tuple[str, str]] = set()
    out: list[str] = []
    for text in (primary, *others):
        for line in text.splitlines():
            m = _FAMILY_COMMENT.match(line)
            if m is not None:
                key = (m.group(1), m.group(2))
                if key in seen:
                    continue
                seen.add(key)
            out.append(line)
    return "\n".join(out) + ("\n" if out else "")


_metrics_exporter: "OTLPMetricsExporter | None" = None


def init_otlp_metrics_from_env() -> "OTLPMetricsExporter | None":
    """OTEL_EXPORTER_OTLP_METRICS_ENDPOINT / OTEL_EXPORTER_OTLP_ENDPOINT."""
    global _metrics_exporter
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if not endpoint:
        return None
    _metrics_exporter = OTLPMetricsExporter(
        endpoint, service_name=os.environ.get("OTEL_SERVICE_NAME", "cerbos-tpu")
    )
    return _metrics_exporter


def metrics_exporter() -> "OTLPMetricsExporter | None":
    return _metrics_exporter


def close_metrics_exporter() -> None:
    global _metrics_exporter
    if _metrics_exporter is not None:
        _metrics_exporter.close()
        _metrics_exporter = None
