"""Cerbos custom CEL function library.

Behavioral reference: internal/conditions/cerbos_lib.go:25-46 (function list)
and internal/conditions/types/{hierarchy,spiffe}.go. ``now()``/``timeSince()``
read the request-stable now-function from the activation, matching the
reference's CacheFriendlyTimeDecorator behavior (cerbos_lib.go:274-334).
"""

from __future__ import annotations

import ipaddress
import posixpath
import re as _re
from typing import Any

from .errors import CelError, no_such_overload
from .stdlib import FUNCTIONS, METHODS, _as_list, _as_str, func, method
from .values import Duration, Timestamp, UInt, values_equal


def _set_except(a: Any, b: Any) -> list:
    xs, ys = _as_list(a, "except"), _as_list(b, "except")
    return [x for x in xs if not any(values_equal(x, y) for y in ys)]


def _set_intersect(a: Any, b: Any) -> list:
    xs, ys = _as_list(a, "intersect"), _as_list(b, "intersect")
    out = []
    for x in xs:
        if any(values_equal(x, y) for y in ys) and not any(values_equal(x, o) for o in out):
            out.append(x)
    return out


def _set_has_intersection(a: Any, b: Any) -> bool:
    xs, ys = _as_list(a, "hasIntersection"), _as_list(b, "hasIntersection")
    return any(any(values_equal(x, y) for y in ys) for x in xs)


def _set_is_subset(a: Any, b: Any) -> bool:
    xs, ys = _as_list(a, "isSubset"), _as_list(b, "isSubset")
    return all(any(values_equal(x, y) for y in ys) for x in xs)


for _name, _fn in (
    ("except", _set_except),
    ("intersect", _set_intersect),
    ("hasIntersection", _set_has_intersection),
    ("has_intersection", _set_has_intersection),
    ("isSubset", _set_is_subset),
    ("is_subset", _set_is_subset),
):
    FUNCTIONS[_name] = (lambda f: lambda args, ctx: f(args[0], args[1]))(_fn)
    METHODS[_name] = (lambda f: lambda t, args, ctx: f(t, args[0]))(_fn)


@func("now")
def _f_now(args, ctx):
    return ctx.now()


@func("timeSince")
def _f_timesince(args, ctx):
    return _time_since(args[0], ctx)


@method("timeSince")
def _m_timesince(t, args, ctx):
    return _time_since(t, ctx)


def _time_since(v: Any, ctx) -> Duration:
    if not isinstance(v, Timestamp):
        raise no_such_overload("timeSince", v)
    return Duration.from_timedelta(ctx.now() - v)


@method("inIPAddrRange")
def _m_in_ip_range(t, args, ctx):
    addr_s = _as_str(t, "inIPAddrRange")
    cidr_s = _as_str(args[0], "inIPAddrRange")
    try:
        addr = ipaddress.ip_address(addr_s)
        net = ipaddress.ip_network(cidr_s, strict=False)
    except ValueError as e:
        raise CelError(f"inIPAddrRange: {e}") from None
    if addr.version != net.version:
        return False
    return addr in net


@func("id")
def _f_id(args, ctx):
    return args[0]


# --- path functions (ref: internal/conditions/crosspath; POSIX semantics) ---


def _clean_path(p: str) -> str:
    if p == "":
        return "."
    cleaned = posixpath.normpath(p)
    if p.endswith("/") and cleaned != "/":
        pass  # normpath drops trailing slash, matching Go's path.Clean
    return cleaned


@func("basePath")
def _f_basepath(args, ctx):
    p = _as_str(args[0], "basePath")
    if p == "":
        return "."
    p = p.rstrip("/")
    if p == "":
        return "/"
    base = posixpath.basename(p)
    return base if base else "/"


@func("dirPath")
def _f_dirpath(args, ctx):
    return posixpath.dirname(_as_str(args[0], "dirPath")) or "."


@func("extPath")
def _f_extpath(args, ctx):
    p = _as_str(args[0], "extPath")
    base = posixpath.basename(p)
    i = base.rfind(".")
    return base[i:] if i >= 0 else ""


@func("joinPath")
def _f_joinpath(args, ctx):
    parts = _as_list(args[0], "joinPath")
    strs = []
    for p in parts:
        if not isinstance(p, str):
            raise no_such_overload("joinPath", p)
        strs.append(p)
    nonempty = [p for p in strs if p]
    if not nonempty:
        return ""
    return _clean_path("/".join(nonempty))


def _path_has_prefix(p: str, prefix: str) -> bool:
    p, prefix = _clean_path(p), _clean_path(prefix)
    if prefix in (".", "/"):
        return prefix == "/" and p.startswith("/") or prefix == "."
    return p == prefix or p.startswith(prefix + "/")


@func("pathHasPrefix")
def _f_pathhasprefix(args, ctx):
    return _path_has_prefix(_as_str(args[0], "pathHasPrefix"), _as_str(args[1], "pathHasPrefix"))


@method("pathHasPrefix")
def _m_pathhasprefix(t, args, ctx):
    return _path_has_prefix(_as_str(t, "pathHasPrefix"), _as_str(args[0], "pathHasPrefix"))


def _path_match(pattern: str, name: str) -> bool:
    """Go path.Match semantics: *, ?, [class]; no ** and * stops at '/'."""
    rx = _path_match_rx(pattern)
    return bool(rx.match(name))


_PATH_RX_CACHE: dict[str, _re.Pattern] = {}


def _path_match_rx(pattern: str) -> _re.Pattern:
    rx = _PATH_RX_CACHE.get(pattern)
    if rx is not None:
        return rx
    out, i, n = [], 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "*":
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = i + 1
            neg = j < n and pattern[j] == "^"
            if neg:
                j += 1
            k = j
            while k < n and pattern[k] != "]":
                k += 1
            if k >= n:
                raise CelError(f"pathMatch: bad pattern {pattern!r}")
            body = pattern[j:k]
            out.append(f"[{'^' if neg else ''}{body}]")
            i = k
        elif c == "\\":
            if i + 1 >= n:
                raise CelError(f"pathMatch: bad pattern {pattern!r}")
            out.append(_re.escape(pattern[i + 1]))
            i += 1
        else:
            out.append(_re.escape(c))
        i += 1
    rx = _re.compile("^" + "".join(out) + "$")
    _PATH_RX_CACHE[pattern] = rx
    return rx


@func("pathMatch")
def _f_pathmatch(args, ctx):
    # arg order per crosspath.Match(path, pattern)
    return _path_match(_as_str(args[1], "pathMatch"), _as_str(args[0], "pathMatch"))


@method("pathMatch")
def _m_pathmatch(t, args, ctx):
    return _path_match(_as_str(args[0], "pathMatch"), _as_str(t, "pathMatch"))


@func("pathMatchAnyOf")
def _f_pathmatchanyof(args, ctx):
    name = _as_str(args[0], "pathMatchAnyOf")
    pats = _as_list(args[1], "pathMatchAnyOf")
    return any(_path_match(_as_str(p, "pathMatchAnyOf"), name) for p in pats)


@method("pathMatchAnyOf")
def _m_pathmatchanyof(t, args, ctx):
    name = _as_str(t, "pathMatchAnyOf")
    pats = _as_list(args[0], "pathMatchAnyOf")
    return any(_path_match(_as_str(p, "pathMatchAnyOf"), name) for p in pats)


@func("relPath")
def _f_relpath(args, ctx):
    base = _as_str(args[0], "relPath")
    target = _as_str(args[1], "relPath")
    try:
        return posixpath.relpath(target, base)
    except ValueError as e:
        raise CelError(f"relPath: {e}") from None


@func("volumeName")
def _f_volumename(args, ctx):
    _as_str(args[0], "volumeName")
    return ""  # POSIX paths have no volume component


# --- hierarchy type (ref: internal/conditions/types/hierarchy.go) ---


class Hierarchy:
    """Dotted-path hierarchy value: hierarchy("a.b.c").

    The reference applies no segment validation (hierarchy.go:146-167);
    it is an indexable, sizable sequence of strings (hierarchy.go:259-276).
    """

    __slots__ = ("parts",)

    def __init__(self, parts: list[str]):
        self.parts = parts

    def cel_type_name(self) -> str:
        return "cerbos.lib.hierarchy"

    def cel_equals(self, other: Any) -> bool:
        return isinstance(other, Hierarchy) and other.parts == self.parts

    def cel_size(self) -> int:
        return len(self.parts)

    def cel_index(self, idx: Any) -> str:
        # hierarchy.go:259-270 Get accepts types.Int only (not uint)
        if isinstance(idx, (bool, UInt)) or not isinstance(idx, int):
            raise no_such_overload("_[_]", self, idx)
        if not 0 <= idx < len(self.parts):
            raise CelError("index out of range")
        return self.parts[idx]

    def __repr__(self) -> str:  # pragma: no cover
        return f"hierarchy({'.'.join(self.parts)!r})"


def _as_hierarchy(v: Any, fn: str) -> Hierarchy:
    if isinstance(v, Hierarchy):
        return v
    raise no_such_overload(fn, v)


@func("hierarchy")
def _f_hierarchy(args, ctx):
    v = args[0]
    if isinstance(v, Hierarchy):
        return v
    if isinstance(v, str):
        delim = "."
        if len(args) > 1:
            delim = _as_str(args[1], "hierarchy")
        return Hierarchy(v.split(delim)) if v else Hierarchy([])
    if isinstance(v, (list, tuple)):
        return Hierarchy([_as_str(x, "hierarchy") for x in v])
    raise no_such_overload("hierarchy", v)


@method("ancestorOf")
def _m_ancestorof(t, args, ctx):
    h, o = _as_hierarchy(t, "ancestorOf"), _as_hierarchy(args[0], "ancestorOf")
    return len(h.parts) < len(o.parts) and o.parts[: len(h.parts)] == h.parts


@method("descendentOf")
def _m_descendentof(t, args, ctx):
    h, o = _as_hierarchy(t, "descendentOf"), _as_hierarchy(args[0], "descendentOf")
    return len(o.parts) < len(h.parts) and h.parts[: len(o.parts)] == o.parts


@method("commonAncestors")
def _m_commonancestors(t, args, ctx):
    """Ref: hierarchy.go:297-323 — equal-length paths drop their last element
    (excluding self), then the common prefix is the answer (possibly empty)."""
    h, o = _as_hierarchy(t, "commonAncestors"), _as_hierarchy(args[0], "commonAncestors")
    short, long = (h.parts, o.parts) if len(h.parts) <= len(o.parts) else (o.parts, h.parts)
    if len(long) == len(short):
        short, long = short[:-1], long[:-1]
    common = []
    for a, b in zip(short, long):
        if a != b:
            break
        common.append(a)
    return Hierarchy(common)


@method("immediateChildOf")
def _m_immediatechildof(t, args, ctx):
    h, o = _as_hierarchy(t, "immediateChildOf"), _as_hierarchy(args[0], "immediateChildOf")
    return len(h.parts) == len(o.parts) + 1 and h.parts[: len(o.parts)] == o.parts


@method("immediateParentOf")
def _m_immediateparentof(t, args, ctx):
    h, o = _as_hierarchy(t, "immediateParentOf"), _as_hierarchy(args[0], "immediateParentOf")
    return len(o.parts) == len(h.parts) + 1 and o.parts[: len(h.parts)] == h.parts


@method("siblingOf")
def _m_siblingof(t, args, ctx):
    h, o = _as_hierarchy(t, "siblingOf"), _as_hierarchy(args[0], "siblingOf")
    return (
        len(h.parts) == len(o.parts)
        and len(h.parts) > 0
        and h.parts[:-1] == o.parts[:-1]
        and h.parts != o.parts
    )


@method("overlaps")
def _m_overlaps(t, args, ctx):
    h, o = _as_hierarchy(t, "overlaps"), _as_hierarchy(args[0], "overlaps")
    m = min(len(h.parts), len(o.parts))
    return h.parts[:m] == o.parts[:m]
