"""CEL (Common Expression Language) runtime.

Behavioral reference: internal/conditions (cel-go environment with the Cerbos
declarations and custom library). This is an independent implementation: a
recursive-descent parser to a small AST, a tree-walking interpreter with CEL
error semantics (error-absorbing ``||``/``&&``/``?:``), the standard library
plus the strings/lists/math/encoders/bindings extensions the reference enables
(internal/conditions/cel.go:62-74), and the Cerbos custom functions
(internal/conditions/cerbos_lib.go:25-46).
"""

from .ast import (  # noqa: F401
    Call,
    Comprehension,
    Ident,
    Index,
    ListLit,
    Lit,
    MapLit,
    Node,
    Select,
)
from .errors import CelError, CelParseError  # noqa: F401
from .parser import parse  # noqa: F401
from .interp import Activation, evaluate  # noqa: F401
from .values import Duration, Timestamp, UInt, celtype_name  # noqa: F401
from .checker import check  # noqa: F401
