"""CEL standard library + extension functions.

Covers the function surface the reference enables
(internal/conditions/cel.go:62-74): the CEL standard library, the strings,
lists, math, encoders and bindings extensions, and cross-type numeric
comparisons. Functions are strict: a CelError raised by an argument
evaluation propagates (absorption happens in the interpreter for
``||``/``&&``/``?:``/comprehensions only).
"""

from __future__ import annotations

import base64 as _b64
import datetime as _dt
import math as _math
import re as _re
from typing import Any, Callable

from .errors import CelError, no_such_overload
from .values import (
    CelType,
    Duration,
    Timestamp,
    UInt,
    celtype_name,
    check_int,
    check_uint,
    compare,
    is_number,
    values_equal,
)

Ctx = Any  # interp.Activation; kept as Any to avoid a circular import


# ---------------------------------------------------------------------------
# helpers


def _as_list(v: Any, fn: str) -> list:
    if isinstance(v, (list, tuple)):
        return list(v)
    raise no_such_overload(fn, v)


def _as_str(v: Any, fn: str) -> str:
    if isinstance(v, str):
        return v
    raise no_such_overload(fn, v)


def _as_int_index(v: Any, fn: str) -> int:
    if type(v) is bool or not isinstance(v, int):
        raise no_such_overload(fn, v)
    return int(v)


_TZ_CACHE: dict[str, _dt.tzinfo] = {}


def _resolve_tz(name: str) -> _dt.tzinfo:
    if name in _TZ_CACHE:
        return _TZ_CACHE[name]
    tz: _dt.tzinfo
    if name in ("UTC", "utc", ""):
        tz = _dt.timezone.utc
    elif _re.fullmatch(r"[+-]\d\d:\d\d", name):
        sign = 1 if name[0] == "+" else -1
        hh, mm = int(name[1:3]), int(name[4:6])
        tz = _dt.timezone(sign * _dt.timedelta(hours=hh, minutes=mm))
    else:
        try:
            from zoneinfo import ZoneInfo

            tz = ZoneInfo(name)
        except Exception:
            raise CelError(f"unknown timezone {name!r}") from None
    _TZ_CACHE[name] = tz
    return tz


def _ts_in_tz(ts: Timestamp, args: tuple) -> _dt.datetime:
    if args:
        return ts.astimezone(_resolve_tz(_as_str(args[0], "timezone")))
    return ts


# ---------------------------------------------------------------------------
# conversions


def _to_int(v: Any) -> int:
    if type(v) is bool:
        raise no_such_overload("int", v)
    if isinstance(v, UInt):
        return check_int(int(v))
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        if _math.isnan(v) or _math.isinf(v):
            raise CelError("integer overflow")
        # cel-go rejects doubles outside the representable range
        if not (-9.223372036854776e18 <= v <= 9.223372036854776e18):
            raise CelError("integer overflow")
        return check_int(int(v))
    if isinstance(v, str):
        try:
            return check_int(int(v.strip(), 10))
        except ValueError:
            raise CelError(f"cannot convert {v!r} to int") from None
    if isinstance(v, Timestamp):
        # Go Time.Unix() floors toward negative infinity for pre-epoch times
        return int(_math.floor(v.timestamp()))
    if isinstance(v, Duration):
        us = (v.days * 86_400 + v.seconds) * 1_000_000 + v.microseconds
        q = abs(us) // 1_000_000
        return -q if us < 0 else q
    raise no_such_overload("int", v)


def _to_uint(v: Any) -> UInt:
    if type(v) is bool:
        raise no_such_overload("uint", v)
    if isinstance(v, UInt):
        return v
    if isinstance(v, int):
        return check_uint(v)
    if isinstance(v, float):
        if _math.isnan(v) or _math.isinf(v) or v < 0 or v > 1.8446744073709552e19:
            raise CelError("unsigned integer overflow")
        return check_uint(int(v))
    if isinstance(v, str):
        try:
            return check_uint(int(v.strip(), 10))
        except ValueError:
            raise CelError(f"cannot convert {v!r} to uint") from None
    raise no_such_overload("uint", v)


def _to_double(v: Any) -> float:
    if type(v) is bool:
        raise no_such_overload("double", v)
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v.strip())
        except ValueError:
            raise CelError(f"cannot convert {v!r} to double") from None
    raise no_such_overload("double", v)


def _double_str(f: float) -> str:
    if f != f:
        return "NaN"
    if f == _math.inf:
        return "+Inf"
    if f == -_math.inf:
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _to_string(v: Any) -> str:
    if type(v) is bool:
        return "true" if v else "false"
    if isinstance(v, UInt):
        return str(int(v))
    if isinstance(v, Timestamp):
        return v.rfc3339()
    if isinstance(v, Duration):
        # cel-go formats durations as seconds with "s" suffix
        secs = v.total_seconds()
        if secs == int(secs):
            return f"{int(secs)}s"
        return f"{secs}s"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return _double_str(v)
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    raise no_such_overload("string", v)


def _to_bool(v: Any) -> bool:
    if type(v) is bool:
        return v
    if isinstance(v, str):
        s = v.lower()
        if s in ("true", "t", "1"):
            return True
        if s in ("false", "f", "0"):
            return False
        raise CelError(f"cannot convert {v!r} to bool")
    raise no_such_overload("bool", v)


def _to_bytes(v: Any) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    raise no_such_overload("bytes", v)


def _to_timestamp(v: Any) -> Timestamp:
    if isinstance(v, Timestamp):
        return v
    if isinstance(v, str):
        return Timestamp.parse(v)
    if type(v) is not bool and isinstance(v, int):
        return Timestamp.from_datetime(_dt.datetime.fromtimestamp(int(v), _dt.timezone.utc))
    raise no_such_overload("timestamp", v)


def _to_duration(v: Any) -> Duration:
    if isinstance(v, Duration):
        return v
    if isinstance(v, str):
        return Duration.parse(v)
    if type(v) is not bool and isinstance(v, int):
        return Duration(seconds=int(v))
    raise no_such_overload("duration", v)


def _size(v: Any) -> int:
    if isinstance(v, (str, bytes, list, tuple, dict)):
        return len(v)
    if hasattr(v, "cel_size"):
        return v.cel_size()
    raise no_such_overload("size", v)


def _type_of(v: Any) -> CelType:
    return CelType(celtype_name(v))


# ---------------------------------------------------------------------------
# math extension


def _math_minmax(fn: str, args: tuple, pick: Callable) -> Any:
    vals = list(args[0]) if len(args) == 1 and isinstance(args[0], (list, tuple)) else list(args)
    if not vals:
        raise CelError(f"{fn}: no arguments")
    best = vals[0]
    if not is_number(best) and type(best) is not bool:
        raise no_such_overload(fn, best)
    for v in vals[1:]:
        if not is_number(v) and type(v) is not bool:
            raise no_such_overload(fn, v)
        if pick(compare(v, best)):
            best = v
    return best


def _require_double(v: Any, fn: str) -> float:
    if isinstance(v, float):
        return v
    raise no_such_overload(fn, v)


def _require_int(v: Any, fn: str) -> int:
    if type(v) is bool or not isinstance(v, int) or isinstance(v, UInt):
        raise no_such_overload(fn, v)
    return v


# ---------------------------------------------------------------------------
# global functions: name -> fn(args: tuple, ctx) -> value

FUNCTIONS: dict[str, Callable[..., Any]] = {}
METHODS: dict[str, Callable[..., Any]] = {}


def func(name: str):
    def deco(f):
        FUNCTIONS[name] = f
        return f

    return deco


def method(name: str):
    def deco(f):
        METHODS[name] = f
        return f

    return deco


@func("size")
def _f_size(args, ctx):
    return _size(args[0])


@func("int")
def _f_int(args, ctx):
    return _to_int(args[0])


@func("uint")
def _f_uint(args, ctx):
    return _to_uint(args[0])


@func("double")
def _f_double(args, ctx):
    return _to_double(args[0])


@func("string")
def _f_string(args, ctx):
    return _to_string(args[0])


@func("bool")
def _f_bool(args, ctx):
    return _to_bool(args[0])


@func("bytes")
def _f_bytes(args, ctx):
    return _to_bytes(args[0])


@func("timestamp")
def _f_timestamp(args, ctx):
    return _to_timestamp(args[0])


@func("duration")
def _f_duration(args, ctx):
    return _to_duration(args[0])


@func("dyn")
def _f_dyn(args, ctx):
    return args[0]


@func("type")
def _f_type(args, ctx):
    return _type_of(args[0])


@func("matches")
def _f_matches(args, ctx):
    return _m_matches(args[0], (args[1],), ctx)


@func("math.greatest")
def _f_greatest(args, ctx):
    return _math_minmax("math.greatest", args, lambda c: c > 0)


@func("math.least")
def _f_least(args, ctx):
    return _math_minmax("math.least", args, lambda c: c < 0)


@func("math.ceil")
def _f_ceil(args, ctx):
    return float(_math.ceil(_require_double(args[0], "math.ceil")))


@func("math.floor")
def _f_floor(args, ctx):
    return float(_math.floor(_require_double(args[0], "math.floor")))


@func("math.round")
def _f_round(args, ctx):
    v = _require_double(args[0], "math.round")
    # round-half-away-from-zero (Go semantics), not banker's rounding
    return float(_math.floor(v + 0.5) if v >= 0 else _math.ceil(v - 0.5))


@func("math.trunc")
def _f_trunc(args, ctx):
    return float(_math.trunc(_require_double(args[0], "math.trunc")))


@func("math.abs")
def _f_abs(args, ctx):
    v = args[0]
    if isinstance(v, float):
        return abs(v)
    if isinstance(v, UInt):
        return v
    if type(v) is not bool and isinstance(v, int):
        return check_int(abs(v))
    raise no_such_overload("math.abs", v)


@func("math.sign")
def _f_sign(args, ctx):
    v = args[0]
    if isinstance(v, float):
        if _math.isnan(v):
            return v
        return float((v > 0) - (v < 0))
    if isinstance(v, UInt):
        return UInt(1 if v > 0 else 0)
    if type(v) is not bool and isinstance(v, int):
        return (v > 0) - (v < 0)
    raise no_such_overload("math.sign", v)


@func("math.isNaN")
def _f_isnan(args, ctx):
    return _math.isnan(_require_double(args[0], "math.isNaN"))


@func("math.isInf")
def _f_isinf(args, ctx):
    return _math.isinf(_require_double(args[0], "math.isInf"))


@func("math.isFinite")
def _f_isfinite(args, ctx):
    return _math.isfinite(_require_double(args[0], "math.isFinite"))


@func("math.sqrt")
def _f_sqrt(args, ctx):
    v = args[0]
    if type(v) is bool or not isinstance(v, (int, float)):
        raise no_such_overload("math.sqrt", v)
    f = float(v)
    return _math.sqrt(f) if f >= 0 else float("nan")


@func("math.bitAnd")
def _f_bitand(args, ctx):
    a, b = args
    if isinstance(a, UInt) and isinstance(b, UInt):
        return UInt(a & b)
    return check_int(_require_int(a, "math.bitAnd") & _require_int(b, "math.bitAnd"))


@func("math.bitOr")
def _f_bitor(args, ctx):
    a, b = args
    if isinstance(a, UInt) and isinstance(b, UInt):
        return UInt(a | b)
    return check_int(_require_int(a, "math.bitOr") | _require_int(b, "math.bitOr"))


@func("math.bitXor")
def _f_bitxor(args, ctx):
    a, b = args
    if isinstance(a, UInt) and isinstance(b, UInt):
        return UInt(a ^ b)
    return check_int(_require_int(a, "math.bitXor") ^ _require_int(b, "math.bitXor"))


@func("math.bitNot")
def _f_bitnot(args, ctx):
    v = args[0]
    if isinstance(v, UInt):
        return UInt(v ^ (2**64 - 1))
    return check_int(~_require_int(v, "math.bitNot"))


@func("math.bitShiftLeft")
def _f_bitshl(args, ctx):
    v, s = args
    shift = _require_int(s, "math.bitShiftLeft")
    if shift < 0:
        raise CelError("math.bitShiftLeft: negative shift")
    if isinstance(v, UInt):
        return UInt((int(v) << shift) & (2**64 - 1)) if shift < 64 else UInt(0)
    iv = _require_int(v, "math.bitShiftLeft")
    if shift >= 64:
        return 0
    r = (iv << shift) & (2**64 - 1)
    return r - 2**64 if r >= 2**63 else r


@func("math.bitShiftRight")
def _f_bitshr(args, ctx):
    v, s = args
    shift = _require_int(s, "math.bitShiftRight")
    if shift < 0:
        raise CelError("math.bitShiftRight: negative shift")
    if isinstance(v, UInt):
        return UInt(int(v) >> shift) if shift < 64 else UInt(0)
    iv = _require_int(v, "math.bitShiftRight")
    if shift >= 64:
        return 0
    return (iv & (2**64 - 1)) >> shift  # logical shift on the 2's complement bits


@func("base64.encode")
def _f_b64enc(args, ctx):
    v = args[0]
    if not isinstance(v, bytes):
        raise no_such_overload("base64.encode", v)
    return _b64.b64encode(v).decode("ascii")


@func("base64.decode")
def _f_b64dec(args, ctx):
    v = _as_str(args[0], "base64.decode")
    try:
        pad = v + "=" * (-len(v) % 4)
        return _b64.b64decode(pad)
    except Exception:
        raise CelError("base64.decode: invalid input") from None


@func("strings.quote")
def _f_quote(args, ctx):
    s = _as_str(args[0], "strings.quote")
    out = ['"']
    for ch in s:
        if ch == '"':
            out.append('\\"')
        elif ch == "\\":
            out.append("\\\\")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\t":
            out.append("\\t")
        elif ch in ("\a", "\b", "\f", "\v"):
            out.append({"\a": "\\a", "\b": "\\b", "\f": "\\f", "\v": "\\v"}[ch])
        else:
            out.append(ch)
    out.append('"')
    return "".join(out)


# ---------------------------------------------------------------------------
# member methods: name -> fn(target, args: tuple, ctx) -> value


@method("contains")
def _m_contains(t, args, ctx):
    return _as_str(args[0], "contains") in _as_str(t, "contains")


@method("startsWith")
def _m_startswith(t, args, ctx):
    return _as_str(t, "startsWith").startswith(_as_str(args[0], "startsWith"))


@method("endsWith")
def _m_endswith(t, args, ctx):
    return _as_str(t, "endsWith").endswith(_as_str(args[0], "endsWith"))


_RE_CACHE: dict[str, _re.Pattern] = {}


def _compile_re(pat: str) -> _re.Pattern:
    rx = _RE_CACHE.get(pat)
    if rx is None:
        try:
            rx = _re.compile(pat)
        except _re.error as e:
            raise CelError(f"invalid regex {pat!r}: {e}") from None
        _RE_CACHE[pat] = rx
    return rx


@method("matches")
def _m_matches(t, args, ctx):
    return bool(_compile_re(_as_str(args[0], "matches")).search(_as_str(t, "matches")))


@method("size")
def _m_size(t, args, ctx):
    return _size(t)


@method("charAt")
def _m_charat(t, args, ctx):
    s = _as_str(t, "charAt")
    i = _as_int_index(args[0], "charAt")
    if i == len(s):
        return ""
    if not 0 <= i < len(s):
        raise CelError(f"charAt: index out of range: {i}")
    return s[i]


@method("indexOf")
def _m_indexof(t, args, ctx):
    s = _as_str(t, "indexOf")
    sub = _as_str(args[0], "indexOf")
    start = _as_int_index(args[1], "indexOf") if len(args) > 1 else 0
    if start < 0 or start > len(s):
        raise CelError(f"indexOf: index out of range: {start}")
    return s.find(sub, start)


@method("lastIndexOf")
def _m_lastindexof(t, args, ctx):
    s = _as_str(t, "lastIndexOf")
    sub = _as_str(args[0], "lastIndexOf")
    end = _as_int_index(args[1], "lastIndexOf") if len(args) > 1 else len(s)
    if end < 0 or end > len(s):
        raise CelError(f"lastIndexOf: index out of range: {end}")
    if len(args) > 1:
        # offset marks the start position for the backwards search in cel-go
        return s.rfind(sub, 0, end + max(len(sub), 1))
    return s.rfind(sub)


@method("join")
def _m_join(t, args, ctx):
    items = _as_list(t, "join")
    sep = _as_str(args[0], "join") if args else ""
    parts = []
    for it in items:
        if not isinstance(it, str):
            raise no_such_overload("join", it)
        parts.append(it)
    return sep.join(parts)


def _fmt_string(v: Any) -> str:
    """%s clause of the cel-go strings extension (ext/formatting.go)."""
    if v is None:
        return "null"
    if isinstance(v, str):
        return v
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt_string(e) for e in v) + "]"
    if isinstance(v, dict):
        # cel-go sorts map entries by key for deterministic output
        entries = sorted(((_fmt_string(k), _fmt_string(val)) for k, val in v.items()))
        return "{" + ", ".join(f"{k}: {val}" for k, val in entries) + "}"
    return _to_string(v)


@method("format")
def _m_format(t, args, ctx):
    """cel-go strings extension: "%s_%d".format([a, b]) (ext/formatting.go)."""
    fmt = _as_str(t, "format")
    fargs = _as_list(args[0], "format") if args else []
    out: list[str] = []
    ai = 0
    i = 0
    n = len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        i += 1
        if i < n and fmt[i] == "%":
            out.append("%")
            i += 1
            continue
        precision = -1
        if i < n and fmt[i] == ".":
            i += 1
            start = i
            while i < n and fmt[i].isdigit():
                i += 1
            precision = int(fmt[start:i] or "0")
        if i >= n:
            raise CelError("format: unexpected end of format string")
        verb = fmt[i]
        i += 1
        if ai >= len(fargs):
            raise CelError("format: index %d out of range" % ai)
        v = fargs[ai]
        ai += 1
        if verb == "s":
            out.append(_fmt_string(v))
        elif verb == "d":
            if isinstance(v, bool) or not isinstance(v, (int, UInt)):
                raise CelError("format: integer clause can only be used on integers")
            out.append(str(int(v)))
        elif verb in ("f", "e"):
            if isinstance(v, bool) or not isinstance(v, (int, float, UInt)):
                raise CelError("format: fixed-point clause can only be used on numbers")
            p = 6 if precision < 0 else precision
            out.append(("%." + str(p) + verb) % float(v))
        elif verb == "b":
            if isinstance(v, bool):
                out.append("1" if v else "0")
            elif isinstance(v, (int, UInt)):
                x = int(v)
                out.append(("-" if x < 0 else "") + bin(abs(x))[2:])
            else:
                raise CelError("format: binary clause can only be used on integers and bools")
        elif verb in ("x", "X"):
            if isinstance(v, bool):
                raise CelError("format: hex clause can only be used on integers, bytes and strings")
            if isinstance(v, (int, UInt)):
                x = int(v)
                s = ("-" if x < 0 else "") + hex(abs(x))[2:]
            elif isinstance(v, str):
                s = v.encode("utf-8").hex()
            elif isinstance(v, bytes):
                s = v.hex()
            else:
                raise CelError("format: hex clause can only be used on integers, bytes and strings")
            out.append(s.upper() if verb == "X" else s)
        elif verb == "o":
            if isinstance(v, bool) or not isinstance(v, (int, UInt)):
                raise CelError("format: octal clause can only be used on integers")
            x = int(v)
            out.append(("-" if x < 0 else "") + oct(abs(x))[2:])
        else:
            raise CelError(f"format: unrecognized formatting clause: {verb}")
    return "".join(out)


@method("lowerAscii")
def _m_lowerascii(t, args, ctx):
    return "".join(c.lower() if "A" <= c <= "Z" else c for c in _as_str(t, "lowerAscii"))


@method("upperAscii")
def _m_upperascii(t, args, ctx):
    return "".join(c.upper() if "a" <= c <= "z" else c for c in _as_str(t, "upperAscii"))


@method("replace")
def _m_replace(t, args, ctx):
    s = _as_str(t, "replace")
    old = _as_str(args[0], "replace")
    new = _as_str(args[1], "replace")
    limit = _as_int_index(args[2], "replace") if len(args) > 2 else -1
    if limit < 0:
        return s.replace(old, new)
    return s.replace(old, new, limit)


@method("split")
def _m_split(t, args, ctx):
    s = _as_str(t, "split")
    sep = _as_str(args[0], "split")
    limit = _as_int_index(args[1], "split") if len(args) > 1 else -1
    if limit == 0:
        return []
    if sep == "":
        chars = list(s)
        if limit > 0:
            return chars[: limit - 1] + (["".join(chars[limit - 1 :])] if len(chars) >= limit else [])
        return chars
    if limit > 0:
        return s.split(sep, limit - 1)
    return s.split(sep)


@method("substring")
def _m_substring(t, args, ctx):
    s = _as_str(t, "substring")
    start = _as_int_index(args[0], "substring")
    end = _as_int_index(args[1], "substring") if len(args) > 1 else len(s)
    if start < 0 or end < 0 or start > len(s) or end > len(s) or start > end:
        raise CelError(f"substring: invalid range [{start}:{end}]")
    return s[start:end]


@method("trim")
def _m_trim(t, args, ctx):
    return _as_str(t, "trim").strip()


@method("reverse")
def _m_reverse(t, args, ctx):
    if isinstance(t, str):
        return t[::-1]
    if isinstance(t, (list, tuple)):
        return list(t)[::-1]
    raise no_such_overload("reverse", t)


@method("flatten")
def _m_flatten(t, args, ctx):
    items = _as_list(t, "flatten")
    depth = _as_int_index(args[0], "flatten") if args else 1
    if depth < 0:
        raise CelError("flatten: negative depth")

    def fl(xs: list, d: int) -> list:
        out = []
        for x in xs:
            if isinstance(x, (list, tuple)) and d > 0:
                out.extend(fl(list(x), d - 1))
            else:
                out.append(x)
        return out

    return fl(items, depth)


@method("slice")
def _m_slice(t, args, ctx):
    items = _as_list(t, "slice")
    start = _as_int_index(args[0], "slice")
    end = _as_int_index(args[1], "slice")
    if start < 0 or end < 0 or start > len(items) or end > len(items) or start > end:
        raise CelError(f"slice: invalid range [{start}:{end}]")
    return items[start:end]


@func("lists.range")
def _f_lists_range(args, ctx):
    n = args[0]
    # int only — no uint overload in the lists extension
    if isinstance(n, (bool, UInt)) or not isinstance(n, int):
        raise no_such_overload("lists.range", n)
    return list(range(int(n)))


@method("distinct")
def _m_distinct(t, args, ctx):
    items = _as_list(t, "distinct")
    out: list = []
    for x in items:
        if not any(values_equal(x, y) for y in out):
            out.append(x)
    return out


@method("sort")
def _m_sort(t, args, ctx):
    items = _as_list(t, "sort")
    if not items:
        return []
    import functools

    try:
        return sorted(items, key=functools.cmp_to_key(compare))
    except CelError:
        raise
    except Exception:
        raise CelError("sort: list is not comparable") from None


# --- timestamp / duration accessors ---


def _dur_or_ts(t, fn):
    if isinstance(t, (Timestamp, Duration)):
        return t
    raise no_such_overload(fn, t)


@method("getFullYear")
def _m_getfullyear(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getFullYear", t)
    return _ts_in_tz(t, args).year


@method("getMonth")
def _m_getmonth(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getMonth", t)
    return _ts_in_tz(t, args).month - 1


@method("getDayOfYear")
def _m_getdayofyear(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getDayOfYear", t)
    return _ts_in_tz(t, args).timetuple().tm_yday - 1


@method("getDayOfMonth")
def _m_getdayofmonth(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getDayOfMonth", t)
    return _ts_in_tz(t, args).day - 1


@method("getDate")
def _m_getdate(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getDate", t)
    return _ts_in_tz(t, args).day


@method("getDayOfWeek")
def _m_getdayofweek(t, args, ctx):
    if not isinstance(t, Timestamp):
        raise no_such_overload("getDayOfWeek", t)
    return (_ts_in_tz(t, args).weekday() + 1) % 7  # Sunday == 0


def _dur_us(v: Duration) -> int:
    """Total microseconds, exact (timedelta normalizes fields; reconstruct)."""
    return (v.days * 86_400 + v.seconds) * 1_000_000 + v.microseconds


def _trunc_div(a: int, b: int) -> int:
    """Go-style division truncated toward zero (floor division differs for negatives)."""
    q = abs(a) // b
    return -q if a < 0 else q


@method("getHours")
def _m_gethours(t, args, ctx):
    v = _dur_or_ts(t, "getHours")
    if isinstance(v, Duration):
        return _trunc_div(_dur_us(v), 3_600_000_000)
    return _ts_in_tz(v, args).hour


@method("getMinutes")
def _m_getminutes(t, args, ctx):
    v = _dur_or_ts(t, "getMinutes")
    if isinstance(v, Duration):
        return _trunc_div(_dur_us(v), 60_000_000)
    return _ts_in_tz(v, args).minute


@method("getSeconds")
def _m_getseconds(t, args, ctx):
    v = _dur_or_ts(t, "getSeconds")
    if isinstance(v, Duration):
        return _trunc_div(_dur_us(v), 1_000_000)
    return _ts_in_tz(v, args).second


@method("getMilliseconds")
def _m_getmillis(t, args, ctx):
    v = _dur_or_ts(t, "getMilliseconds")
    if isinstance(v, Duration):
        # cel-go returns the TOTAL milliseconds for durations
        # (time.Duration.Milliseconds), not the millisecond component —
        # confirmed by cel_eval/duration_funcs.yaml (3750s → 3750000)
        return _trunc_div(_dur_us(v), 1_000)
    return _ts_in_tz(v, args).microsecond // 1000
