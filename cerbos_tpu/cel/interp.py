"""Tree-walking CEL interpreter with cel-go-compatible semantics.

Error values propagate as :class:`CelError` exceptions; ``||``/``&&``/``?:``
and the all/exists comprehension aggregates absorb them per the CEL spec
(commutative logical operators). This evaluator is the CPU oracle the TPU
lowering is differentially tested against.
"""

from __future__ import annotations

import math as _math
from typing import Any, Callable, Optional

from .ast import Bind, Call, Comprehension, Ident, Index, ListLit, Lit, MapLit, Node, Present, Select
from .errors import CelError, no_such_key, no_such_overload
from .stdlib import FUNCTIONS, METHODS
from . import cerbos_lib  # noqa: F401  (registers cerbos functions on import)
from . import spiffe  # noqa: F401  (registers SPIFFE functions on import)
from .values import (
    Duration,
    Timestamp,
    UInt,
    check_int,
    check_uint,
    compare,
    is_number,
    keys_equal,
    values_equal,
)


from .values import CelType as _CelType

TYPE_IDENTS = {
    n: _CelType(n)
    for n in ("int", "uint", "double", "bool", "string", "bytes", "list", "map", "null_type", "type")
}


def snake_to_json_name(field: str) -> str:
    """Proto field name → JSON name (lowerCamelCase)."""
    head, *rest = field.split("_")
    return head + "".join(p[:1].upper() + p[1:] for p in rest)


class Message:
    """A proto-message-like value: fixed fields with defaults.

    Used for ``request``/``request.principal``/``request.resource``/``runtime``
    so that unset fields yield defaults (proto semantics) while ``attr`` maps
    yield errors for missing keys (map semantics), matching the reference's
    typed CEL declarations (internal/conditions/cel.go:44-55).
    """

    __slots__ = ("fields",)

    def __init__(self, fields: dict[str, Any]):
        self.fields = fields

    def _resolve(self, field: str) -> str:
        """cel-go indexes proto fields under both the proto (snake_case) and
        JSON (camelCase) names; canonical storage here is the JSON name."""
        if field in self.fields:
            return field
        if "_" in field:
            alias = snake_to_json_name(field)
            if alias in self.fields:
                return alias
        raise CelError(f"no such field: {field}")

    def cel_select(self, field: str) -> Any:
        return self.fields[self._resolve(field)]

    def cel_has(self, field: str) -> bool:
        field = self._resolve(field)
        v = self.fields[field]
        if isinstance(v, (str, bytes, list, tuple, dict)):
            return len(v) > 0
        if isinstance(v, bool):
            return v
        if v is None:
            return False
        if is_number(v):
            return v != 0
        return True

    def cel_type_name(self) -> str:
        return "message"


class LazyVal:
    """Wraps a zero-arg callable resolved on first access (ref: lazyRuntime)."""

    __slots__ = ("fn", "_val", "_done")

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn
        self._val = None
        self._done = False

    def get(self) -> Any:
        if not self._done:
            self._val = self.fn()
            self._done = True
        return self._val


class Activation:
    """Variable bindings + the request-stable now() function."""

    __slots__ = ("vars", "parent", "_now_fn", "_now_cache")

    def __init__(self, vars: dict[str, Any], parent: Optional["Activation"] = None, now_fn: Optional[Callable[[], Timestamp]] = None):
        self.vars = vars
        self.parent = parent
        self._now_fn = now_fn
        self._now_cache: Optional[Timestamp] = None

    def child(self, vars: dict[str, Any]) -> "Activation":
        return Activation(vars, parent=self)

    def resolve(self, name: str) -> Any:
        a: Optional[Activation] = self
        while a is not None:
            if name in a.vars:
                v = a.vars[name]
                if isinstance(v, LazyVal):
                    v = v.get()
                    a.vars[name] = v
                return v
            a = a.parent
        if name in TYPE_IDENTS:
            return TYPE_IDENTS[name]
        raise CelError(f"no such attribute: {name}")

    def has(self, name: str) -> bool:
        a: Optional[Activation] = self
        while a is not None:
            if name in a.vars:
                return True
            a = a.parent
        return False

    def now(self) -> Timestamp:
        a: Optional[Activation] = self
        while a is not None and a._now_fn is None:
            a = a.parent
        if a is None:
            raise CelError("now() is not available")
        if a._now_cache is None:
            a._now_cache = a._now_fn()
        return a._now_cache


def evaluate(node: Node, act: Activation) -> Any:
    """Evaluate; raises CelError for CEL runtime errors.

    Host-level arithmetic/conversion failures (OverflowError from datetime
    math, ValueError from out-of-range timestamps) become CEL error values —
    cel-go returns error values for these, and a malformed attribute must
    fail the condition, not crash the check (see review finding on
    timestamp overflow DoS). TypeError/AttributeError are implementation
    bugs and still surface.
    """
    try:
        return _eval(node, act)
    except CelError:
        raise
    except (OverflowError, ValueError, ZeroDivisionError) as e:
        raise CelError(f"evaluation error: {e}") from None


def _eval(node: Node, act: Activation) -> Any:
    t = type(node)
    if t is Lit:
        return node.value
    if t is Ident:
        return act.resolve(node.name)
    if t is Select:
        return _select(_eval(node.operand, act), node.field)
    if t is Present:
        return _present(_eval(node.operand, act), node.field)
    if t is Index:
        return _index(_eval(node.operand, act), _eval(node.index, act))
    if t is ListLit:
        return [_eval(x, act) for x in node.items]
    if t is MapLit:
        out: dict = {}
        for k_node, v_node in node.entries:
            k = _eval(k_node, act)
            if isinstance(k, (list, dict)):
                raise no_such_overload("map_key", k)
            dup = (k in out) if type(k) is str else any(keys_equal(k, existing) for existing in out)
            if dup:
                raise CelError(f"repeated key: {k!r}")
            out[k] = _eval(v_node, act)
        return out
    if t is Bind:
        return _eval(node.body, act.child({node.name: _eval(node.init, act)}))
    if t is Comprehension:
        return _comprehension(node, act)
    if t is Call:
        return _call(node, act)
    raise CelError(f"unknown AST node {t.__name__}")


def _select(operand: Any, field: str) -> Any:
    if isinstance(operand, Message):
        return operand.cel_select(field)
    if isinstance(operand, dict):
        if field in operand:
            return operand[field]
        raise no_such_key(field)
    sel = getattr(operand, "cel_select", None)
    if sel is not None:
        return sel(field)
    raise no_such_overload(f".{field}", operand)


def _present(operand: Any, field: str) -> bool:
    if isinstance(operand, Message):
        return operand.cel_has(field)
    if isinstance(operand, dict):
        return field in operand
    has = getattr(operand, "cel_has", None)
    if has is not None:
        return has(field)
    raise no_such_overload(f"has(.{field})", operand)


def _index(operand: Any, idx: Any) -> Any:
    if isinstance(operand, (list, tuple)):
        if type(idx) is bool:
            raise no_such_overload("_[_]", operand, idx)
        if isinstance(idx, float):
            if idx != int(idx):
                raise CelError(f"invalid index: {idx}")
            idx = int(idx)
        if not isinstance(idx, int):
            raise no_such_overload("_[_]", operand, idx)
        i = int(idx)
        if not 0 <= i < len(operand):
            raise CelError(f"index out of range: {i}")
        return operand[i]
    if isinstance(operand, dict):
        # Fast path for string keys (the common case: attr maps). Python would
        # conflate 1/True/1.0/UInt(1) as dict keys, which CEL key equality
        # does not, so non-string lookups take the scan path.
        if type(idx) is str:
            try:
                return operand[idx]
            except KeyError:
                raise no_such_key(idx) from None
        for k, v in operand.items():
            if keys_equal(idx, k):
                return v
        raise no_such_key(idx)
    if isinstance(operand, Message):
        if isinstance(idx, str):
            return operand.cel_select(idx)
        raise no_such_overload("_[_]", operand, idx)
    if hasattr(operand, "cel_index"):
        return operand.cel_index(idx)
    raise no_such_overload("_[_]", operand, idx)


# ---------------------------------------------------------------------------
# operators


def _arith_add(a: Any, b: Any) -> Any:
    if type(a) is bool or type(b) is bool:
        raise no_such_overload("_+_", a, b)
    if isinstance(a, UInt) and isinstance(b, UInt):
        return check_uint(int(a) + int(b))
    if isinstance(a, Timestamp) and isinstance(b, Duration):
        return Timestamp.from_datetime(a + b)
    if isinstance(a, Duration) and isinstance(b, Timestamp):
        return Timestamp.from_datetime(b + a)
    if isinstance(a, Duration) and isinstance(b, Duration):
        return Duration.from_timedelta(a + b)
    if isinstance(a, (Timestamp, Duration)) or isinstance(b, (Timestamp, Duration)):
        raise no_such_overload("_+_", a, b)
    if type(a) is int and type(b) is int:
        return check_int(a + b)
    if isinstance(a, float) and isinstance(b, float):
        return a + b
    if isinstance(a, str) and isinstance(b, str):
        return a + b
    if isinstance(a, bytes) and isinstance(b, bytes):
        return a + b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return list(a) + list(b)
    raise no_such_overload("_+_", a, b)


def _arith_sub(a: Any, b: Any) -> Any:
    if type(a) is bool or type(b) is bool:
        raise no_such_overload("_-_", a, b)
    if isinstance(a, UInt) and isinstance(b, UInt):
        return check_uint(int(a) - int(b))
    if isinstance(a, Timestamp) and isinstance(b, Timestamp):
        return Duration.from_timedelta(a - b)
    if isinstance(a, Timestamp) and isinstance(b, Duration):
        return Timestamp.from_datetime(a - b)
    if isinstance(a, Duration) and isinstance(b, Duration):
        return Duration.from_timedelta(a - b)
    if isinstance(a, (Timestamp, Duration)) or isinstance(b, (Timestamp, Duration)):
        raise no_such_overload("_-_", a, b)
    if type(a) is int and type(b) is int:
        return check_int(a - b)
    if isinstance(a, float) and isinstance(b, float):
        return a - b
    raise no_such_overload("_-_", a, b)


def _arith_mul(a: Any, b: Any) -> Any:
    if type(a) is bool or type(b) is bool:
        raise no_such_overload("_*_", a, b)
    if isinstance(a, UInt) and isinstance(b, UInt):
        return check_uint(int(a) * int(b))
    if type(a) is int and type(b) is int:
        return check_int(a * b)
    if isinstance(a, float) and isinstance(b, float):
        return a * b
    raise no_such_overload("_*_", a, b)


def _arith_div(a: Any, b: Any) -> Any:
    if type(a) is bool or type(b) is bool:
        raise no_such_overload("_/_", a, b)
    if isinstance(a, UInt) and isinstance(b, UInt):
        if int(b) == 0:
            raise CelError("division by zero")
        return check_uint(int(a) // int(b))
    if type(a) is int and type(b) is int:
        if b == 0:
            raise CelError("division by zero")
        q = abs(a) // abs(b)
        if (a < 0) != (b < 0):
            q = -q
        return check_int(q)
    if isinstance(a, float) and isinstance(b, float):
        if b == 0.0:
            if a == 0.0 or _math.isnan(a):
                return float("nan")
            return _math.inf if (a > 0) == (not _math.copysign(1, b) < 0) else -_math.inf
        return a / b
    raise no_such_overload("_/_", a, b)


def _arith_mod(a: Any, b: Any) -> Any:
    if type(a) is bool or type(b) is bool:
        raise no_such_overload("_%_", a, b)
    if isinstance(a, UInt) and isinstance(b, UInt):
        if int(b) == 0:
            raise CelError("modulus by zero")
        return check_uint(int(a) % int(b))
    if type(a) is int and type(b) is int:
        if b == 0:
            raise CelError("modulus by zero")
        r = abs(a) % abs(b)
        return check_int(-r if a < 0 else r)
    raise no_such_overload("_%_", a, b)


def _neg(a: Any) -> Any:
    if type(a) is bool:
        raise no_such_overload("-_", a)
    if isinstance(a, UInt):
        raise no_such_overload("-_", a)
    if isinstance(a, int):
        return check_int(-a)
    if isinstance(a, float):
        return -a
    raise no_such_overload("-_", a)


def _in_op(a: Any, b: Any) -> bool:
    if isinstance(b, (list, tuple)):
        return any(values_equal(a, x) for x in b)
    if isinstance(b, dict):
        if type(a) is str:
            return a in b
        return any(keys_equal(a, k) for k in b)
    raise no_such_overload("_in_", a, b)


def _logic(node: Call, act: Activation, is_and: bool) -> Any:
    """Commutative error-absorbing && / ||."""
    short = False if is_and else True
    vals: list[Any] = []
    err: Optional[CelError] = None
    for arg in node.args:
        try:
            v = _eval(arg, act)
        except CelError as e:
            err = err or e
            continue
        if type(v) is bool:
            if v is short:
                return short
            vals.append(v)
        else:
            err = err or no_such_overload("_&&_" if is_and else "_||_", v)
    if err is not None:
        raise err
    return not short


def _call(node: Call, act: Activation) -> Any:
    fn = node.fn
    if node.target is None:
        if fn == "_&&_":
            return _logic(node, act, is_and=True)
        if fn == "_||_":
            return _logic(node, act, is_and=False)
        if fn == "_?_:_":
            cond = _eval(node.args[0], act)
            if type(cond) is not bool:
                raise no_such_overload("_?_:_", cond)
            return _eval(node.args[1 if cond else 2], act)
        if fn == "_==_":
            return values_equal(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "_!=_":
            return not values_equal(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn in ("_<_", "_<=_", "_>_", "_>=_"):
            c = compare(_eval(node.args[0], act), _eval(node.args[1], act))
            return {"_<_": c < 0, "_<=_": c <= 0, "_>_": c > 0, "_>=_": c >= 0}[fn]
        if fn == "_+_":
            return _arith_add(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "_-_":
            return _arith_sub(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "_*_":
            return _arith_mul(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "_/_":
            return _arith_div(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "_%_":
            return _arith_mod(_eval(node.args[0], act), _eval(node.args[1], act))
        if fn == "!_":
            v = _eval(node.args[0], act)
            if type(v) is not bool:
                raise no_such_overload("!_", v)
            return not v
        if fn == "-_":
            return _neg(_eval(node.args[0], act))
        if fn == "_in_":
            return _in_op(_eval(node.args[0], act), _eval(node.args[1], act))
        handler = FUNCTIONS.get(fn)
        if handler is None:
            raise CelError(f"unknown function: {fn}")
        args = tuple(_eval(a, act) for a in node.args)
        return handler(args, act)

    target = _eval(node.target, act)
    handler = METHODS.get(fn)
    if handler is None:
        raise CelError(f"unknown function: {fn}")
    args = tuple(_eval(a, act) for a in node.args)
    return handler(target, args, act)


# ---------------------------------------------------------------------------
# comprehensions


def _iter_items(range_val: Any, two_var: bool, kind: str):
    if isinstance(range_val, (list, tuple)):
        if two_var:
            return list(enumerate(range_val))
        return [(None, v) for v in range_val]
    if isinstance(range_val, dict):
        if two_var:
            return list(range_val.items())
        return [(None, k) for k in range_val.keys()]
    raise no_such_overload(kind, range_val)


def _comprehension(node: Comprehension, act: Activation) -> Any:
    range_val = _eval(node.iter_range, act)
    two_var = node.iter_var2 is not None
    items = _iter_items(range_val, two_var, node.kind)

    def bind(k: Any, v: Any) -> Activation:
        if two_var:
            return act.child({node.iter_var: k, node.iter_var2: v})
        return act.child({node.iter_var: v})

    kind = node.kind
    if kind in ("all", "exists"):
        # && / || aggregation with error absorption
        short = kind == "exists"
        err: Optional[CelError] = None
        for k, v in items:
            try:
                p = _eval(node.step, bind(k, v))
            except CelError as e:
                err = err or e
                continue
            if type(p) is not bool:
                err = err or no_such_overload(kind, p)
                continue
            if p is short:
                return short
        if err is not None:
            raise err
        return not short
    if kind == "exists_one":
        count = 0
        for k, v in items:
            p = _eval(node.step, bind(k, v))
            if type(p) is not bool:
                raise no_such_overload(kind, p)
            if p:
                count += 1
        return count == 1
    if kind == "map":
        out = []
        for k, v in items:
            a = bind(k, v)
            if node.step2 is not None:
                keep = _eval(node.step2, a)
                if type(keep) is not bool:
                    raise no_such_overload("map", keep)
                if not keep:
                    continue
            out.append(_eval(node.step, a))
        return out
    if kind == "filter":
        out = []
        for k, v in items:
            p = _eval(node.step, bind(k, v))
            if type(p) is not bool:
                raise no_such_overload("filter", p)
            if p:
                out.append(v)
        return out
    if kind == "transform_list":
        out = []
        for k, v in items:
            a = bind(k, v)
            if node.step2 is not None:
                keep = _eval(node.step2, a)
                if type(keep) is not bool:
                    raise no_such_overload(kind, keep)
                if not keep:
                    continue
            out.append(_eval(node.step, a))
        return out
    if kind in ("transform_map", "transform_map_entry"):
        out_map: dict = {}
        for k, v in items:
            a = bind(k, v)
            if node.step2 is not None:
                keep = _eval(node.step2, a)
                if type(keep) is not bool:
                    raise no_such_overload(kind, keep)
                if not keep:
                    continue
            r = _eval(node.step, a)
            if kind == "transform_map":
                out_map[k] = r
            else:
                if not isinstance(r, dict):
                    raise no_such_overload(kind, r)
                for rk, rv in r.items():
                    if any(keys_equal(rk, existing) for existing in out_map):
                        raise CelError(f"insert failed, key {rk!r} already exists")
                    out_map[rk] = rv
        return out_map
    if kind == "sort_by":
        # cel-go lists extension sortBy(e, keyExpr): stable sort by the key
        import functools

        keyed = [(_eval(node.step, bind(k, v)), v) for k, v in items]
        keyed.sort(key=functools.cmp_to_key(lambda a, b: compare(a[0], b[0])))
        return [v for _, v in keyed]
    raise CelError(f"unknown comprehension kind {kind}")
