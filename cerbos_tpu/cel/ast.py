"""CEL AST nodes.

Macros (has/all/exists/exists_one/map/filter, cel.bind, two-var
comprehensions) are desugared by the parser into :class:`Comprehension` /
:class:`Bind` / :class:`Present` nodes so the interpreter and the TPU lowering
see a small, closed node set.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class Node:
    __slots__ = ()


@dataclass(frozen=True)
class Lit(Node):
    value: Any


@dataclass(frozen=True)
class Ident(Node):
    name: str


@dataclass(frozen=True)
class Select(Node):
    operand: Node
    field: str


@dataclass(frozen=True)
class Present(Node):
    """has(e.f) — field/key presence test."""

    operand: Node
    field: str


@dataclass(frozen=True)
class Index(Node):
    operand: Node
    index: Node


@dataclass(frozen=True)
class Call(Node):
    """Function or operator call. ``target`` is the receiver for member calls
    (``a.f(b)``); None for global calls and operators (named ``_&&_`` etc.)."""

    fn: str
    args: tuple[Node, ...]
    target: Optional[Node] = None


@dataclass(frozen=True)
class ListLit(Node):
    items: tuple[Node, ...]


@dataclass(frozen=True)
class MapLit(Node):
    entries: tuple[tuple[Node, Node], ...]


@dataclass(frozen=True)
class Bind(Node):
    """cel.bind(name, init, body)."""

    name: str
    init: Node
    body: Node


@dataclass(frozen=True)
class Comprehension(Node):
    """Desugared macro over ``iter_range``.

    kind: one of all/exists/exists_one/map/filter/transform_list/transform_map
    /transform_map_entry. ``iter_var2`` is set for two-var comprehensions.
    ``step2`` holds the transform for map-with-filter / transform variants.
    """

    kind: str
    iter_range: Node
    iter_var: str
    step: Node
    iter_var2: Optional[str] = None
    step2: Optional[Node] = None


def walk(node: Node):
    """Yield every node in the tree (pre-order)."""
    yield node
    if isinstance(node, (Select, Present)):
        yield from walk(node.operand)
    elif isinstance(node, Index):
        yield from walk(node.operand)
        yield from walk(node.index)
    elif isinstance(node, Call):
        if node.target is not None:
            yield from walk(node.target)
        for a in node.args:
            yield from walk(a)
    elif isinstance(node, ListLit):
        for a in node.items:
            yield from walk(a)
    elif isinstance(node, MapLit):
        for k, v in node.entries:
            yield from walk(k)
            yield from walk(v)
    elif isinstance(node, Bind):
        yield from walk(node.init)
        yield from walk(node.body)
    elif isinstance(node, Comprehension):
        yield from walk(node.iter_range)
        yield from walk(node.step)
        if node.step2 is not None:
            yield from walk(node.step2)
