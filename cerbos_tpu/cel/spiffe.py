"""SPIFFE ID support for CEL conditions.

Behavioral reference: internal/conditions/types/spiffe.go — spiffeID(),
spiffeTrustDomain(), matchers (spiffeMatchAny/Exact/OneOf/TrustDomain),
member methods id.isMemberOf(td) / id.path() / id.trustDomain() /
td.id() / td.name() / matcher.matchesID(id|string).
"""

from __future__ import annotations

from typing import Any

from .errors import CelError, no_such_overload
from .stdlib import _as_list, _as_str, func, method


import re as _re

# go-spiffe charsets: trust domains are lowercase-only; path segments are
# restricted and must not be empty, '.' or '..'
_TD_RX = _re.compile(r"^[a-z0-9._-]+$")
_SEG_RX = _re.compile(r"^[a-zA-Z0-9._-]+$")


def _validate_td(name: str, uri: str) -> str:
    if not name or not _TD_RX.match(name):
        raise CelError(f"invalid SPIFFE trust domain in {uri!r}")
    return name


def _validate_path(path: str, uri: str) -> str:
    if not path:
        return ""
    for seg in path.split("/"):
        if seg in ("", ".", "..") or not _SEG_RX.match(seg):
            raise CelError(f"invalid SPIFFE ID path in {uri!r}")
    return f"/{path}"


class SpiffeID:
    __slots__ = ("trust_domain", "path")

    def __init__(self, uri: str):
        if not uri.startswith("spiffe://"):
            raise CelError(f"invalid SPIFFE ID {uri!r}: scheme must be spiffe://")
        rest = uri[len("spiffe://"):]
        td, _, path = rest.partition("/")
        # go-spiffe rejects (not normalizes) malformed IDs — fail closed
        self.trust_domain = _validate_td(td, uri)
        self.path = _validate_path(path, uri)

    def uri(self) -> str:
        return f"spiffe://{self.trust_domain}{self.path}"

    def cel_type_name(self) -> str:
        return "cerbos.lib.spiffeID"

    def cel_equals(self, other: Any) -> bool:
        # the reference compares SPIFFE IDs against strings by URI
        if isinstance(other, str):
            return other == self.uri()
        return isinstance(other, SpiffeID) and other.uri() == self.uri()


class SpiffeTrustDomain:
    __slots__ = ("name",)

    def __init__(self, name: str):
        # accepts a bare name or a full spiffe:// URI (path discarded),
        # matching go-spiffe TrustDomainFromString
        if name.startswith("spiffe://"):
            name = name[len("spiffe://"):].partition("/")[0]
        self.name = _validate_td(name, name)

    def id_uri(self) -> str:
        return f"spiffe://{self.name}"

    def cel_type_name(self) -> str:
        return "cerbos.lib.spiffeTrustDomain"

    def cel_equals(self, other: Any) -> bool:
        return isinstance(other, SpiffeTrustDomain) and other.name == self.name


class SpiffeMatcher:
    __slots__ = ("kind", "arg")

    def __init__(self, kind: str, arg: Any = None):
        self.kind = kind  # any | exact | oneof | trustdomain
        self.arg = arg

    def matches(self, sid: SpiffeID) -> bool:
        if self.kind == "any":
            return True
        if self.kind == "exact":
            return sid.uri() == self.arg.uri()
        if self.kind == "oneof":
            return any(sid.uri() == x.uri() for x in self.arg)
        if self.kind == "trustdomain":
            return sid.trust_domain == self.arg.name
        return False

    def cel_type_name(self) -> str:
        return "cerbos.lib.spiffeMatcher"


def _as_spiffe_id(v: Any, fn: str) -> SpiffeID:
    if isinstance(v, SpiffeID):
        return v
    if isinstance(v, str):
        return SpiffeID(v)
    raise no_such_overload(fn, v)


@func("spiffeID")
def _f_spiffe_id(args, ctx):
    return SpiffeID(_as_str(args[0], "spiffeID"))


@func("spiffeTrustDomain")
def _f_spiffe_td(args, ctx):
    v = args[0]
    if isinstance(v, SpiffeID):
        return SpiffeTrustDomain(v.trust_domain)
    return SpiffeTrustDomain(_as_str(v, "spiffeTrustDomain"))


@func("spiffeMatchAny")
def _f_match_any(args, ctx):
    return SpiffeMatcher("any")


@func("spiffeMatchExact")
def _f_match_exact(args, ctx):
    return SpiffeMatcher("exact", _as_spiffe_id(args[0], "spiffeMatchExact"))


@func("spiffeMatchOneOf")
def _f_match_oneof(args, ctx):
    ids = [_as_spiffe_id(x, "spiffeMatchOneOf") for x in _as_list(args[0], "spiffeMatchOneOf")]
    return SpiffeMatcher("oneof", ids)


@func("spiffeMatchTrustDomain")
def _f_match_td(args, ctx):
    v = args[0]
    td = v if isinstance(v, SpiffeTrustDomain) else SpiffeTrustDomain(_as_str(v, "spiffeMatchTrustDomain"))
    return SpiffeMatcher("trustdomain", td)


@method("isMemberOf")
def _m_is_member_of(t, args, ctx):
    sid = _as_spiffe_id(t, "isMemberOf")
    td = args[0]
    if not isinstance(td, SpiffeTrustDomain):
        raise no_such_overload("isMemberOf", td)
    return sid.trust_domain == td.name


@method("path")
def _m_path(t, args, ctx):
    return _as_spiffe_id(t, "path").path


@method("trustDomain")
def _m_trust_domain(t, args, ctx):
    return SpiffeTrustDomain(_as_spiffe_id(t, "trustDomain").trust_domain)


@method("matchesID")
def _m_matches_id(t, args, ctx):
    if not isinstance(t, SpiffeMatcher):
        raise no_such_overload("matchesID", t)
    return t.matches(_as_spiffe_id(args[0], "matchesID"))


@method("name")
def _m_name(t, args, ctx):
    if isinstance(t, SpiffeTrustDomain):
        return t.name
    raise no_such_overload("name", t)


@method("id")
def _m_id(t, args, ctx):
    if isinstance(t, SpiffeTrustDomain):
        # the reference returns the ID *string* (td.IDString()), not an ID value
        return t.id_uri()
    raise no_such_overload("id", t)
