"""Light static checks at policy-compile time.

The reference type-checks conditions against typed declarations
(internal/conditions/cel.go:44-55); unknown root identifiers or misspelled
request fields fail compilation. This checker reproduces the checks that
matter for policy authoring without a full CEL type system: known root
identifiers and the request message field shapes.
"""

from __future__ import annotations

from .ast import Bind, Call, Comprehension, Ident, Index, ListLit, MapLit, Node, Present, Select
from .errors import CelParseError

ROOT_IDENTS = {
    "request", "R", "P", "runtime",
    "constants", "C", "variables", "V", "globals", "G",
    # CEL type identifiers
    "int", "uint", "double", "bool", "string", "bytes", "list", "map",
    "null_type", "type",
}

# cel-go indexes proto fields under both the proto (snake_case) and JSON
# (camelCase) names, so both spellings are legal in conditions (e.g.
# runtime.effective_derived_roles, internal/conditions/types/runtime.go:26).
_REQUEST_FIELDS = {"principal", "resource", "auxData", "aux_data"}
_PRINCIPAL_FIELDS = {"id", "roles", "attr", "policyVersion", "policy_version", "scope"}
_RESOURCE_FIELDS = {"kind", "id", "attr", "policyVersion", "policy_version", "scope"}
_RUNTIME_FIELDS = {"effectiveDerivedRoles", "effective_derived_roles"}
_AUXDATA_FIELDS = {"jwt"}


class CheckError(CelParseError):
    pass


def check(node: Node) -> None:
    """Raise CheckError for references that cel-go would reject at compile."""
    _walk(node, set())


def _walk(node: Node, bound: set[str]) -> None:
    if isinstance(node, Ident):
        if node.name not in ROOT_IDENTS and node.name not in bound:
            raise CheckError(f"undeclared reference to '{node.name}' (in container '')")
        return
    if isinstance(node, (Select, Present)):
        _check_select(node, bound)
        return
    if isinstance(node, Index):
        # variables/constants/globals are typed messages in the reference,
        # not maps: index syntax on them fails the type check
        # (compile corpus variables_index_lookup.yaml) — unless the name is
        # locally bound (a comprehension variable shadowing V/C/G)
        if (
            isinstance(node.operand, Ident)
            and node.operand.name not in bound
            and node.operand.name in ("V", "variables", "C", "constants", "G", "globals")
        ):
            raise CheckError(
                "found no matching overload for '_[_]' applied to "
                "'(cerbos.Variables, string)'"
            )
        _walk(node.operand, bound)
        _walk(node.index, bound)
        return
    if isinstance(node, Call):
        if node.target is not None:
            _walk(node.target, bound)
        for a in node.args:
            _walk(a, bound)
        return
    if isinstance(node, ListLit):
        for a in node.items:
            _walk(a, bound)
        return
    if isinstance(node, MapLit):
        for k, v in node.entries:
            _walk(k, bound)
            _walk(v, bound)
        return
    if isinstance(node, Bind):
        _walk(node.init, bound)
        _walk(node.body, bound | {node.name})
        return
    if isinstance(node, Comprehension):
        _walk(node.iter_range, bound)
        inner = bound | {node.iter_var}
        if node.iter_var2:
            inner |= {node.iter_var2}
        _walk(node.step, inner)
        if node.step2 is not None:
            _walk(node.step2, inner)
        return


def _check_select(node: Node, bound: set[str]) -> None:
    field = node.field  # type: ignore[union-attr]
    operand = node.operand  # type: ignore[union-attr]
    # typed message field checks along known chains
    if isinstance(operand, Ident) and operand.name not in bound:
        if operand.name == "request" and field not in _REQUEST_FIELDS:
            raise CheckError(f"undefined field '{field}' on request")
        if operand.name == "P" and field not in _PRINCIPAL_FIELDS:
            raise CheckError(f"undefined field '{field}' on principal")
        if operand.name == "R" and field not in _RESOURCE_FIELDS:
            raise CheckError(f"undefined field '{field}' on resource")
        if operand.name == "runtime" and field not in _RUNTIME_FIELDS:
            raise CheckError(f"undefined field '{field}' on runtime")
    elif isinstance(operand, Select) and isinstance(operand.operand, Ident) and operand.operand.name == "request":
        if operand.field == "principal" and field not in _PRINCIPAL_FIELDS:
            raise CheckError(f"undefined field '{field}' on request.principal")
        if operand.field == "resource" and field not in _RESOURCE_FIELDS:
            raise CheckError(f"undefined field '{field}' on request.resource")
        if operand.field in ("auxData", "aux_data") and field not in _AUXDATA_FIELDS:
            raise CheckError(f"undefined field '{field}' on request.auxData")
    _walk(operand, bound)
