"""CEL evaluation and parse errors."""

from __future__ import annotations


class CelParseError(ValueError):
    def __init__(self, msg: str, pos: int = -1, src: str = ""):
        self.pos = pos
        self.src = src
        loc = f" at offset {pos}" if pos >= 0 else ""
        super().__init__(f"{msg}{loc}")


class CelError(Exception):
    """A CEL runtime error value. Propagates like cel-go errors: strict
    functions re-raise it; ``||``/``&&``/``?:`` and comprehension aggregates
    absorb it where the spec requires."""

    def __init__(self, msg: str):
        self.msg = msg
        super().__init__(msg)


def no_such_overload(fn: str, *args: object) -> CelError:
    from .values import celtype_name

    sig = ", ".join(celtype_name(a) for a in args)
    return CelError(f"found no matching overload for '{fn}' applied to ({sig})")


def no_such_key(key: object) -> CelError:
    return CelError(f"no such key: {key!r}")


def no_such_attribute(name: str) -> CelError:
    return CelError(f"no such attribute: {name}")
