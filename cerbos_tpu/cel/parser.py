"""Recursive-descent CEL parser (grammar per the CEL spec).

Produces the AST in :mod:`cerbos_tpu.cel.ast`, desugaring macros at parse time
the way cel-go's macro expander does: ``has()``, the comprehension macros
(``all``/``exists``/``exists_one``/``map``/``filter`` and their two-var
variants), and ``cel.bind``.
"""

from __future__ import annotations

from typing import Any, Optional

from .ast import Bind, Call, Comprehension, Ident, Index, ListLit, Lit, MapLit, Node, Present, Select
from .errors import CelParseError
from .values import UInt, check_int

_RESERVED = {
    "as", "break", "const", "continue", "else", "for", "function", "if",
    "import", "let", "loop", "package", "namespace", "return", "var",
    "void", "while",
}

_TWO_CHAR_OPS = {"==", "!=", "<=", ">=", "&&", "||"}
_PUNCT = set("()[]{}.,?:;+-*/%<>!=&|")


class _Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: Any, pos: int):
        self.kind = kind  # IDENT, INT, UINT, FLOAT, STRING, BYTES, OP, EOF
        self.value = value
        self.pos = pos

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self.kind}({self.value!r})"


def _is_ident_start(c: str) -> bool:
    return c.isalpha() or c == "_"


def _is_ident_char(c: str) -> bool:
    return c.isalnum() or c == "_"


_ESCAPES = {
    "a": "\a", "b": "\b", "f": "\f", "n": "\n", "r": "\r", "t": "\t",
    "v": "\v", "\\": "\\", "'": "'", '"': '"', "`": "`", "?": "?",
}


def _tokenize(src: str) -> list[_Token]:
    toks: list[_Token] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            while i < n and src[i] != "\n":
                i += 1
            continue
        start = i
        # string / bytes literals with optional r/b prefixes (any order/case)
        if c in "rRbB" or c in "'\"":
            j = i
            raw = is_bytes = False
            while j < n and src[j] in "rRbB":
                if src[j] in "rR":
                    raw = True
                else:
                    is_bytes = True
                j += 1
            if j < n and src[j] in "'\"" and j - i <= 2:
                s, j2 = _scan_string(src, j, raw, as_bytes=is_bytes)
                toks.append(_Token("BYTES" if is_bytes else "STRING", s, start))
                i = j2
                continue
            # fall through: plain identifier starting with r/b
        if _is_ident_start(c):
            j = i
            while j < n and _is_ident_char(src[j]):
                j += 1
            toks.append(_Token("IDENT", src[i:j], start))
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            tok, i = _scan_number(src, i)
            toks.append(tok)
            continue
        two = src[i : i + 2]
        if two in _TWO_CHAR_OPS:
            toks.append(_Token("OP", two, start))
            i += 2
            continue
        if c in _PUNCT:
            toks.append(_Token("OP", c, start))
            i += 1
            continue
        raise CelParseError(f"unexpected character {c!r}", start, src)
    toks.append(_Token("EOF", None, n))
    return toks


def _scan_number(src: str, i: int) -> tuple[_Token, int]:
    n = len(src)
    start = i
    if src[i] == "0" and i + 1 < n and src[i + 1] in "xX":
        j = i + 2
        while j < n and src[j] in "0123456789abcdefABCDEF":
            j += 1
        if j == i + 2:
            raise CelParseError("invalid hex literal", start, src)
        if j < n and src[j] in "uU":
            try:
                return _Token("UINT", UInt(int(src[i:j], 16)), start), j + 1
            except Exception:
                raise CelParseError("uint literal out of range", start, src) from None
        # range-checked in primary() after any sign folding
        return _Token("INT", int(src[i:j], 16), start), j
    j = i
    is_float = False
    while j < n and src[j].isdigit():
        j += 1
    if j < n and src[j] == "." and j + 1 < n and src[j + 1].isdigit():
        is_float = True
        j += 1
        while j < n and src[j].isdigit():
            j += 1
    if j < n and src[j] in "eE":
        k = j + 1
        if k < n and src[k] in "+-":
            k += 1
        if k < n and src[k].isdigit():
            is_float = True
            j = k
            while j < n and src[j].isdigit():
                j += 1
    if not is_float and j < n and src[j] in "uU":
        return _Token("UINT", UInt(int(src[i:j])), start), j + 1
    if is_float:
        return _Token("FLOAT", float(src[i:j]), start), j
    # no range check here: the parser folds a leading '-' before checking,
    # so INT_MIN (-9223372036854775808) lexes as 9223372036854775808
    return _Token("INT", int(src[i:j]), start), j


def _scan_string(src: str, i: int, raw: bool, as_bytes: bool = False) -> tuple[str | bytes, int]:
    """Scan a string/bytes literal body.

    In bytes literals, ``\\xFF``/``\\377`` escapes are raw byte values
    (b"\\xff" is one byte), while plain characters contribute their UTF-8
    encoding — matching cel-go. In string literals they are code points.
    """
    n = len(src)
    quote = src[i]
    triple = src[i : i + 3] in ('"""', "'''")
    close = quote * 3 if triple else quote
    i += len(close)
    out: list[str] = []
    bout = bytearray()
    while i < n:
        if src.startswith(close, i):
            return (bytes(bout), i + len(close)) if as_bytes else ("".join(out), i + len(close))
        c = src[i]
        if c == "\n" and not triple:
            raise CelParseError("newline in string literal", i, src)
        if c == "\\" and not raw:
            if i + 1 >= n:
                raise CelParseError("unterminated escape", i, src)
            e = src[i + 1]
            if e in _ESCAPES:
                if as_bytes:
                    bout.extend(_ESCAPES[e].encode("utf-8"))
                else:
                    out.append(_ESCAPES[e])
                i += 2
            elif e in ("x", "X", "u", "U") or e.isdigit():
                if e in ("u", "U") and as_bytes:
                    # cel-go rejects unicode escapes inside bytes literals
                    raise CelParseError(f"\\{e} escape is not allowed in bytes literals", i, src)
                if e in ("x", "X"):
                    digits, base, skip = src[i + 2 : i + 4], 16, 4
                elif e == "u":
                    digits, base, skip = src[i + 2 : i + 6], 16, 6
                elif e == "U":
                    digits, base, skip = src[i + 2 : i + 10], 16, 10
                else:
                    digits, base, skip = src[i + 1 : i + 4], 8, 4
                if as_bytes:
                    # hex/octal escapes in bytes literals are raw byte values
                    try:
                        b = int(digits, base)
                        if not 0 <= b <= 0xFF:
                            raise ValueError
                        bout.append(b)
                    except (ValueError, OverflowError):
                        raise CelParseError(f"invalid escape sequence \\{e}{digits}", i, src) from None
                else:
                    try:
                        ch = chr(int(digits, base))
                    except (ValueError, OverflowError):
                        raise CelParseError(f"invalid escape sequence \\{e}{digits}", i, src) from None
                    if as_bytes:
                        bout.extend(ch.encode("utf-8"))
                    else:
                        out.append(ch)
                i += skip
            else:
                raise CelParseError(f"invalid escape \\{e}", i, src)
        else:
            if as_bytes:
                bout.extend(c.encode("utf-8"))
            else:
                out.append(c)
            i += 1
    raise CelParseError("unterminated string literal", i, src)


_ONE_VAR_MACROS = {
    "all": "all", "exists": "exists", "exists_one": "exists_one",
    "existsOne": "exists_one", "map": "map", "filter": "filter",
    "sortBy": "sort_by",
}
_TWO_VAR_MACROS = {
    "all": "all", "exists": "exists", "existsOne": "exists_one", "exists_one": "exists_one",
    "transformList": "transform_list", "transformMap": "transform_map",
    "transformMapEntry": "transform_map_entry",
}


# Each nesting level costs ~9 interpreter stack frames in this
# recursive-descent parser, so the cap must stay well inside Python's default
# 1000-frame recursion limit. cel-go uses 250; real policy conditions are
# nowhere near either bound.
_MAX_RECURSION_DEPTH = 80


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.toks = _tokenize(src)
        self.i = 0
        self.depth = 0

    def peek(self) -> _Token:
        return self.toks[self.i]

    def next(self) -> _Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "OP" and t.value == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        t = self.next()
        if t.kind != "OP" or t.value != op:
            raise CelParseError(f"expected {op!r}, got {t.value!r}", t.pos, self.src)

    def _check_int_lit(self, v: int, pos: int) -> int:
        try:
            return check_int(v)
        except Exception:
            raise CelParseError("integer literal out of range", pos, self.src) from None

    def parse(self) -> Node:
        e = self.expr()
        t = self.peek()
        if t.kind != "EOF":
            raise CelParseError(f"unexpected trailing input {t.value!r}", t.pos, self.src)
        return e

    def expr(self) -> Node:
        self.depth += 1
        if self.depth > _MAX_RECURSION_DEPTH:
            raise CelParseError("expression recursion limit exceeded", self.peek().pos, self.src)
        try:
            return self._expr_inner()
        finally:
            self.depth -= 1

    def _expr_inner(self) -> Node:
        cond = self.or_expr()
        if self.accept_op("?"):
            then = self.or_expr()
            self.expect_op(":")
            other = self.expr()
            return Call("_?_:_", (cond, then, other))
        return cond

    def or_expr(self) -> Node:
        left = self.and_expr()
        while self.accept_op("||"):
            right = self.and_expr()
            left = Call("_||_", (left, right))
        return left

    def and_expr(self) -> Node:
        left = self.relation()
        while self.accept_op("&&"):
            right = self.relation()
            left = Call("_&&_", (left, right))
        return left

    _REL_NAMES = {"<": "_<_", "<=": "_<=_", ">": "_>_", ">=": "_>=_", "==": "_==_", "!=": "_!=_", "in": "_in_"}

    def relation(self) -> Node:
        # left-associative: `1 < 2 == true` parses as ((1 < 2) == true)
        left = self.addition()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("<", "<=", ">", ">=", "==", "!="):
                op = t.value
                self.i += 1
            elif t.kind == "IDENT" and t.value == "in":
                op = "in"
                self.i += 1
            else:
                return left
            left = Call(self._REL_NAMES[op], (left, self.addition()))

    def addition(self) -> Node:
        left = self.multiplication()
        while True:
            if self.accept_op("+"):
                left = Call("_+_", (left, self.multiplication()))
            elif self.accept_op("-"):
                left = Call("_-_", (left, self.multiplication()))
            else:
                return left

    def multiplication(self) -> Node:
        left = self.unary()
        while True:
            if self.accept_op("*"):
                left = Call("_*_", (left, self.unary()))
            elif self.accept_op("/"):
                left = Call("_/_", (left, self.unary()))
            elif self.accept_op("%"):
                left = Call("_%_", (left, self.unary()))
            else:
                return left

    def unary(self) -> Node:
        if self.accept_op("!"):
            count = 1
            while self.accept_op("!"):
                count += 1
            operand = self.member()
            return operand if count % 2 == 0 else Call("!_", (operand,))
        if self.accept_op("-"):
            count = 1
            while self.accept_op("-"):
                count += 1
            # fold negation into a directly-following numeric literal so that
            # INT_MIN parses (cel-go does the same in its parser)
            nt = self.peek()
            if nt.kind == "INT":
                self.next()
                v = -nt.value if count % 2 == 1 else nt.value
                e: Node = Lit(self._check_int_lit(v, nt.pos))
                return self._member_suffix(e)
            if nt.kind == "FLOAT":
                self.next()
                e = Lit(-nt.value if count % 2 == 1 else nt.value)
                return self._member_suffix(e)
            operand = self.member()
            return Call("-_", (operand,)) if count % 2 == 1 else operand
        return self.member()

    def member(self) -> Node:
        return self._member_suffix(self.primary())

    def _member_suffix(self, e: Node) -> Node:
        while True:
            if self.accept_op("."):
                t = self.next()
                if t.kind != "IDENT":
                    raise CelParseError("expected identifier after '.'", t.pos, self.src)
                name = t.value
                if self.accept_op("("):
                    args = self.arg_list(")")
                    e = self.member_call(e, name, args)
                else:
                    e = Select(e, name)
            elif self.accept_op("["):
                idx = self.expr()
                self.expect_op("]")
                e = Index(e, idx)
            else:
                return e

    def member_call(self, target: Node, name: str, args: list[Node]) -> Node:
        # macro desugaring
        if len(args) == 2 and name in _ONE_VAR_MACROS and isinstance(args[0], Ident):
            kind = _ONE_VAR_MACROS[name]
            return Comprehension(kind=kind, iter_range=target, iter_var=args[0].name, step=args[1])
        if len(args) == 3 and name == "map" and isinstance(args[0], Ident):
            # e.map(x, filter, transform)
            return Comprehension(kind="map", iter_range=target, iter_var=args[0].name, step=args[2], step2=args[1])
        if len(args) >= 3 and name in _TWO_VAR_MACROS and isinstance(args[0], Ident) and isinstance(args[1], Ident):
            kind = _TWO_VAR_MACROS[name]
            if name in ("transformList", "transformMap", "transformMapEntry"):
                if len(args) == 3:
                    return Comprehension(kind=kind, iter_range=target, iter_var=args[0].name, iter_var2=args[1].name, step=args[2])
                if len(args) == 4:
                    return Comprehension(kind=kind, iter_range=target, iter_var=args[0].name, iter_var2=args[1].name, step=args[3], step2=args[2])
            elif len(args) == 3:
                return Comprehension(kind=kind, iter_range=target, iter_var=args[0].name, iter_var2=args[1].name, step=args[2])
        return Call(name, tuple(args), target=target)

    def arg_list(self, close: str) -> list[Node]:
        args: list[Node] = []
        if self.accept_op(close):
            return args
        while True:
            args.append(self.expr())
            if self.accept_op(","):
                if self.accept_op(close):  # trailing comma
                    return args
                continue
            self.expect_op(close)
            return args

    def primary(self) -> Node:
        t = self.peek()
        if t.kind == "OP":
            if t.value == "(":
                self.next()
                e = self.expr()
                self.expect_op(")")
                return e
            if t.value == "[":
                self.next()
                items = self.arg_list("]")
                return ListLit(tuple(items))
            if t.value == "{":
                self.next()
                return self.map_lit()
            if t.value == ".":
                # leading-dot absolute reference: `.a.b`
                self.next()
                t2 = self.next()
                if t2.kind != "IDENT":
                    raise CelParseError("expected identifier after leading '.'", t2.pos, self.src)
                return self.global_or_call(t2.value)
            raise CelParseError(f"unexpected token {t.value!r}", t.pos, self.src)
        if t.kind == "INT":
            self.next()
            return Lit(self._check_int_lit(t.value, t.pos))
        if t.kind in ("UINT", "FLOAT", "STRING", "BYTES"):
            self.next()
            return Lit(t.value)
        if t.kind == "IDENT":
            self.next()
            name = t.value
            if name == "true":
                return Lit(True)
            if name == "false":
                return Lit(False)
            if name == "null":
                return Lit(None)
            if name in _RESERVED:
                raise CelParseError(f"reserved word {name!r}", t.pos, self.src)
            return self.global_or_call(name)
        raise CelParseError(f"unexpected token {t.value!r}", t.pos, self.src)

    def global_or_call(self, name: str) -> Node:
        # qualified function names: cel.bind, math.greatest, base64.encode, ...
        if self.accept_op("("):
            args = self.arg_list(")")
            if name == "has":
                if len(args) != 1 or not isinstance(args[0], Select):
                    raise CelParseError("has() requires a field selection argument", self.peek().pos, self.src)
                sel = args[0]
                return Present(sel.operand, sel.field)
            return Call(name, tuple(args))
        return Ident(name)

    def map_lit(self) -> Node:
        entries: list[tuple[Node, Node]] = []
        if self.accept_op("}"):
            return MapLit(tuple(entries))
        while True:
            k = self.expr()
            self.expect_op(":")
            v = self.expr()
            entries.append((k, v))
            if self.accept_op(","):
                if self.accept_op("}"):
                    return MapLit(tuple(entries))
                continue
            self.expect_op("}")
            return MapLit(tuple(entries))


def _rewrite_namespaced(node: Node) -> Node:
    """Turn Select-chains used as namespaced calls into plain Calls.

    The tokenizer produces ``Call(fn='bind', target=Ident('cel'))`` for
    ``cel.bind(...)`` via member_call; normalize the known namespaces
    (cel, math, base64, lists, strings) into global function names
    ``cel.bind``/``math.greatest``/... and desugar cel.bind into Bind.
    """
    if isinstance(node, Call) and isinstance(node.target, Ident) and node.target.name in ("cel", "math", "base64", "lists", "strings"):
        fn = f"{node.target.name}.{node.fn}"
        args = tuple(_rewrite_namespaced(a) for a in node.args)
        if fn == "cel.bind":
            if len(args) == 3 and isinstance(args[0], Ident):
                return Bind(args[0].name, args[1], args[2])
            raise CelParseError("cel.bind requires (ident, init, body)")
        return Call(fn, args)
    if isinstance(node, Call):
        return Call(
            node.fn,
            tuple(_rewrite_namespaced(a) for a in node.args),
            target=_rewrite_namespaced(node.target) if node.target is not None else None,
        )
    if isinstance(node, Select):
        return Select(_rewrite_namespaced(node.operand), node.field)
    if isinstance(node, Present):
        return Present(_rewrite_namespaced(node.operand), node.field)
    if isinstance(node, Index):
        return Index(_rewrite_namespaced(node.operand), _rewrite_namespaced(node.index))
    if isinstance(node, ListLit):
        return ListLit(tuple(_rewrite_namespaced(a) for a in node.items))
    if isinstance(node, MapLit):
        return MapLit(tuple((_rewrite_namespaced(k), _rewrite_namespaced(v)) for k, v in node.entries))
    if isinstance(node, Bind):
        return Bind(node.name, _rewrite_namespaced(node.init), _rewrite_namespaced(node.body))
    if isinstance(node, Comprehension):
        return Comprehension(
            kind=node.kind,
            iter_range=_rewrite_namespaced(node.iter_range),
            iter_var=node.iter_var,
            step=_rewrite_namespaced(node.step),
            iter_var2=node.iter_var2,
            step2=_rewrite_namespaced(node.step2) if node.step2 is not None else None,
        )
    return node


def parse(src: str) -> Node:
    """Parse a CEL expression into an AST."""
    return _rewrite_namespaced(_Parser(src).parse())


def token_offset(
    src: str, anchor: str, nth: int = 0, kinds: Optional[tuple[str, ...]] = None
) -> int:
    """Character offset of the ``nth`` token whose text equals ``anchor``.

    The static analyzer (tpu/analyze.py) anchors findings to real token
    positions in a condition's original source instead of substring matches,
    so an operator name inside a string literal never misleads a report:
    by default STRING/BYTES tokens are skipped; pass ``kinds=("STRING",)``
    to anchor on a string literal instead. Returns -1 when the anchor is
    absent or the source does not tokenize.
    """
    try:
        toks = _tokenize(src)
    except CelParseError:
        return -1
    seen = 0
    for t in toks:
        if t.kind == "EOF":
            break
        if kinds is None:
            if t.kind in ("STRING", "BYTES"):
                continue
        elif t.kind not in kinds:
            continue
        if str(t.value) == anchor:
            if seen == nth:
                return t.pos
            seen += 1
    return -1
