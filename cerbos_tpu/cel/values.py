"""CEL value model: wrappers, typing, equality, ordering, arithmetic.

CEL types map onto Python as: int->int, uint->UInt, double->float, bool->bool,
string->str, bytes->bytes, list->list, map->dict, null->None,
timestamp->Timestamp (tz-aware datetime), duration->Duration (timedelta).
64-bit overflow raises CelError, matching cel-go runtime semantics.
"""

from __future__ import annotations

import datetime as _dt
import math
import re as _re
from typing import Any

from .errors import CelError, no_such_overload

INT_MIN = -(2**63)
INT_MAX = 2**63 - 1
UINT_MAX = 2**64 - 1


class UInt(int):
    """CEL uint. Subclasses int so hashing/dict keys work naturally."""

    __slots__ = ()

    def __new__(cls, v: int):
        if not 0 <= v <= UINT_MAX:
            raise CelError("unsigned integer overflow")
        return super().__new__(cls, v)

    def __repr__(self) -> str:  # pragma: no cover
        return f"{int(self)}u"


class Timestamp(_dt.datetime):
    """CEL timestamp: a tz-aware datetime pinned to UTC internally."""

    __slots__ = ()

    @classmethod
    def from_datetime(cls, dt: _dt.datetime) -> "Timestamp":
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        dt = dt.astimezone(_dt.timezone.utc)
        return cls(
            dt.year, dt.month, dt.day, dt.hour, dt.minute, dt.second,
            dt.microsecond, tzinfo=_dt.timezone.utc,
        )

    @classmethod
    def parse(cls, s: str) -> "Timestamp":
        txt = s.strip()
        if txt.endswith(("z", "Z")):
            txt = txt[:-1] + "+00:00"
        # Python 3.10's fromisoformat only accepts exactly 3 or 6 fractional
        # digits; RFC3339 allows any precision ("...T23:59:59.5Z").
        m = _re.match(r"^(.*T\d{2}:\d{2}:\d{2})\.(\d+)(.*)$", txt)
        if m:
            frac = (m.group(2) + "000000")[:6]
            txt = f"{m.group(1)}.{frac}{m.group(3)}"
        try:
            # RFC3339 with fractional seconds of any precision
            dt = _dt.datetime.fromisoformat(txt)
        except ValueError:
            raise CelError(f"invalid timestamp {s!r}") from None
        if dt.tzinfo is None:
            raise CelError(f"invalid timestamp {s!r}: missing timezone")
        return cls.from_datetime(dt)

    def rfc3339(self) -> str:
        us = self.microsecond
        base = self.strftime("%Y-%m-%dT%H:%M:%S")
        if us:
            frac = f"{us:06d}".rstrip("0")
            # pad to multiple of 3 digits, matching protobuf JSON formatting
            pad = (3 - len(frac) % 3) % 3
            base += "." + frac + "0" * pad
        return base + "Z"


class Duration(_dt.timedelta):
    """CEL duration (microsecond resolution)."""

    __slots__ = ()

    _UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}

    @classmethod
    def from_timedelta(cls, td: _dt.timedelta) -> "Duration":
        return cls(days=td.days, seconds=td.seconds, microseconds=td.microseconds)

    @classmethod
    def parse(cls, s: str) -> "Duration":
        # Go duration syntax: [-+]?([0-9]*(\.[0-9]*)?(ns|us|µs|ms|s|m|h))+ or "0"
        txt = s.strip()
        if txt in ("0", "+0", "-0"):
            return cls(0)
        neg = False
        if txt and txt[0] in "+-":
            neg = txt[0] == "-"
            txt = txt[1:]
        if not txt:
            raise CelError(f"invalid duration {s!r}")
        total = 0.0
        i, n = 0, len(txt)
        while i < n:
            j = i
            while j < n and (txt[j].isdigit() or txt[j] == "."):
                j += 1
            if j == i:
                raise CelError(f"invalid duration {s!r}")
            try:
                num = float(txt[i:j])
            except ValueError:
                raise CelError(f"invalid duration {s!r}") from None
            k = j
            while k < n and not (txt[k].isdigit() or txt[k] == "."):
                k += 1
            unit = txt[j:k].replace("µs", "us")
            if unit not in cls._UNITS:
                raise CelError(f"invalid duration {s!r}: unknown unit {unit!r}")
            total += num * cls._UNITS[unit]
            i = k
        if neg:
            total = -total
        return cls(seconds=total)

    def go_string(self) -> str:
        """Format like Go's time.Duration.String()."""
        total_us = self.days * 86_400_000_000 + self.seconds * 1_000_000 + self.microseconds
        if total_us == 0:
            return "0s"
        neg = total_us < 0
        us = abs(total_us)
        out = ""
        h, rem = divmod(us, 3_600_000_000)
        m, rem = divmod(rem, 60_000_000)
        secs = rem / 1_000_000
        if h:
            out += f"{h}h"
        if m:
            out += f"{m}m"
        if secs or not out:
            s_txt = f"{secs:.6f}".rstrip("0").rstrip(".")
            out += f"{s_txt}s"
        return ("-" if neg else "") + out

    def total_seconds_float(self) -> float:
        return self.total_seconds()


def celtype_name(v: Any) -> str:
    if v is None:
        return "null_type"
    t = type(v)
    if t is bool:
        return "bool"
    if t is UInt:
        return "uint"
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, UInt):
        return "uint"
    if isinstance(v, Timestamp):
        return "google.protobuf.Timestamp"
    if isinstance(v, Duration):
        return "google.protobuf.Duration"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "double"
    if isinstance(v, str):
        return "string"
    if isinstance(v, bytes):
        return "bytes"
    if isinstance(v, (list, tuple)):
        return "list"
    if isinstance(v, dict):
        return "map"
    if callable(getattr(v, "cel_type_name", None)):
        return v.cel_type_name()
    return t.__name__


class CelType:
    """A CEL type value (result of type(x))."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CelType) and other.name == self.name

    def __hash__(self) -> int:
        return hash(("CelType", self.name))

    def __repr__(self) -> str:  # pragma: no cover
        return self.name

    def cel_type_name(self) -> str:
        return "type"


def is_number(v: Any) -> bool:
    return not isinstance(v, bool) and isinstance(v, (int, float)) and not isinstance(v, (Timestamp, Duration))


def check_int(v: int) -> int:
    if not INT_MIN <= v <= INT_MAX:
        raise CelError("integer overflow")
    return v


def check_uint(v: int) -> UInt:
    if not 0 <= v <= UINT_MAX:
        raise CelError("unsigned integer overflow")
    return UInt(v)


def values_equal(a: Any, b: Any) -> bool:
    """CEL equality: cross-type numeric, deep for lists/maps, False on type mismatch."""
    if type(a) is bool or type(b) is bool:
        return type(a) is bool and type(b) is bool and a == b
    if a is None or b is None:
        return a is None and b is None
    if is_number(a) and is_number(b):
        if isinstance(a, float) and math.isnan(a):
            return False
        if isinstance(b, float) and math.isnan(b):
            return False
        return a == b
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if isinstance(a, bytes) and isinstance(b, bytes):
        return a == b
    if isinstance(a, Timestamp) and isinstance(b, Timestamp):
        return a == b
    if isinstance(a, Duration) and isinstance(b, Duration):
        return a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        return all(values_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        if len(a) != len(b):
            return False
        for k, v in a.items():
            found = False
            for k2, v2 in b.items():
                if keys_equal(k, k2):
                    found = values_equal(v, v2)
                    break
            if not found:
                return False
        return True
    if isinstance(a, CelType) and isinstance(b, CelType):
        return a == b
    eq = getattr(a, "cel_equals", None)
    if eq is not None:
        return bool(eq(b))
    return False


def keys_equal(a: Any, b: Any) -> bool:
    if type(a) is bool or type(b) is bool:
        return type(a) is bool and type(b) is bool and a == b
    if is_number(a) and is_number(b):
        return a == b
    return type(a) is type(b) and a == b


def compare(a: Any, b: Any) -> int:
    """Three-way compare; raises CelError for unorderable pairs."""
    if type(a) is bool and type(b) is bool:
        return (a > b) - (a < b)
    if is_number(a) and is_number(b):
        af, bf = a, b
        if isinstance(af, float) and math.isnan(af):
            raise CelError("NaN is not ordered")
        if isinstance(bf, float) and math.isnan(bf):
            raise CelError("NaN is not ordered")
        return (af > bf) - (af < bf)
    if isinstance(a, str) and isinstance(b, str):
        return (a > b) - (a < b)
    if isinstance(a, bytes) and isinstance(b, bytes):
        return (a > b) - (a < b)
    if isinstance(a, Timestamp) and isinstance(b, Timestamp):
        return (a > b) - (a < b)
    if isinstance(a, Duration) and isinstance(b, Duration):
        return (a > b) - (a < b)
    raise no_such_overload("compare", a, b)
