"""Structured, versioned serialization of the compiled policy IR.

Replaces the pickle payload of v2 bundles: the encoding is pure data (JSON
with tagged nodes + a structural intern table), so decoding untrusted
bundles is safe — no code execution, only dataclass construction from a
closed vocabulary. This is the analogue of the reference's marshaled
rule-table proto (internal/ruletable/index/marshal.go:20,240), which is
likewise safe to load from anywhere.

Layout: ``{"v": 1, "nodes": [...], "policies": [...]}`` where ``nodes`` is
a flat table of unique encoded objects (CEL AST nodes, conditions, exprs,
variables, outputs, params) referenced by index. Structural sharing does
double duty: identical conditions across policies (the common case — policy
fleets repeat templates) encode once, and ``PolicyParams`` object identity
— which downstream caches key on (``params.cache_key``) — survives the
round trip because each table entry decodes to exactly one object.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional

from .cel import ast as A
from .compile.compiler import (
    CompiledCondition,
    CompiledDerivedRole,
    CompiledExpr,
    CompiledOutput,
    CompiledPolicy,
    CompiledPrincipalPolicy,
    CompiledPrincipalRule,
    CompiledResourcePolicy,
    CompiledResourceRule,
    CompiledRolePolicy,
    CompiledRoleRule,
    CompiledVariable,
    PolicyParams,
)
from .policy import model

CODEC_VERSION = 1


class CodecError(ValueError):
    pass


# -- values (Lit payloads, constants, source attributes) ----------------------


def _enc_value(v: Any) -> Any:
    """JSON-safe value encoding preserving the distinctions JSON collapses:
    bytes, non-string map keys, int-vs-float (JSON already keeps), tuples."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, bytes):
        return {"$B": base64.b64encode(v).decode()}
    if isinstance(v, (list, tuple)):
        return {"$L": [_enc_value(x) for x in v]}
    if isinstance(v, (set, frozenset)):
        return {"$S": [_enc_value(x) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        return {"$M": [[_enc_value(k), _enc_value(x)] for k, x in v.items()]}
    raise CodecError(f"unencodable value type {type(v).__name__}")


def _dec_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        if "$B" in v:
            return base64.b64decode(v["$B"])
        if "$L" in v:
            return [_dec_value(x) for x in v["$L"]]
        if "$S" in v:
            return frozenset(_dec_value(x) for x in v["$S"])
        if "$M" in v:
            return {_dec_value(k): _dec_value(x) for k, x in v["$M"]}
    raise CodecError(f"malformed value payload: {v!r}")


# -- intern-table encoder -----------------------------------------------------


class _Encoder:
    def __init__(self) -> None:
        self.nodes: list[Any] = []
        self._by_id: dict[int, int] = {}  # id(obj) -> index (identity fast path)
        self._by_key: dict[Any, int] = {}  # structural key -> index

    def _put(self, obj: Any, key: Any, encoded: Any) -> int:
        idx = len(self.nodes)
        self.nodes.append(encoded)
        self._by_id[id(obj)] = idx
        if key is not None:
            self._by_key[key] = idx
        return idx

    def ref(self, obj: Any) -> Optional[int]:
        if obj is None:
            return None
        hit = self._by_id.get(id(obj))
        if hit is not None:
            return hit
        if isinstance(obj, A.Node):
            return self._node(obj)
        if isinstance(obj, CompiledExpr):
            key = ("E", obj.original, self._node(obj.node))
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, ["E", obj.original, self._node(obj.node)])
        if isinstance(obj, CompiledCondition):
            enc = [
                "C",
                obj.kind,
                self.ref(obj.expr),
                [self.ref(c) for c in obj.children],
            ]
            key = ("C", json.dumps(enc[1:], sort_keys=False))
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, CompiledVariable):
            enc = ["V", obj.name, self.ref(obj.expr)]
            key = ("V", obj.name, enc[2])
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, CompiledOutput):
            enc = ["O", self.ref(obj.rule_activated), self.ref(obj.condition_not_met)]
            key = ("O", enc[1], enc[2])
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, PolicyParams):
            # params are interned by IDENTITY only: the decoder must produce
            # one object per encoded entry so cache keys keyed on object
            # identity stay coherent, but two structurally equal params from
            # different policies remain distinct (as built)
            enc = [
                "P",
                _enc_value(obj.constants),
                [self.ref(v) for v in obj.ordered_variables],
            ]
            return self._put(obj, None, enc)
        raise CodecError(f"unencodable object {type(obj).__name__}")

    def _node(self, n: A.Node) -> int:
        hit = self._by_id.get(id(n))
        if hit is not None:
            return hit
        if isinstance(n, A.Lit):
            enc: list[Any] = ["lit", _enc_value(n.value)]
        elif isinstance(n, A.Ident):
            enc = ["id", n.name]
        elif isinstance(n, A.Select):
            enc = ["sel", self._node(n.operand), n.field]
        elif isinstance(n, A.Present):
            enc = ["has", self._node(n.operand), n.field]
        elif isinstance(n, A.Index):
            enc = ["ix", self._node(n.operand), self._node(n.index)]
        elif isinstance(n, A.Call):
            enc = [
                "call",
                n.fn,
                [self._node(a) for a in n.args],
                self._node(n.target) if n.target is not None else None,
            ]
        elif isinstance(n, A.ListLit):
            enc = ["list", [self._node(a) for a in n.items]]
        elif isinstance(n, A.MapLit):
            enc = ["map", [[self._node(k), self._node(v)] for k, v in n.entries]]
        elif isinstance(n, A.Bind):
            enc = ["bind", n.name, self._node(n.init), self._node(n.body)]
        elif isinstance(n, A.Comprehension):
            enc = [
                "comp",
                n.kind,
                self._node(n.iter_range),
                n.iter_var,
                self._node(n.step),
                n.iter_var2,
                self._node(n.step2) if n.step2 is not None else None,
            ]
        else:
            raise CodecError(f"unencodable AST node {type(n).__name__}")
        key = json.dumps(enc, sort_keys=False, default=_json_default)
        hit = self._by_key.get(key)
        if hit is not None:
            self._by_id[id(n)] = hit
            return hit
        return self._put(n, key, enc)


def _json_default(o: Any) -> Any:
    raise CodecError(f"unencodable literal {type(o).__name__}")


def _enc_schemas(s: Optional[model.Schemas]) -> Any:
    if s is None:
        return None

    def ref(r: Optional[model.SchemaRef]) -> Any:
        if r is None:
            return None
        return [r.ref, list(r.ignore_when_actions)]

    return [ref(s.principal_schema), ref(s.resource_schema)]


def _dec_schemas(v: Any) -> Optional[model.Schemas]:
    if v is None:
        return None

    def ref(r: Any) -> Optional[model.SchemaRef]:
        if r is None:
            return None
        return model.SchemaRef(ref=r[0], ignore_when_actions=list(r[1]))

    return model.Schemas(principal_schema=ref(v[0]), resource_schema=ref(v[1]))


def encode_compiled(policies: list[CompiledPolicy]) -> bytes:
    enc = _Encoder()
    out: list[Any] = []
    for p in policies:
        if isinstance(p, CompiledResourcePolicy):
            out.append({
                "k": "R",
                "fqn": p.fqn,
                "res": p.resource,
                "raw": p.raw_resource,
                "ver": p.version,
                "sc": p.scope,
                "sp": p.scope_permissions,
                "par": enc.ref(p.params),
                "rules": [
                    [
                        list(r.actions), list(r.roles), list(r.derived_roles),
                        r.effect, r.name, enc.ref(r.condition), enc.ref(r.output),
                    ]
                    for r in p.rules
                ],
                "dr": [
                    [
                        name, sorted(dr.parent_roles), enc.ref(dr.condition),
                        enc.ref(dr.params), dr.origin_fqn,
                    ]
                    for name, dr in p.derived_roles.items()
                ],
                "schemas": _enc_schemas(p.schemas),
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        elif isinstance(p, CompiledPrincipalPolicy):
            out.append({
                "k": "P",
                "fqn": p.fqn,
                "pr": p.principal,
                "ver": p.version,
                "sc": p.scope,
                "sp": p.scope_permissions,
                "par": enc.ref(p.params),
                "rules": [
                    [r.resource, r.action, r.effect, r.name, enc.ref(r.condition), enc.ref(r.output)]
                    for r in p.rules
                ],
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        elif isinstance(p, CompiledRolePolicy):
            out.append({
                "k": "L",
                "fqn": p.fqn,
                "role": p.role,
                "ver": p.version,
                "sc": p.scope,
                "pp": list(p.parent_roles),
                "par": enc.ref(p.params),
                "rules": [
                    [r.resource, sorted(r.allow_actions), r.name, enc.ref(r.condition), enc.ref(r.output)]
                    for r in p.rules
                ],
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        else:
            raise CodecError(f"unknown policy type {type(p).__name__}")
    doc = {"v": CODEC_VERSION, "nodes": enc.nodes, "policies": out}
    return json.dumps(doc, separators=(",", ":"), default=_json_default).encode()


# -- decoder ------------------------------------------------------------------


class _Decoder:
    def __init__(self, nodes: list[Any]) -> None:
        self.raw = nodes
        self.cache: list[Any] = [None] * len(nodes)
        self.done: list[bool] = [False] * len(nodes)

    def ref(self, idx: Optional[int]) -> Any:
        if idx is None:
            return None
        if not isinstance(idx, int) or not (0 <= idx < len(self.raw)):
            raise CodecError(f"bad node ref {idx!r}")
        if self.done[idx]:
            return self.cache[idx]
        e = self.raw[idx]
        tag = e[0]
        if tag == "lit":
            obj: Any = A.Lit(_dec_value(e[1]))
        elif tag == "id":
            obj = A.Ident(e[1])
        elif tag == "sel":
            obj = A.Select(self.ref(e[1]), e[2])
        elif tag == "has":
            obj = A.Present(self.ref(e[1]), e[2])
        elif tag == "ix":
            obj = A.Index(self.ref(e[1]), self.ref(e[2]))
        elif tag == "call":
            obj = A.Call(e[1], tuple(self.ref(a) for a in e[2]),
                         self.ref(e[3]) if e[3] is not None else None)
        elif tag == "list":
            obj = A.ListLit(tuple(self.ref(a) for a in e[1]))
        elif tag == "map":
            obj = A.MapLit(tuple((self.ref(k), self.ref(v)) for k, v in e[1]))
        elif tag == "bind":
            obj = A.Bind(e[1], self.ref(e[2]), self.ref(e[3]))
        elif tag == "comp":
            obj = A.Comprehension(e[1], self.ref(e[2]), e[3], self.ref(e[4]),
                                  e[5], self.ref(e[6]) if e[6] is not None else None)
        elif tag == "E":
            obj = CompiledExpr(original=e[1], node=self.ref(e[2]))
        elif tag == "C":
            obj = CompiledCondition(kind=e[1], expr=self.ref(e[2]),
                                    children=tuple(self.ref(c) for c in e[3]))
        elif tag == "V":
            obj = CompiledVariable(name=e[1], expr=self.ref(e[2]))
        elif tag == "O":
            obj = CompiledOutput(rule_activated=self.ref(e[1]), condition_not_met=self.ref(e[2]))
        elif tag == "P":
            obj = PolicyParams(constants=_dec_value(e[1]),
                               ordered_variables=tuple(self.ref(v) for v in e[2]))
        else:
            raise CodecError(f"unknown node tag {tag!r}")
        self.cache[idx] = obj
        self.done[idx] = True
        return obj


def decode_compiled(blob: bytes) -> list[CompiledPolicy]:
    """Decode; ANY structural malformation raises CodecError (never an
    arbitrary exception) so untrusted bundles degrade to source recompile
    instead of crashing the loader."""
    try:
        return _decode_compiled(blob)
    except CodecError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, AttributeError, RecursionError) as e:
        raise CodecError(f"malformed bundle IR: {type(e).__name__}: {e}") from e


def _decode_compiled(blob: bytes) -> list[CompiledPolicy]:
    try:
        doc = json.loads(blob)
    except json.JSONDecodeError as e:
        raise CodecError(f"malformed bundle IR: {e}") from e
    if not isinstance(doc, dict) or doc.get("v") != CODEC_VERSION:
        raise CodecError(f"unsupported IR codec version {doc.get('v') if isinstance(doc, dict) else None!r}")
    dec = _Decoder(doc.get("nodes", []))
    out: list[CompiledPolicy] = []
    for p in doc.get("policies", []):
        kind = p.get("k")
        if kind == "R":
            out.append(CompiledResourcePolicy(
                fqn=p["fqn"],
                resource=p["res"],
                raw_resource=p["raw"],
                version=p["ver"],
                scope=p["sc"],
                scope_permissions=p["sp"],
                params=dec.ref(p["par"]),
                rules=[
                    CompiledResourceRule(
                        actions=tuple(r[0]), roles=tuple(r[1]), derived_roles=tuple(r[2]),
                        effect=r[3], name=r[4], condition=dec.ref(r[5]), output=dec.ref(r[6]),
                    )
                    for r in p["rules"]
                ],
                derived_roles={
                    d[0]: CompiledDerivedRole(
                        name=d[0], parent_roles=frozenset(d[1]), condition=dec.ref(d[2]),
                        params=dec.ref(d[3]), origin_fqn=d[4],
                    )
                    for d in p["dr"]
                },
                schemas=_dec_schemas(p.get("schemas")),
                source_attributes=_dec_value(p.get("src", {"$M": []})),
                annotations=dict(p.get("ann", {})),
            ))
        elif kind == "P":
            out.append(CompiledPrincipalPolicy(
                fqn=p["fqn"],
                principal=p["pr"],
                version=p["ver"],
                scope=p["sc"],
                scope_permissions=p["sp"],
                params=dec.ref(p["par"]),
                rules=[
                    CompiledPrincipalRule(
                        resource=r[0], action=r[1], effect=r[2], name=r[3],
                        condition=dec.ref(r[4]), output=dec.ref(r[5]),
                    )
                    for r in p["rules"]
                ],
                source_attributes=_dec_value(p.get("src", {"$M": []})),
                annotations=dict(p.get("ann", {})),
            ))
        elif kind == "L":
            out.append(CompiledRolePolicy(
                fqn=p["fqn"],
                role=p["role"],
                version=p["ver"],
                scope=p["sc"],
                parent_roles=tuple(p["pp"]),
                params=dec.ref(p["par"]),
                rules=[
                    CompiledRoleRule(
                        resource=r[0], allow_actions=frozenset(r[1]), name=r[2],
                        condition=dec.ref(r[3]), output=dec.ref(r[4]),
                    )
                    for r in p["rules"]
                ],
                source_attributes=_dec_value(p.get("src", {"$M": []})),
                annotations=dict(p.get("ann", {})),
            ))
        else:
            raise CodecError(f"unknown policy kind {kind!r}")
    return out
