"""Structured, versioned serialization of the compiled policy IR.

Replaces the pickle payload of v2 bundles: the encoding is pure data (JSON
with tagged nodes + a structural intern table), so decoding untrusted
bundles is safe — no code execution, only dataclass construction from a
closed vocabulary. This is the analogue of the reference's marshaled
rule-table proto (internal/ruletable/index/marshal.go:20,240), which is
likewise safe to load from anywhere.

Layout: ``{"v": 1, "nodes": [...], "policies": [...]}`` where ``nodes`` is
a flat table of unique encoded objects (CEL AST nodes, conditions, exprs,
variables, outputs, params) referenced by index. Structural sharing does
double duty: identical conditions across policies (the common case — policy
fleets repeat templates) encode once, and ``PolicyParams`` object identity
— which downstream caches key on (``params.cache_key``) — survives the
round trip because each table entry decodes to exactly one object.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional

from .cel import ast as A
from .compile.compiler import (
    CompiledCondition,
    CompiledDerivedRole,
    CompiledExpr,
    CompiledOutput,
    CompiledPolicy,
    CompiledPrincipalPolicy,
    CompiledPrincipalRule,
    CompiledResourcePolicy,
    CompiledResourceRule,
    CompiledRolePolicy,
    CompiledRoleRule,
    CompiledVariable,
    PolicyParams,
)
from .policy import model

CODEC_VERSION = 1


class CodecError(ValueError):
    pass


# -- values (Lit payloads, constants, source attributes) ----------------------


def _enc_value(v: Any) -> Any:
    """JSON-safe value encoding preserving the distinctions JSON collapses:
    bytes, non-string map keys, int-vs-float (JSON already keeps), tuples."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, bytes):
        return {"$B": base64.b64encode(v).decode()}
    if isinstance(v, (list, tuple)):
        return {"$L": [_enc_value(x) for x in v]}
    if isinstance(v, (set, frozenset)):
        return {"$S": [_enc_value(x) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        return {"$M": [[_enc_value(k), _enc_value(x)] for k, x in v.items()]}
    raise CodecError(f"unencodable value type {type(v).__name__}")


def _dec_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        if "$B" in v:
            return base64.b64decode(v["$B"])
        if "$L" in v:
            return [_dec_value(x) for x in v["$L"]]
        if "$S" in v:
            return frozenset(_dec_value(x) for x in v["$S"])
        if "$M" in v:
            return {_dec_value(k): _dec_value(x) for k, x in v["$M"]}
    raise CodecError(f"malformed value payload: {v!r}")


# -- intern-table encoder -----------------------------------------------------


class _Encoder:
    def __init__(self) -> None:
        self.nodes: list[Any] = []
        self._by_id: dict[int, int] = {}  # id(obj) -> index (identity fast path)
        self._by_key: dict[Any, int] = {}  # structural key -> index

    def _put(self, obj: Any, key: Any, encoded: Any) -> int:
        idx = len(self.nodes)
        self.nodes.append(encoded)
        self._by_id[id(obj)] = idx
        if key is not None:
            self._by_key[key] = idx
        return idx

    def ref(self, obj: Any) -> Optional[int]:
        if obj is None:
            return None
        hit = self._by_id.get(id(obj))
        if hit is not None:
            return hit
        if isinstance(obj, A.Node):
            return self._node(obj)
        if isinstance(obj, CompiledExpr):
            key = ("E", obj.original, self._node(obj.node))
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, ["E", obj.original, self._node(obj.node)])
        if isinstance(obj, CompiledCondition):
            enc = [
                "C",
                obj.kind,
                self.ref(obj.expr),
                [self.ref(c) for c in obj.children],
            ]
            key = ("C", json.dumps(enc[1:], sort_keys=False))
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, CompiledVariable):
            enc = ["V", obj.name, self.ref(obj.expr)]
            key = ("V", obj.name, enc[2])
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, CompiledOutput):
            enc = ["O", self.ref(obj.rule_activated), self.ref(obj.condition_not_met)]
            key = ("O", enc[1], enc[2])
            hit = self._by_key.get(key)
            if hit is not None:
                self._by_id[id(obj)] = hit
                return hit
            return self._put(obj, key, enc)
        if isinstance(obj, PolicyParams):
            # params are interned by IDENTITY only: the decoder must produce
            # one object per encoded entry so cache keys keyed on object
            # identity stay coherent, but two structurally equal params from
            # different policies remain distinct (as built)
            enc = [
                "P",
                _enc_value(obj.constants),
                [self.ref(v) for v in obj.ordered_variables],
            ]
            return self._put(obj, None, enc)
        raise CodecError(f"unencodable object {type(obj).__name__}")

    def _node(self, n: A.Node) -> int:
        hit = self._by_id.get(id(n))
        if hit is not None:
            return hit
        if isinstance(n, A.Lit):
            enc: list[Any] = ["lit", _enc_value(n.value)]
        elif isinstance(n, A.Ident):
            enc = ["id", n.name]
        elif isinstance(n, A.Select):
            enc = ["sel", self._node(n.operand), n.field]
        elif isinstance(n, A.Present):
            enc = ["has", self._node(n.operand), n.field]
        elif isinstance(n, A.Index):
            enc = ["ix", self._node(n.operand), self._node(n.index)]
        elif isinstance(n, A.Call):
            enc = [
                "call",
                n.fn,
                [self._node(a) for a in n.args],
                self._node(n.target) if n.target is not None else None,
            ]
        elif isinstance(n, A.ListLit):
            enc = ["list", [self._node(a) for a in n.items]]
        elif isinstance(n, A.MapLit):
            enc = ["map", [[self._node(k), self._node(v)] for k, v in n.entries]]
        elif isinstance(n, A.Bind):
            enc = ["bind", n.name, self._node(n.init), self._node(n.body)]
        elif isinstance(n, A.Comprehension):
            enc = [
                "comp",
                n.kind,
                self._node(n.iter_range),
                n.iter_var,
                self._node(n.step),
                n.iter_var2,
                self._node(n.step2) if n.step2 is not None else None,
            ]
        else:
            raise CodecError(f"unencodable AST node {type(n).__name__}")
        key = json.dumps(enc, sort_keys=False, default=_json_default)
        hit = self._by_key.get(key)
        if hit is not None:
            self._by_id[id(n)] = hit
            return hit
        return self._put(n, key, enc)


def _json_default(o: Any) -> Any:
    raise CodecError(f"unencodable literal {type(o).__name__}")


def _enc_schemas(s: Optional[model.Schemas]) -> Any:
    if s is None:
        return None

    def ref(r: Optional[model.SchemaRef]) -> Any:
        if r is None:
            return None
        return [r.ref, list(r.ignore_when_actions)]

    return [ref(s.principal_schema), ref(s.resource_schema)]


def _dec_schemas(v: Any) -> Optional[model.Schemas]:
    if v is None:
        return None

    def ref(r: Any) -> Optional[model.SchemaRef]:
        if r is None:
            return None
        return model.SchemaRef(ref=r[0], ignore_when_actions=list(r[1]))

    return model.Schemas(principal_schema=ref(v[0]), resource_schema=ref(v[1]))


def encode_compiled(policies: list[CompiledPolicy]) -> bytes:
    enc = _Encoder()
    out: list[Any] = []
    for p in policies:
        if isinstance(p, CompiledResourcePolicy):
            out.append({
                "k": "R",
                "fqn": p.fqn,
                "res": p.resource,
                "raw": p.raw_resource,
                "ver": p.version,
                "sc": p.scope,
                "sp": p.scope_permissions,
                "par": enc.ref(p.params),
                "rules": [
                    [
                        list(r.actions), list(r.roles), list(r.derived_roles),
                        r.effect, r.name, enc.ref(r.condition), enc.ref(r.output),
                    ]
                    for r in p.rules
                ],
                "dr": [
                    [
                        name, sorted(dr.parent_roles), enc.ref(dr.condition),
                        enc.ref(dr.params), dr.origin_fqn,
                    ]
                    for name, dr in p.derived_roles.items()
                ],
                "schemas": _enc_schemas(p.schemas),
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        elif isinstance(p, CompiledPrincipalPolicy):
            out.append({
                "k": "P",
                "fqn": p.fqn,
                "pr": p.principal,
                "ver": p.version,
                "sc": p.scope,
                "sp": p.scope_permissions,
                "par": enc.ref(p.params),
                "rules": [
                    [r.resource, r.action, r.effect, r.name, enc.ref(r.condition), enc.ref(r.output)]
                    for r in p.rules
                ],
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        elif isinstance(p, CompiledRolePolicy):
            out.append({
                "k": "L",
                "fqn": p.fqn,
                "role": p.role,
                "ver": p.version,
                "sc": p.scope,
                "pp": list(p.parent_roles),
                "par": enc.ref(p.params),
                "rules": [
                    [r.resource, sorted(r.allow_actions), r.name, enc.ref(r.condition), enc.ref(r.output)]
                    for r in p.rules
                ],
                "src": _enc_value(p.source_attributes),
                "ann": dict(p.annotations),
            })
        else:
            raise CodecError(f"unknown policy type {type(p).__name__}")
    doc = {"v": CODEC_VERSION, "nodes": enc.nodes, "policies": out}
    try:
        import msgpack

        # msgpack unpacks this shape ~3x faster than json and encodes
        # smaller; the payload stays a pure data tree (no code execution on
        # decode, same as the JSON form)
        return msgpack.packb(doc, use_bin_type=True, default=_json_default)
    except ImportError:
        pass
    except OverflowError:
        # msgpack ints are 64-bit; YAML integer literals are arbitrary
        # precision. The JSON container has no such limit, so fall back to
        # it rather than failing the build (decode sniffs the container).
        pass
    return json.dumps(doc, separators=(",", ":"), default=_json_default).encode()


# -- decoder ------------------------------------------------------------------


# tag → class for the native linear decoder (cerbos_native.decode_node_pool);
# must mirror the Python fallback's dispatch below
_NODE_CLASSES = {
    "lit": A.Lit, "id": A.Ident, "sel": A.Select, "has": A.Present,
    "ix": A.Index, "call": A.Call, "list": A.ListLit, "map": A.MapLit,
    "bind": A.Bind, "comp": A.Comprehension,
    "E": CompiledExpr, "C": CompiledCondition, "V": CompiledVariable,
    "O": CompiledOutput, "P": PolicyParams,
}


class _Decoder:
    """Single linear pass over the node pool.

    The encoder emits children strictly before parents (every child is
    encoded before ``_put`` assigns the parent's index), so decode is one
    forward loop with plain list indexing — no recursion, no per-child
    memo checks. A forward reference (child index >= parent index) is
    structurally impossible in encoder output and raises CodecError.

    Hot classes are built via ``object.__new__`` + direct ``__dict__``
    population: the frozen dataclasses' generated ``__init__`` goes through
    ``object.__setattr__`` per field, which measures ~3x slower across the
    ~10 objects/policy this loop constructs."""

    def __init__(self, nodes: list[Any]) -> None:
        self.raw = nodes
        from . import native as native_mod

        native = native_mod.get()
        if native is not None and hasattr(native, "decode_node_pool"):
            try:
                self.cache: list[Any] = native.decode_node_pool(
                    nodes, _NODE_CLASSES, _dec_value
                )
                return
            except ValueError as e:
                raise CodecError(f"malformed bundle IR: {e}") from e
        self.cache = [None] * len(nodes)
        self._decode_all()

    def _decode_all(self) -> None:
        cache = self.cache
        raw = self.raw
        new = object.__new__
        Lit, Ident, Select, Present, Idx = A.Lit, A.Ident, A.Select, A.Present, A.Index
        Call, ListLit, MapLit, Bind, Comp = A.Call, A.ListLit, A.MapLit, A.Bind, A.Comprehension

        def child(i: int, j: Any) -> Any:
            if j is None:
                return None
            if not isinstance(j, int) or not 0 <= j < i:
                raise CodecError(f"bad node ref {j!r} in node {i}")
            return cache[j]

        for i, e in enumerate(raw):
            tag = e[0]
            if tag == "sel":
                obj: Any = new(Select)
                obj.__dict__["operand"] = child(i, e[1])
                obj.__dict__["field"] = e[2]
            elif tag == "id":
                obj = new(Ident)
                obj.__dict__["name"] = e[1]
            elif tag == "lit":
                obj = new(Lit)
                obj.__dict__["value"] = _dec_value(e[1])
            elif tag == "call":
                obj = new(Call)
                d = obj.__dict__
                d["fn"] = e[1]
                d["args"] = tuple(child(i, a) for a in e[2])
                d["target"] = child(i, e[3])
            elif tag == "has":
                obj = new(Present)
                obj.__dict__["operand"] = child(i, e[1])
                obj.__dict__["field"] = e[2]
            elif tag == "ix":
                obj = new(Idx)
                obj.__dict__["operand"] = child(i, e[1])
                obj.__dict__["index"] = child(i, e[2])
            elif tag == "list":
                obj = new(ListLit)
                obj.__dict__["items"] = tuple(child(i, a) for a in e[1])
            elif tag == "map":
                obj = new(MapLit)
                obj.__dict__["entries"] = tuple((child(i, k), child(i, v)) for k, v in e[1])
            elif tag == "bind":
                obj = new(Bind)
                d = obj.__dict__
                d["name"] = e[1]
                d["init"] = child(i, e[2])
                d["body"] = child(i, e[3])
            elif tag == "comp":
                obj = new(Comp)
                d = obj.__dict__
                d["kind"] = e[1]
                d["iter_range"] = child(i, e[2])
                d["iter_var"] = e[3]
                d["step"] = child(i, e[4])
                d["iter_var2"] = e[5]
                d["step2"] = child(i, e[6])
            elif tag == "E":
                obj = new(CompiledExpr)
                obj.__dict__["original"] = e[1]
                obj.__dict__["node"] = child(i, e[2])
            elif tag == "C":
                obj = new(CompiledCondition)
                d = obj.__dict__
                d["kind"] = e[1]
                d["expr"] = child(i, e[2])
                d["children"] = tuple(child(i, c) for c in e[3])
            elif tag == "V":
                obj = new(CompiledVariable)
                obj.__dict__["name"] = e[1]
                obj.__dict__["expr"] = child(i, e[2])
            elif tag == "O":
                obj = new(CompiledOutput)
                obj.__dict__["rule_activated"] = child(i, e[1])
                obj.__dict__["condition_not_met"] = child(i, e[2])
            elif tag == "P":
                obj = new(PolicyParams)
                obj.__dict__["constants"] = _dec_value(e[1])
                obj.__dict__["ordered_variables"] = tuple(child(i, v) for v in e[2])
            else:
                raise CodecError(f"unknown node tag {tag!r}")
            cache[i] = obj

    def ref(self, idx: Optional[int]) -> Any:
        if idx is None:
            return None
        if not isinstance(idx, int) or not (0 <= idx < len(self.raw)):
            raise CodecError(f"bad node ref {idx!r}")
        return self.cache[idx]


def decode_compiled(blob: bytes) -> list[CompiledPolicy]:
    """Decode; ANY structural malformation raises CodecError (never an
    arbitrary exception) so untrusted bundles degrade to source recompile
    instead of crashing the loader."""
    try:
        return _decode_compiled(blob)
    except CodecError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, AttributeError, RecursionError) as e:
        raise CodecError(f"malformed bundle IR: {type(e).__name__}: {e}") from e


def _decode_compiled(blob: bytes) -> list[CompiledPolicy]:
    # container sniff: JSON docs start with '{'; anything else is msgpack
    # (old bundles stay readable either way)
    if blob[:1] == b"{":
        try:
            doc = json.loads(blob)
        except json.JSONDecodeError as e:
            raise CodecError(f"malformed bundle IR: {e}") from e
    else:
        try:
            import msgpack

            doc = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        except Exception as e:  # noqa: BLE001 — any unpack failure is a codec error
            raise CodecError(f"malformed bundle IR: {e}") from e
    if not isinstance(doc, dict) or doc.get("v") != CODEC_VERSION:
        raise CodecError(f"unsupported IR codec version {doc.get('v') if isinstance(doc, dict) else None!r}")
    dec = _Decoder(doc.get("nodes", []))
    out: list[CompiledPolicy] = []
    # positional construction + locally-bound names: the dataclass __init__s
    # run once per policy/rule and keyword parsing measures ~2x the cost of
    # positional at this volume
    cache = dec.cache
    n_nodes = len(cache)
    empty_src = {"$M": []}

    def ref(j):
        if j is None:
            return None
        if not isinstance(j, int) or not 0 <= j < n_nodes:
            raise CodecError(f"bad node ref {j!r}")
        return cache[j]

    RPol, RRule = CompiledResourcePolicy, CompiledResourceRule
    PPol, PRule = CompiledPrincipalPolicy, CompiledPrincipalRule
    LPol, LRule = CompiledRolePolicy, CompiledRoleRule
    DRole = CompiledDerivedRole
    dec_value, dec_schemas = _dec_value, _dec_schemas
    for p in doc.get("policies", []):
        kind = p.get("k")
        if kind == "R":
            out.append(RPol(
                p["fqn"], p["res"], p["raw"], p["ver"], p["sc"], p["sp"],
                ref(p["par"]),
                [
                    RRule(tuple(r[0]), tuple(r[1]), tuple(r[2]), r[3], r[4],
                          ref(r[5]), ref(r[6]))
                    for r in p["rules"]
                ],
                {
                    d[0]: DRole(d[0], frozenset(d[1]), ref(d[2]), ref(d[3]), d[4])
                    for d in p["dr"]
                },
                dec_schemas(p.get("schemas")),
                dec_value(p.get("src", empty_src)),
                dict(p.get("ann", {})),
            ))
        elif kind == "P":
            out.append(PPol(
                p["fqn"], p["pr"], p["ver"], p["sc"], p["sp"], ref(p["par"]),
                [
                    PRule(r[0], r[1], r[2], r[3], ref(r[4]), ref(r[5]))
                    for r in p["rules"]
                ],
                dec_value(p.get("src", empty_src)),
                dict(p.get("ann", {})),
            ))
        elif kind == "L":
            out.append(LPol(
                p["fqn"], p["role"], p["ver"], p["sc"], tuple(p["pp"]),
                ref(p["par"]),
                [
                    LRule(r[0], frozenset(r[1]), r[2], ref(r[3]), ref(r[4]))
                    for r in p["rules"]
                ],
                dec_value(p.get("src", empty_src)),
                dict(p.get("ann", {})),
            ))
        else:
            raise CodecError(f"unknown policy kind {kind!r}")
    return out
