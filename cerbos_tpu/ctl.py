"""cerbos-tpuctl: remote admin client.

Behavioral reference: cmd/cerbosctl — get/put/delete/enable/disable policies
and schemas, store reload, audit log browsing, all against a running PDP's
admin API.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import urllib.error
import urllib.parse
import urllib.request

import yaml


class Client:
    def __init__(self, server: str, username: str, password: str):
        self.base = server if server.startswith("http") else f"http://{server}"
        token = base64.b64encode(f"{username}:{password}".encode()).decode()
        self.headers = {"Authorization": f"Basic {token}", "Content-Type": "application/json"}

    def call(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict | None = None,
        timeout: float = 30,
    ):
        url = self.base + path
        if params:
            pairs = []
            for k, v in params.items():
                if isinstance(v, list):
                    pairs.extend((k, x) for x in v)
                else:
                    pairs.append((k, v))
            url += "?" + urllib.parse.urlencode(pairs)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, headers=self.headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise SystemExit(f"error: {e.code} {detail}") from None


class GrpcClient:
    """cerbos.svc.v1.CerbosAdminService transport (the reference cerbosctl's
    native protocol); exposes the same call(method, path) surface as the
    HTTP client so the command handlers stay transport-agnostic."""

    def __init__(self, server: str, username: str, password: str):
        import grpc

        from .api.cerbos.request.v1 import request_pb2
        from .api.cerbos.response.v1 import response_pb2

        self.req = request_pb2
        self.resp = response_pb2
        self.channel = grpc.insecure_channel(server)
        token = base64.b64encode(f"{username}:{password}".encode()).decode()
        self.metadata = (("authorization", f"Basic {token}"),)

    def _rpc(self, name: str, request, resp_cls, stream: bool = False):
        import grpc

        factory = self.channel.unary_stream if stream else self.channel.unary_unary
        fn = factory(
            f"/cerbos.svc.v1.CerbosAdminService/{name}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        try:
            return fn(request, metadata=self.metadata, timeout=30)
        except grpc.RpcError as e:
            raise SystemExit(f"error: {e.code().name} {e.details()}") from None

    def call(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict | None = None,
        timeout: float = 30,
    ):
        from google.protobuf import json_format

        from .api.cerbos.policy.v1 import policy_pb2
        from .api.cerbos.schema.v1 import schema_pb2

        params = params or {}
        if path == "/admin/policies":
            r = self._rpc(
                "ListPolicies",
                self.req.ListPoliciesRequest(include_disabled=params.get("includeDisabled") == "true"),
                self.resp.ListPoliciesResponse,
            )
            return {"policyIds": list(r.policy_ids)}
        if path == "/admin/policy" and method == "GET":
            r = self._rpc("GetPolicy", self.req.GetPolicyRequest(id=params.get("id", [])), self.resp.GetPolicyResponse)
            return {"policies": [json_format.MessageToDict(p) for p in r.policies]}
        if path == "/admin/policy" and method == "POST":
            req = self.req.AddOrUpdatePolicyRequest()
            for p in (body or {}).get("policies", []):
                req.policies.append(json_format.ParseDict(p, policy_pb2.Policy(), ignore_unknown_fields=True))
            self._rpc("AddOrUpdatePolicy", req, self.resp.AddOrUpdatePolicyResponse)
            return {"success": {}}
        if path == "/admin/policy" and method == "DELETE":
            raise SystemExit("error: the gRPC admin API has no DeletePolicy (match the reference); use disable")
        if path in ("/admin/policy/enable", "/admin/policy/disable"):
            enable = path.endswith("enable")
            name = "EnablePolicy" if enable else "DisablePolicy"
            req = (self.req.EnablePolicyRequest if enable else self.req.DisablePolicyRequest)(id=params.get("id", []))
            r = self._rpc(name, req, self.resp.EnablePolicyResponse if enable else self.resp.DisablePolicyResponse)
            return {"enabledPolicies": r.enabled_policies} if enable else {"disabledPolicies": r.disabled_policies}
        if path == "/admin/schemas":
            r = self._rpc("ListSchemas", self.req.ListSchemasRequest(), self.resp.ListSchemasResponse)
            return {"schemaIds": list(r.schema_ids)}
        if path == "/admin/schema" and method == "GET":
            r = self._rpc("GetSchema", self.req.GetSchemaRequest(id=params.get("id", [])), self.resp.GetSchemaResponse)
            return {"schemas": [{"id": s.id, "definition": json.loads(s.definition or b"{}")} for s in r.schemas]}
        if path == "/admin/schema" and method == "POST":
            req = self.req.AddOrUpdateSchemaRequest()
            for s in (body or {}).get("schemas", []):
                req.schemas.append(
                    schema_pb2.Schema(id=s["id"], definition=json.dumps(s["definition"]).encode())
                )
            self._rpc("AddOrUpdateSchema", req, self.resp.AddOrUpdateSchemaResponse)
            return {}
        if path == "/admin/schema" and method == "DELETE":
            r = self._rpc("DeleteSchema", self.req.DeleteSchemaRequest(id=params.get("id", [])), self.resp.DeleteSchemaResponse)
            return {"deletedSchemas": r.deleted_schemas}
        if path == "/admin/store/reload":
            if (params or {}).get("wait"):
                raise SystemExit(
                    "error: the gRPC admin API has no staged-reload report; "
                    "use the HTTP transport for store reload --wait"
                )
            self._rpc("ReloadStore", self.req.ReloadStoreRequest(), self.resp.ReloadStoreResponse)
            return {}
        if path == "/admin/store/rollback":
            raise SystemExit(
                "error: the gRPC admin API has no store rollback (match the "
                "reference); use the HTTP transport"
            )
        if path.startswith("/admin/auditlog/list/"):
            kind_name = path.rsplit("/", 1)[-1]
            kind = (
                self.req.ListAuditLogEntriesRequest.KIND_DECISION
                if kind_name == "decision_logs"
                else self.req.ListAuditLogEntriesRequest.KIND_ACCESS
            )
            req = self.req.ListAuditLogEntriesRequest(kind=kind, tail=int(params.get("tail", "20")))
            entries = []
            import grpc

            try:
                # stream errors surface on iteration, not on the call itself
                for msg in self._rpc("ListAuditLogEntries", req, self.resp.ListAuditLogEntriesResponse, stream=True):
                    field = msg.WhichOneof("entry")
                    if field:
                        entries.append(json_format.MessageToDict(getattr(msg, field)))
            except grpc.RpcError as e:
                raise SystemExit(f"error: {e.code().name} {e.details()}") from None
            return {"entries": entries}
        raise SystemExit(f"error: unsupported admin call {method} {path}")


def _render_decision(e: dict) -> str:
    ts = e.get("timestamp", "")[:19]
    cr = e.get("checkResources") or {}
    parts = []
    for out in cr.get("outputs", []) or []:
        pid = ""
        for inp in cr.get("inputs", []) or []:
            if inp.get("requestId") == out.get("requestId"):
                pid = (inp.get("principal") or {}).get("id", "")
        for action, res in (out.get("actions") or {}).items():
            effect = res.get("effect", "")
            mark = "ALLOW" if effect == "EFFECT_ALLOW" else "DENY "
            parts.append(f"{ts}  {mark}  {pid:<12} {action:<20} {res.get('policy', '')}")
    pr = e.get("planResources") or {}
    if pr:
        parts.append(f"{ts}  PLAN   {','.join(pr.get('actions', [])):<20} {pr.get('resourceKind', '')} -> {pr.get('kind', '')}")
    return "\n".join(parts) or f"{ts}  (empty decision entry)"


def _decisions_browser(client, tail: int, follow: bool, interval: float) -> int:
    """Streaming decision browser: renders ALLOW/DENY per action; with
    --follow keeps polling and prints only unseen call ids."""
    import time as _time

    seen: dict[str, None] = {}  # insertion-ordered set
    try:
        while True:
            resp = client.call("GET", "/admin/auditlog/list/decision_logs", params={"tail": str(tail)})
            for e in resp.get("entries", []):
                # entries without a callId dedup on content
                key = e.get("callId") or str(hash(json.dumps(e, sort_keys=True, default=str)))
                if key in seen:
                    continue
                if len(seen) > 65536:
                    # drop the oldest half; recent keys keep deduping
                    for old in list(seen)[:32768]:
                        del seen[old]
                seen[key] = None
                print(_render_decision(e))
            if not follow:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def _effects_only(rows: list[dict]) -> list[dict]:
    """Project effect rows down to resource_id + action→effect (the API
    response carries effects; policy/scope provenance needs the local oracle)."""
    return [
        {
            "resourceId": r.get("resourceId", ""),
            "actions": {a: {"effect": (e or {}).get("effect", "")} for a, e in (r.get("actions") or {}).items()},
        }
        for r in rows
    ]


def _replay_local(records, policies_path: str):
    """Replay corpus inputs on a freshly built local CPU oracle — the
    bit-exact reference, independent of any running server."""
    import glob
    import os

    from .compile import compile_policy_set
    from .engine import types as T
    from .engine.sentinel import effect_rows, input_from_json
    from .policy.parser import parse_policies
    from .ruletable import build_rule_table, check_input

    paths = []
    if os.path.isdir(policies_path):
        for pat in ("*.yaml", "*.yml"):
            paths.extend(sorted(glob.glob(os.path.join(policies_path, "**", pat), recursive=True)))
    else:
        paths = [policies_path]
    policies = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            policies.extend(parse_policies(f.read()))
    if not policies:
        raise SystemExit(f"error: no policies found at {policies_path}")
    rt = build_rule_table(compile_policy_set(policies))
    params = T.EvalParams()
    for _path, rec in records:
        inputs = [input_from_json(j) for j in rec.get("inputs", [])]
        yield rec, effect_rows([check_input(rt, i, params, None) for i in inputs])


def _replay_server(records, client):
    """Replay corpus inputs through a running PDP's /api/check/resources —
    one request per input (each corpus input carries its own principal)."""
    for _path, rec in records:
        rows = []
        for j in rec.get("inputs", []):
            body = {
                "requestId": j.get("requestId", ""),
                "principal": j.get("principal") or {},
                "resources": [{"resource": j.get("resource") or {}, "actions": j.get("actions") or []}],
            }
            resp = client.call("POST", "/api/check/resources", body=body)
            results = resp.get("results") or [{}]
            r = results[0]
            rows.append(
                {
                    "resourceId": (r.get("resource") or {}).get("id", ""),
                    "actions": {a: {"effect": eff} for a, eff in (r.get("actions") or {}).items()},
                }
            )
        yield rec, rows


def _replay_divergences(args, client) -> int:
    """Offline repro of captured parity divergences: re-evaluate each corpus
    record's raw inputs (local oracle with --policies, else through the
    server API) and report whether the recorded oracle effects reproduce and
    whether the recorded device effects still diverge."""
    from .engine.sentinel import DivergenceCorpus, compare_rows

    records = DivergenceCorpus.load(args.dir)
    if not records:
        print(f"no divergence records in {args.dir}")
        return 0
    if args.policies:
        replays = _replay_local(records, args.policies)
        exact = True
    else:
        replays = _replay_server(records, client)
        exact = False  # API replies carry effects, not policy/scope provenance
    total = reproduced = still_divergent = 0
    for rec, fresh in replays:
        total += 1
        recorded_oracle = rec.get("oracle_effects") or []
        recorded_device = rec.get("device_effects") or []
        if not exact:
            recorded_oracle = _effects_only(recorded_oracle)
            recorded_device = _effects_only(recorded_device)
            fresh = _effects_only(fresh)
        oracle_ok = not compare_rows(fresh, recorded_oracle)
        device_diff = compare_rows(fresh, recorded_device)
        reproduced += oracle_ok
        still_divergent += bool(device_diff)
        mark = "ok " if oracle_ok else "DRIFT"
        print(
            f"{mark} shard={rec.get('shard')} batch={rec.get('batch_id')} "
            f"inputs={len(rec.get('inputs', []))} "
            f"device_still_diverges={'yes' if device_diff else 'no'} "
            f"traces={','.join(rec.get('trace_ids') or []) or '-'}"
        )
        if getattr(args, "explain", False):
            _explain_record(rec)
    mode = "bit-exact (local oracle)" if exact else "effects-only (server API)"
    print(
        f"\nreplayed {total} divergence record(s) [{mode}]: "
        f"{reproduced} reproduce the recorded oracle effects, "
        f"{still_divergent} still diverge from the recorded device effects"
    )
    # drift between replay and the recorded oracle means the policies changed
    # since capture — the repro is stale, flag it to the operator
    return 0 if reproduced == total else 1


def _explain_record(rec: dict) -> None:
    """Winning-rule diff for one divergence record: which rule each side
    claims won, per action of every divergent row. Records captured before
    provenance landed carry no rule data — say so instead of guessing."""
    dev_p = rec.get("device_provenance") or []
    ora_p = rec.get("oracle_provenance") or []
    if not dev_p and not ora_p:
        print("      (record predates provenance capture — no winning-rule data)")
        return
    idxs = rec.get("divergent_indices") or list(range(max(len(dev_p), len(ora_p))))
    for i in idxs:
        d = dev_p[i] if i < len(dev_p) else {}
        o = ora_p[i] if i < len(ora_p) else {}
        rid = d.get("resourceId") or o.get("resourceId") or "?"
        for a in sorted(set(d.get("actions") or {}) | set(o.get("actions") or {})):
            da = (d.get("actions") or {}).get(a) or {}
            oa = (o.get("actions") or {}).get(a) or {}
            dr = da.get("matchedRule") or "-"
            orr = oa.get("matchedRule") or "-"
            mark = "==" if dr == orr else "!="
            src = da.get("source") or "?"
            print(f"      {rid}/{a}: device[{src}] {dr} {mark} oracle {orr}")


def _load_policies_arg(path: str) -> list:
    """Policy set from a YAML file, a policy directory, or a .crbp bundle."""
    import os

    if os.path.isfile(path) and path.endswith(".crbp"):
        from .bundle import BundleStore

        return BundleStore(path).get_all()
    if os.path.isdir(path):
        # the disk store skips testdata/, _schemas/ and *_test.yaml for us,
        # and stamps each policy with its source file for report provenance
        from .storage.disk import DiskStore

        policies = DiskStore(path).get_all()
    else:
        from .policy import model
        from .policy.parser import parse_policies

        with open(path, encoding="utf-8") as f:
            policies = list(parse_policies(f.read()))
        for p in policies:
            if p.metadata is None:
                p.metadata = model.Metadata()
            p.metadata.source_attributes.setdefault("source", os.path.basename(path))
    if not policies:
        raise SystemExit(f"error: no policies found at {path}")
    return policies


def _analyze_cmd(args) -> int:
    """Static policy analysis for CI gating: device-eligibility classes,
    divergence-risk lints, and policy-graph findings, offline (no server)."""
    from .compile import CompileError
    from .tpu.analyze import analyze_policies, render_text

    globals_ = json.loads(args.globals) if args.globals else {}
    try:
        report = analyze_policies(_load_policies_arg(args.path), globals_)
    except (CompileError, OSError) as e:
        for err in getattr(e, "errors", None) or [str(e)]:
            print(f"ERROR: {err}", file=sys.stderr)
        return 3
    if args.hot:
        return _hot_merge_cmd(report, args)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(render_text(report))
    if args.fail_on and report.failed(args.fail_on):
        print(f"\nanalysis failed --fail-on {args.fail_on}", file=sys.stderr)
        return 1
    return 0


def _hot_merge_cmd(report, args) -> int:
    """Merge a ``/_cerbos/debug/hotrules`` snapshot with the static
    analyzer's eligibility classes and rank oracle-extinction targets: the
    hottest live rules that do NOT lower to the device are the
    highest-leverage fixes (ROADMAP item 5's burn-down list)."""
    from .tpu.analyze import CLASS_DEVICE

    try:
        with open(args.hot, encoding="utf-8") as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR: cannot read hot-rule snapshot {args.hot}: {e}", file=sys.stderr)
        return 3
    by_row = {r.row_id: r for r in report.rules if r.row_id >= 0}
    by_fqn = {f"{r.policy}#{r.rule_name}": r for r in report.rules}
    merged = []
    unmatched = 0
    for entry in snap.get("top") or []:
        rep = by_row.get(entry.get("rule_row_id"))
        if rep is None and entry.get("rule"):
            rep = by_fqn.get(entry["rule"])
        if rep is None:
            # snapshot from a different bundle/epoch than the analyzed one
            unmatched += 1
        merged.append(
            {
                "rule": entry.get("rule") or (f"{rep.policy}#{rep.rule_name}" if rep else "?"),
                "hits": int(entry.get("hits") or 0),
                "share": float(entry.get("share") or 0.0),
                "class": rep.eligibility if rep else (entry.get("class") or "unknown"),
                "reason": rep.primary_reason() if rep else "",
            }
        )
    merged.sort(key=lambda m: m["hits"], reverse=True)
    targets = [m for m in merged if m["class"] != CLASS_DEVICE]
    if args.json:
        print(
            json.dumps(
                {
                    "snapshot": {k: snap.get(k) for k in ("decisions", "attribution_rate", "by_class", "by_source")},
                    "hot_rules": merged,
                    "extinction_targets": targets,
                    "unmatched_rows": unmatched,
                },
                indent=2,
            )
        )
        return 0
    print(
        f"hot-rule snapshot: {snap.get('decisions', 0)} decisions, "
        f"attribution rate {snap.get('attribution_rate', 0.0)}, "
        f"by_class {json.dumps(snap.get('by_class') or {})}"
    )
    print(f"\n{'hits':>10} {'share':>7} {'class':<16} rule")
    for m in merged:
        line = f"{m['hits']:>10} {m['share']:>7.2%} {m['class']:<16} {m['rule']}"
        if m["reason"]:
            line += f"  [{m['reason']}]"
        print(line)
    if unmatched:
        print(f"\nwarning: {unmatched} hot row(s) not in the analyzed bundle (stale snapshot?)")
    if targets:
        print(f"\noracle-extinction targets (hot, not device-eligible): {len(targets)}")
        for m in targets:
            print(f"  {m['share']:>7.2%} of attributed traffic  {m['rule']}  [{m['reason'] or m['class']}]")
    else:
        print("\nno extinction targets: every hot rule already lowers to the device")
    return 0


def _print_rollout_report(report: dict) -> None:
    """Render a rollout run report (``/admin/store/reload?wait=1`` payload)
    as a stage-by-stage verdict: ladder, gate findings with stable reason
    codes, replay diffs, canary result, terminal outcome."""
    head = f"rollout #{report.get('generation', '?')} [{report.get('trigger', '')}]"
    epochs = f"epoch {report.get('from_epoch')} -> {report.get('to_epoch')}"
    bundle = report.get("bundle_hash") or "?"
    print(f"{head}  {epochs}  bundle {bundle}")
    for st in report.get("stages", []):
        line = f"  {st.get('stage', '?'):<10} {st.get('status', '?'):<12} {st.get('seconds', 0.0):>8.3f}s"
        extra = {
            k: v
            for k, v in st.items()
            if k not in ("stage", "status", "seconds") and v not in (None, "", [], {})
        }
        if extra:
            line += "  " + " ".join(f"{k}={v}" for k, v in extra.items())
        print(line)
    gate = report.get("gate") or {}
    analysis = gate.get("analysis")
    if analysis:
        print(f"  gate analysis: {json.dumps(analysis)}")
    for f in gate.get("findings") or []:
        print(
            f"    finding [{f.get('severity', '?')}] {f.get('code', '?')} "
            f"{f.get('policy', '')}/{f.get('rule', '')}: {f.get('message', '')}"
        )
    replay = gate.get("replay")
    if replay:
        print(
            f"  gate replay: {replay.get('replayed', 0)} inputs, "
            f"{replay.get('diffs', 0)} effect diffs, {replay.get('errors', 0)} errors"
        )
        for s in replay.get("samples") or []:
            print(
                f"    diff {s.get('principal')} on {s.get('resource')}: "
                f"{s.get('old')} -> {s.get('new')}"
            )
    canary = report.get("canary") or {}
    if canary:
        print(f"  canary: {json.dumps(canary)}")
    outcome = report.get("outcome", "?")
    err = report.get("error")
    print(f"outcome: {outcome}" + (f" ({err})" if err else ""))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="cerbos-tpuctl", description="Admin client for cerbos-tpu PDPs")
    parser.add_argument("--server", default="127.0.0.1:3592")
    parser.add_argument(
        "--grpc", action="store_true",
        help="talk to the gRPC admin API (cerbos.svc.v1.CerbosAdminService) instead of HTTP",
    )
    parser.add_argument("--username", default="cerbos")
    parser.add_argument("--password", default="cerbosAdmin")
    sub = parser.add_subparsers(dest="command", required=True)

    p_get = sub.add_parser("get", help="list or fetch policies/schemas")
    p_get.add_argument("kind", choices=["policies", "policy", "schemas", "schema"])
    p_get.add_argument("ids", nargs="*")
    p_get.add_argument("--include-disabled", action="store_true")

    p_put = sub.add_parser("put", help="upload policies or schemas from files")
    p_put.add_argument("kind", choices=["policy", "schema"])
    p_put.add_argument("files", nargs="+")

    p_del = sub.add_parser("delete", help="delete policies or schemas")
    p_del.add_argument("kind", choices=["policy", "schema"])
    p_del.add_argument("ids", nargs="+")

    for name in ("enable", "disable"):
        p = sub.add_parser(name, help=f"{name} policies")
        p.add_argument("kind", choices=["policy"])
        p.add_argument("ids", nargs="+")

    p_store = sub.add_parser("store", help="store operations")
    p_store.add_argument("op", choices=["reload", "rollback"])
    p_store.add_argument(
        "--wait", action="store_true",
        help="block until the staged rollout finishes and print its stage-by-stage verdict",
    )
    p_store.add_argument(
        "--timeout", type=float, default=120.0,
        help="seconds to wait for the rollout report (with --wait)",
    )

    p_audit = sub.add_parser("audit", help="browse audit log entries")
    p_audit.add_argument("--kind", choices=["access", "decision"], default="decision")
    p_audit.add_argument("--tail", type=int, default=20)

    p_dec = sub.add_parser("decisions", help="interactive decision log browser (ref: cerbosctl decisions)")
    p_dec.add_argument("--tail", type=int, default=30)
    p_dec.add_argument("--follow", action="store_true", help="poll for new entries")
    p_dec.add_argument("--interval", type=float, default=2.0)

    p_replay = sub.add_parser(
        "replay-divergences",
        help="replay the parity sentinel's divergence corpus (offline repro of device/oracle mismatches)",
    )
    p_replay.add_argument(
        "--dir", required=True, help="divergence corpus directory (engine.tpu.paritySentinel.corpusDir)"
    )
    p_replay.add_argument(
        "--policies",
        default="",
        help="policy YAML file or directory: replay on a local CPU oracle (bit-exact) instead of the server API",
    )
    p_replay.add_argument(
        "--explain",
        action="store_true",
        help="per-record winning-rule diff: which rule the device vs the oracle claims won each action",
    )

    p_an = sub.add_parser(
        "analyze",
        help="static policy analysis: device-eligibility, divergence-risk, dead rules (offline)",
    )
    p_an.add_argument("path", help="policy YAML file, policy directory, or .crbp bundle")
    p_an.add_argument("--json", action="store_true", help="emit the full report as JSON")
    p_an.add_argument(
        "--fail-on",
        choices=["oracle-only", "divergence-risk"],
        default="",
        help="exit non-zero when the report contains the given class/finding kind",
    )
    p_an.add_argument(
        "--globals", default="", help="engine globals as JSON (mirrors engine.globals config)"
    )
    p_an.add_argument(
        "--hot",
        default="",
        metavar="FILE",
        help="a saved /_cerbos/debug/hotrules snapshot: merge live hit counts with the static "
        "classes and rank oracle-extinction targets",
    )

    args = parser.parse_args(argv)
    if args.command == "analyze":
        # pure-local static analysis; no server or credentials involved
        return _analyze_cmd(args)
    if args.command == "replay-divergences":
        # local-oracle replay needs no server at all; the API fallback uses
        # the plain HTTP client (check endpoint, not the admin surface)
        return _replay_divergences(
            args, Client(args.server, args.username, args.password) if not args.policies else None
        )
    if args.grpc:
        client: Client | GrpcClient = GrpcClient(args.server, args.username, args.password)
    else:
        client = Client(args.server, args.username, args.password)

    if args.command == "get":
        if args.kind == "policies" or (args.kind == "policy" and not args.ids):
            resp = client.call("GET", "/admin/policies", params={"includeDisabled": str(args.include_disabled).lower()})
            for pid in resp.get("policyIds", []):
                print(pid)
        elif args.kind == "policy":
            resp = client.call("GET", "/admin/policy", params={"id": args.ids})
            print(yaml.safe_dump_all(resp.get("policies", []), sort_keys=False))
        elif args.kind == "schemas" or (args.kind == "schema" and not args.ids):
            resp = client.call("GET", "/admin/schemas")
            for sid in resp.get("schemaIds", []):
                print(sid)
        else:
            resp = client.call("GET", "/admin/schema", params={"id": args.ids})
            print(json.dumps(resp.get("schemas", []), indent=2))
    elif args.command == "put":
        if args.kind == "policy":
            policies = []
            for path in args.files:
                with open(path, encoding="utf-8") as f:
                    policies.extend(d for d in yaml.safe_load_all(f) if d)
            resp = client.call("POST", "/admin/policy", body={"policies": policies})
            print(f"uploaded {len(policies)} policies")
        else:
            schemas = []
            for path in args.files:
                with open(path, encoding="utf-8") as f:
                    definition = json.load(f)
                sid = path.rsplit("/", 1)[-1]
                schemas.append({"id": sid, "definition": definition})
            client.call("POST", "/admin/schema", body={"schemas": schemas})
            print(f"uploaded {len(schemas)} schemas")
    elif args.command == "delete":
        if args.kind == "policy":
            resp = client.call("DELETE", "/admin/policy", params={"id": args.ids})
            print(f"deleted {resp.get('deletedPolicies', 0)}")
        else:
            resp = client.call("DELETE", "/admin/schema", params={"id": args.ids})
            print(f"deleted {resp.get('deletedSchemas', 0)}")
    elif args.command in ("enable", "disable"):
        resp = client.call("POST", f"/admin/policy/{args.command}", params={"id": args.ids})
        key = "enabledPolicies" if args.command == "enable" else "disabledPolicies"
        print(f"{args.command}d {resp.get(key, 0)}")
    elif args.command == "store":
        if args.op == "rollback":
            report = client.call("GET", "/admin/store/rollback")
            _print_rollout_report(report)
        elif args.wait:
            report = client.call(
                "GET",
                "/admin/store/reload",
                params={"wait": "1", "timeoutSec": str(args.timeout)},
                timeout=args.timeout + 10,
            )
            _print_rollout_report(report)
            if report.get("outcome") not in ("serving",):
                return 1
        else:
            client.call("GET", "/admin/store/reload")
            print("store reload triggered")
    elif args.command == "decisions":
        return _decisions_browser(client, tail=args.tail, follow=args.follow, interval=args.interval)
    elif args.command == "audit":
        kind = {"access": "access_logs", "decision": "decision_logs"}[args.kind]
        resp = client.call("GET", f"/admin/auditlog/list/{kind}", params={"tail": str(args.tail)})
        for entry in resp.get("entries", []):
            print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    sys.exit(main())
