"""JSON codec for the request hot path: native when available, stdlib otherwise.

The native module (native/src/cerbos_native.cpp) implements a strict JSON
parser and an ``ensure_ascii`` encoder matching stdlib semantics for the
wire surface CheckResources actually uses: objects, arrays, strings,
int/float numbers, booleans, null.  Anything the native encoder refuses
(non-str dict keys, custom objects) falls back to ``json.dumps`` so callers
never see a behavioral difference — only a speed one.

``loads`` accepts ``bytes``/``bytearray``/``memoryview``/``str`` and raises
``json.JSONDecodeError`` on malformed input regardless of which engine ran,
so existing ``except json.JSONDecodeError`` sites keep working unchanged.

``dumps`` returns **bytes** (UTF-8/ASCII), ready for an HTTP body without a
second encode pass.
"""

from __future__ import annotations

import json
from typing import Any

from . import native


def loads(data: Any) -> Any:
    """Parse JSON from bytes-like or str; raises json.JSONDecodeError."""
    nat = native.get()
    if nat is not None:
        buf = data.encode("utf-8", "surrogatepass") if isinstance(data, str) else data
        try:
            return nat.json_loads(buf)
        except ValueError as e:
            # normalize to the stdlib exception type callers already catch
            raise json.JSONDecodeError(str(e), _as_str(data), 0) from None
    if isinstance(data, (bytes, bytearray, memoryview)):
        data = bytes(data).decode("utf-8", "replace")
    return json.loads(data)


def dumps(obj: Any) -> bytes:
    """Encode to compact ensure_ascii JSON bytes (stdlib-compatible output)."""
    nat = native.get()
    if nat is not None:
        try:
            return nat.json_dumps(obj)
        except TypeError:
            pass  # e.g. int dict keys: stdlib coerces, native refuses
    return json.dumps(obj, separators=(", ", ": ")).encode("ascii")


def _as_str(data: Any) -> str:
    if isinstance(data, str):
        return data
    try:
        return bytes(data).decode("utf-8", "replace")
    except Exception:  # noqa: BLE001
        return ""
