"""Auxiliary data: JWT verification and claim extraction.

Behavioral reference: internal/auxdata/{auxdata,jwt}.go — configured key
sets (local PEM/JWKS files or inline data), token verification, claims
exposed to CEL as ``request.aux_data.jwt`` (jwt.go:40-242). Supports RS256/
RS384/RS512, ES256/ES384, and HS256/HS384/HS512; verification can be
disabled for development (matching the reference's
``verifyDisabled`` escape hatch).
"""

from __future__ import annotations

import base64
import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from .engine.types import AuxData

try:  # pragma: no cover - exercised implicitly by every test environment
    import cryptography  # noqa: F401

    _HAVE_CRYPTOGRAPHY = True
except Exception:  # noqa: BLE001
    # verification still works without the optional cryptography package:
    # cerbos_tpu.util.softcrypto provides pure-Python RSA/ECDSA/HMAC verify
    # and the PEM/JWK parsing the corpus needs (verify-only, no signing)
    _HAVE_CRYPTOGRAPHY = False


class JWTError(ValueError):
    pass


def _b64url(data: str) -> bytes:
    return base64.urlsafe_b64decode(data + "=" * (-len(data) % 4))


# JWA signature algorithms the reference accepts on keys (jwx jwa.SignatureAlgorithm)
_VALID_ALGS = {
    "RS256", "RS384", "RS512", "PS256", "PS384", "PS512",
    "ES256", "ES384", "ES512", "ES256K",
    "HS256", "HS384", "HS512", "EdDSA", "none",
}


@dataclass
class JWK:
    """One verification key with its JWK metadata (kid/alg lookup)."""

    key: Any  # cryptography public key or ("hmac", secret)
    kid: str = ""
    alg: str = ""


def _jwk_from_dict(k: dict) -> Any:
    kty = k.get("kty")
    if not _HAVE_CRYPTOGRAPHY:
        from .util import softcrypto

        try:
            return softcrypto.jwk_public_key(k, _b64url)
        except ValueError as e:
            raise JWTError(str(e)) from None
    if kty == "RSA":
        from cryptography.hazmat.primitives.asymmetric import rsa

        n = int.from_bytes(_b64url(k["n"]), "big")
        e = int.from_bytes(_b64url(k["e"]), "big")
        return rsa.RSAPublicNumbers(e, n).public_key()
    if kty == "EC":
        from cryptography.hazmat.primitives.asymmetric import ec

        curve = {"P-256": ec.SECP256R1(), "P-384": ec.SECP384R1(), "P-521": ec.SECP521R1()}[k["crv"]]
        x = int.from_bytes(_b64url(k["x"]), "big")
        y = int.from_bytes(_b64url(k["y"]), "big")
        return ec.EllipticCurvePublicNumbers(x, y, curve).public_key()
    if kty == "oct":
        return ("hmac", _b64url(k["k"]))
    raise JWTError(f"unsupported key type {kty!r}")


def parse_key_material(raw: bytes, pem: bool = False) -> list[JWK]:
    """Key material → verification keys, with the reference's validation:
    every JWK needs a non-empty kid and a known alg (jwt.go keyset loading;
    auxdata corpus error text)."""
    if pem:
        keys: list[JWK] = []
        text = raw.decode("utf-8", errors="ignore")
        blocks = ["-----BEGIN" + b for b in text.split("-----BEGIN")[1:]]
        if not blocks:
            raise JWTError("failed to parse PEM key material")
        if not _HAVE_CRYPTOGRAPHY:
            from .util import softcrypto

            for block in blocks:
                try:
                    keys.append(JWK(key=softcrypto.parse_pem_block(block)))
                except ValueError as e:
                    raise JWTError(f"failed to parse PEM block: {e}") from None
            return keys
        from cryptography.hazmat.primitives import serialization

        for block in blocks:
            data = block.encode()
            try:
                keys.append(JWK(key=serialization.load_pem_public_key(data)))
            except Exception:  # noqa: BLE001 — maybe a private key or cert
                try:
                    priv = serialization.load_pem_private_key(data, password=None)
                    keys.append(JWK(key=priv.public_key()))
                except Exception as e:  # noqa: BLE001
                    raise JWTError(f"failed to parse PEM block: {e}") from None
        return keys

    try:
        data = json.loads(raw)
    except Exception as e:  # noqa: BLE001
        raise JWTError(f"failed to parse key material: {e}") from None
    entries = data.get("keys") if isinstance(data, dict) and "keys" in data else [data]
    if not isinstance(entries, list) or not all(isinstance(k, dict) for k in entries):
        raise JWTError("failed to parse key material: not a JWK or JWKS document")
    keys = []
    for i, k in enumerate(entries):
        alg = k.get("alg")
        if alg is not None and alg not in _VALID_ALGS:
            raise JWTError(f"failed to parse key at idx {i}: invalid algorithm (alg) {alg!r}")
        if "kid" not in k:
            raise JWTError(f"failed to validate key at idx {i}: missing key ID (kid)")
        if k.get("kid") == "":
            raise JWTError(f"failed to validate key at idx {i}: empty key ID (kid)")
        if alg is None:
            raise JWTError(f"failed to validate key at idx {i}: missing algorithm (alg)")
        keys.append(JWK(key=_jwk_from_dict(k), kid=k["kid"], alg=alg))
    return keys


class RemoteJWKS:
    """JWKS fetched over HTTP(S) with time-based refresh and keep-cached-on-
    failure (ref: jwt.go:40-242 — jwk.Cache with RefreshInterval; a fetch
    error keeps serving the last good keyset).

    Forced refreshes (signature miss → maybe the signer rotated) are rate-
    limited by ``min_refresh_interval_s``, so a flood of garbage-signature
    tokens cannot hammer the JWKS endpoint (jwk.Cache's refresh-on-miss
    throttle). The HTTP fetch happens OUTSIDE the key lock — concurrent
    verifications keep using the cached keys while one thread refreshes."""

    def __init__(
        self,
        url: str,
        refresh_interval_s: float = 3600.0,
        timeout_s: float = 10.0,
        min_refresh_interval_s: float = 15.0,
    ):
        import threading

        self.url = url
        self.refresh_interval = refresh_interval_s
        self.min_refresh_interval = min_refresh_interval_s
        self.timeout = timeout_s
        self._keys: list[Any] = []
        self._fetched_at = 0.0
        self._attempted_at = 0.0
        self._lock = threading.Lock()
        self._fetching = False
        self.stats = {"fetches": 0, "failures": 0, "throttled": 0}

    def keys(self, force: bool = False) -> list[Any]:
        now = time.time()
        with self._lock:
            stale = now - self._fetched_at >= self.refresh_interval
            throttled = now - self._attempted_at < self.min_refresh_interval
            need = (not self._keys) or stale or force
            if not need or (throttled and self._keys):
                if need and throttled:
                    self.stats["throttled"] += 1
                return list(self._keys)
            if self._fetching:
                # another thread is refreshing: serve what we have (or fail
                # if nothing cached yet)
                if self._keys:
                    return list(self._keys)
            self._fetching = True
            self._attempted_at = now
        try:
            fetched = self._fetch()
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._fetching = False
                self.stats["failures"] += 1
                if not self._keys:
                    raise JWTError(f"remote JWKS fetch failed and no cached keys: {e}") from e
                return list(self._keys)  # keep serving cached
        with self._lock:
            self._fetching = False
            self._keys = fetched
            self._fetched_at = time.time()
            self.stats["fetches"] += 1
            return list(self._keys)

    def _fetch(self) -> list[Any]:
        import urllib.request

        try:
            with urllib.request.urlopen(self.url, timeout=self.timeout) as resp:
                raw = resp.read()
            return parse_key_material(raw)
        except Exception as e:  # noqa: BLE001
            raise JWTError(f"failed to look up remote keyset: {e}") from None


@dataclass
class KeySet:
    id: str
    keys: list[Any] = field(default_factory=list)  # public key objects or (b"secret", alg)
    insecure_no_verification: bool = False
    remote: Optional[RemoteJWKS] = None

    def current_keys(self, force_refresh: bool = False) -> list[Any]:
        if self.remote is not None:
            return self.remote.keys(force=force_refresh)
        return self.keys


def load_keyset(conf: dict) -> KeySet:
    """Config shape mirrors the reference auxdata.jwt.keySets entries."""
    ks = KeySet(id=conf.get("id", ""))
    if conf.get("insecure", {}).get("disableVerification") or conf.get("disableVerification"):
        ks.insecure_no_verification = True
        return ks
    remote = conf.get("remote", {})
    if remote.get("url"):
        ks.remote = RemoteJWKS(
            url=remote["url"],
            refresh_interval_s=float(remote.get("refreshInterval", 3600.0)),
            min_refresh_interval_s=float(remote.get("minRefreshInterval", 15.0)),
        )
        return ks
    local = conf.get("local", {})
    raw: Optional[bytes] = None
    if local.get("file"):
        with open(local["file"], "rb") as f:
            raw = f.read()
    elif local.get("data"):
        raw = base64.b64decode(local["data"])
    if raw is None:
        raise JWTError(f"keyset {ks.id!r} has neither local key material nor a remote JWKS url")
    text = raw.decode("utf-8", errors="ignore").strip()
    if text.startswith("{"):
        ks.keys = parse_key_material(raw)
    elif "BEGIN" in text:
        ks.keys = parse_key_material(raw, pem=True)
    elif str(conf.get("algorithm", "")).startswith("HS"):
        # raw bytes are a symmetric secret only when the keyset explicitly
        # opts into an HS* algorithm; otherwise a corrupted public-key file
        # must fail load, not silently downgrade to HMAC
        ks.keys = [("hmac", raw)]
    else:
        raise JWTError(
            f"keyset {ks.id!r}: key material is neither JWKS nor PEM; "
            "set algorithm: HS256/HS384/HS512 to use it as an HMAC secret"
        )
    return ks


def _verify_signature(alg: str, key: Any, signing_input: bytes, sig: bytes) -> bool:
    if isinstance(key, JWK):
        key = key.key
    if not _HAVE_CRYPTOGRAPHY:
        from .util import softcrypto

        return softcrypto.verify(alg, key, signing_input, sig)
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, hmac as chmac
    from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa, utils as asym_utils

    hash_alg = {"256": hashes.SHA256(), "384": hashes.SHA384(), "512": hashes.SHA512()}[alg[2:]]
    try:
        if alg.startswith("HS"):
            if not (isinstance(key, tuple) and key[0] == "hmac"):
                return False
            h = chmac.HMAC(key[1], hash_alg)
            h.update(signing_input)
            h.verify(sig)
            return True
        if alg.startswith("RS"):
            if not isinstance(key, rsa.RSAPublicKey):
                return False
            key.verify(sig, signing_input, padding.PKCS1v15(), hash_alg)
            return True
        if alg.startswith("ES"):
            if not isinstance(key, ec.EllipticCurvePublicKey):
                return False
            # JOSE raw (r || s) → DER
            half = len(sig) // 2
            r = int.from_bytes(sig[:half], "big")
            s = int.from_bytes(sig[half:], "big")
            der = asym_utils.encode_dss_signature(r, s)
            key.verify(der, signing_input, ec.ECDSA(hash_alg))
            return True
    except InvalidSignature:
        return False
    except Exception:  # noqa: BLE001
        return False
    return False


class AuxDataManager:
    def __init__(self, keysets: list[KeySet], default_keyset_id: str = ""):
        self.keysets = {ks.id: ks for ks in keysets}
        self.default_keyset_id = default_keyset_id or (keysets[0].id if len(keysets) == 1 else "")

    @classmethod
    def from_config(cls, conf: dict) -> "AuxDataManager":
        jwt_conf = conf.get("jwt", {})
        keysets = [load_keyset(k) for k in jwt_conf.get("keySets", [])]
        return cls(keysets)

    def extract(self, token: str, key_set_id: str = "") -> AuxData:
        """Verify + decode; claims land under request.aux_data.jwt."""
        parts = token.split(".")
        if len(parts) != 3:
            raise JWTError("malformed JWT")
        try:
            header = json.loads(_b64url(parts[0]))
            payload = json.loads(_b64url(parts[1]))
            sig = _b64url(parts[2])
        except Exception as e:  # noqa: BLE001
            raise JWTError(f"malformed JWT: {e}") from None

        ks_id = key_set_id or self.default_keyset_id
        ks = self.keysets.get(ks_id)
        if ks is None:
            raise JWTError(f"unknown keyset {ks_id!r}")

        if not ks.insecure_no_verification:
            alg = header.get("alg", "")
            if alg not in ("RS256", "RS384", "RS512", "ES256", "ES384", "HS256", "HS384", "HS512"):
                raise JWTError(f"unsupported JWT algorithm {alg!r}")
            signing_input = f"{parts[0]}.{parts[1]}".encode("ascii")
            kid = header.get("kid", "")

            def candidates(keys):
                # jwx WithKeySet parity: a key with a kid only matches the
                # token's kid (when the token carries one), and a key with a
                # declared alg only verifies tokens of that alg
                out = []
                for key in keys:
                    if isinstance(key, JWK):
                        if kid and key.kid and key.kid != kid:
                            continue
                        if key.alg and key.alg != alg:
                            continue
                    out.append(key)
                return out

            verified = any(
                _verify_signature(alg, key, signing_input, sig)
                for key in candidates(ks.current_keys())
            )
            if not verified and ks.remote is not None:
                # the signer may have rotated since the last fetch: refresh
                # once and retry (jwk.Cache's refresh-on-miss behavior)
                verified = any(
                    _verify_signature(alg, key, signing_input, sig)
                    for key in candidates(ks.current_keys(force_refresh=True))
                )
            if not verified:
                raise JWTError("JWT signature verification failed")
            now = time.time()
            if "exp" in payload and now > float(payload["exp"]):
                raise JWTError("JWT has expired")
            if "nbf" in payload and now < float(payload["nbf"]):
                raise JWTError("JWT not yet valid")

        return AuxData(jwt=payload)
