"""Policy-evaluation tracer: the domain-level trace tree.

Behavioral reference: internal/engine/tracer/{context,sink}.go — a tree of
policy → action → scope → rule → condition events with results, sent to
pluggable sinks; surfaced in playground/verify --verbose. This
implementation wraps the CPU oracle: a TraceRecorder collects events during
a check and renders them as the wire-format trace list."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from .engine import types as T
from .ruletable import check as rt_check
from .ruletable.table import RuleTable


@dataclass
class TraceEvent:
    components: list[dict]  # [{kind: "policy"|"action"|"scope"|"rule"|..., id: str}]
    activated: Optional[bool] = None
    effect: Optional[str] = None
    message: str = ""
    result: Any = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {"components": self.components}
        event: dict[str, Any] = {}
        if self.activated is not None:
            event["status"] = "ACTIVATED" if self.activated else "SKIPPED"
        if self.effect:
            event["effect"] = self.effect
        if self.message:
            event["message"] = self.message
        if self.result is not None:
            event["result"] = self.result
        if event:
            out["event"] = event
        return out


class TraceRecorder:
    """Collects trace events; handed to check via EvalParams-adjacent hook."""

    def __init__(self) -> None:
        self.events: list[TraceEvent] = []

    def add(self, components: list[dict], **kwargs: Any) -> None:
        self.events.append(TraceEvent(components=components, **kwargs))

    def to_json(self) -> list[dict]:
        return [e.to_json() for e in self.events]


def traced_check(
    rt: RuleTable,
    input: T.CheckInput,
    params: Optional[T.EvalParams] = None,
    schema_mgr: Any = None,
) -> tuple[T.CheckOutput, TraceRecorder]:
    """Run the oracle check while recording a per-action trace.

    The trace is reconstructed from the same data the oracle uses: for each
    action we re-query candidate bindings and record rule activations.
    """
    params = params or T.EvalParams()
    recorder = TraceRecorder()
    output = rt_check.check_input(rt, input, params, schema_mgr)

    principal_scope = T.effective_scope(input.principal.scope, params)
    resource_scope = T.effective_scope(input.resource.scope, params)
    resource_version = T.effective_version(input.resource.policy_version, params)
    from . import namer

    _, _, resource_fqn = rt.get_all_scopes(
        "RESOURCE", resource_scope, input.resource.kind, resource_version, params.lenient_scope_search
    )
    r_scopes, _, _ = rt.get_all_scopes(
        "RESOURCE", resource_scope, input.resource.kind, resource_version, params.lenient_scope_search
    )

    request, principal, resource = rt_check.build_request_messages(input)
    ec = rt_check.EvalContext(params, request, principal, resource)

    for action in input.actions:
        ae = output.actions.get(action)
        base = [{"kind": "action", "id": action}]
        parent_roles = rt.idx.add_parent_roles([resource_scope], list(input.principal.roles))
        for scope in r_scopes:
            rows = rt.idx.query(
                resource_version, namer.sanitize(input.resource.kind), scope, action,
                parent_roles, "RESOURCE", "",
            )
            for b in rows:
                comps = base + [
                    {"kind": "policy", "id": namer.policy_key_from_fqn(b.origin_fqn)},
                    {"kind": "scope", "id": scope},
                    {"kind": "rule", "id": b.name or "rule"},
                ]
                constants = b.params.constants if b.params else {}
                variables = ec.evaluate_variables(constants, b.params.ordered_variables) if b.params else {}
                try:
                    sat = ec.satisfies_condition(b.condition, constants, variables)
                    if b.derived_role_condition is not None:
                        dr_consts = b.derived_role_params.constants if b.derived_role_params else {}
                        dr_vars = (
                            ec.evaluate_variables(dr_consts, b.derived_role_params.ordered_variables)
                            if b.derived_role_params
                            else {}
                        )
                        sat = sat and ec.satisfies_condition(b.derived_role_condition, dr_consts, dr_vars)
                except Exception:  # noqa: BLE001
                    sat = False
                recorder.add(
                    comps,
                    activated=sat,
                    effect=b.effect if sat and b.effect != "EFFECT_UNSPECIFIED" else None,
                    message="" if sat else "Condition not satisfied",
                )
        if ae is not None:
            recorder.add(base, effect=ae.effect, message=f"Resolved by {ae.policy}")
    return output, recorder
