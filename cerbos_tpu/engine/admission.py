"""Front-door admission control: compiled priority classes, refusing early.

At overload, the cheapest request is the one never admitted: today's only
pressure valves (deadline expiry, the IPC ring filling) fire *after* the
queue time is already spent. This module gates every request at ingress —
before it touches the batcher, the ticket ring, or a device batch — so a
refusal costs one dict lookup and a token-bucket update, never device work.

Load-shedding is expressed as policy, like everything else this PDP
evaluates: a small declarative ``overload:`` config block declares priority
classes that match on principal id / roles / resource kind / API using the
same glob machinery the rule table compiles (``cerbos_tpu.globs``, gobwas
semantics), compiled once at bootstrap. Each class carries:

- ``priority``      — lower is more important; drives the batcher's
                      weighted priority lanes (interactive preempts bulk);
- ``rate``/``burst`` — token-bucket admission (requests/sec, bucket depth);
- ``maxConcurrent`` — in-flight cap at the front door;
- ``weight``        — fair share among classes of equal priority in the
                      batcher lanes;
- ``queueBudget``   — max tickets queued in this class's batcher lane
                      (enforced batcher-side, surfaced as a refusal here);
- ``sheddable``     — the brownout ladder's ``shed_low_priority`` stage
                      refuses this class outright (default: priority > 0).

Refusals map to HTTP 429 + ``Retry-After`` / gRPC ``RESOURCE_EXHAUSTED``
and are counted as ``decisions_total{outcome=refused}`` in the refusing
worker process, so goodput math is topology-independent. One process-global
controller (the flight-recorder pattern): bootstrap compiles the config,
both servers consult it.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence

from ..globs import matches_glob
from ..observability import metrics

# admission outcomes (the `outcome` label on cerbos_tpu_admission_total)
ADMITTED = "admitted"
REFUSED_RATE = "refused_rate"
REFUSED_CONCURRENCY = "refused_concurrency"
REFUSED_BROWNOUT = "refused_brownout"


class OverloadRefused(Exception):
    """The request was refused by admission control (or a batcher lane's
    queue budget). Maps to HTTP 429 + ``Retry-After`` / gRPC
    RESOURCE_EXHAUSTED at the server layer — never a 5xx."""

    def __init__(self, pclass: str, reason: str, retry_after: float = 1.0):
        super().__init__(f"overloaded: {reason} (class {pclass or 'default'!r})")
        self.pclass = pclass
        self.reason = reason  # rate | concurrency | brownout | queue_budget
        self.retry_after = max(0.0, float(retry_after))


def _match_any(patterns: Sequence[str], values: Iterable[str]) -> bool:
    for v in values:
        for pat in patterns:
            if matches_glob(pat, v):
                return True
    return False


class PriorityClass:
    """One compiled class from the ``overload.classes`` list. Matching is
    first-match-wins in declaration order; within a class, every NON-empty
    match dimension must hit (an empty dimension is a wildcard)."""

    __slots__ = (
        "name",
        "priority",
        "weight",
        "rate",
        "burst",
        "max_concurrent",
        "queue_budget",
        "sheddable",
        "m_principals",
        "m_roles",
        "m_kinds",
        "m_apis",
    )

    def __init__(
        self,
        name: str,
        priority: int = 0,
        weight: int = 1,
        rate: float = 0.0,
        burst: float = 0.0,
        max_concurrent: int = 0,
        queue_budget: int = 0,
        sheddable: Optional[bool] = None,
        principals: Sequence[str] = (),
        roles: Sequence[str] = (),
        kinds: Sequence[str] = (),
        apis: Sequence[str] = (),
    ):
        self.name = str(name)
        self.priority = int(priority)
        self.weight = max(1, int(weight))
        self.rate = max(0.0, float(rate))          # 0 = unlimited
        self.burst = float(burst) if burst else max(self.rate, 1.0)
        self.max_concurrent = max(0, int(max_concurrent))  # 0 = unlimited
        self.queue_budget = max(0, int(queue_budget))      # 0 = unlimited
        # brownout's shed_low_priority stage refuses sheddable classes;
        # priority-0 classes are protected by default
        self.sheddable = bool(sheddable) if sheddable is not None else self.priority > 0
        self.m_principals = tuple(str(p) for p in principals)
        self.m_roles = tuple(str(r) for r in roles)
        self.m_kinds = tuple(str(k) for k in kinds)
        self.m_apis = tuple(str(a) for a in apis)
        # pre-compile every glob once (matches_glob caches by pattern, so
        # the per-request path never pays the parse)
        from ..globs import compile_glob

        for pat in (*self.m_principals, *self.m_roles, *self.m_kinds, *self.m_apis):
            compile_glob(pat)

    @classmethod
    def from_conf(cls, conf: dict) -> "PriorityClass":
        match = conf.get("match") or {}
        return cls(
            name=conf.get("name", ""),
            priority=conf.get("priority", 0),
            weight=conf.get("weight", 1),
            rate=conf.get("rate", 0.0),
            burst=conf.get("burst", 0.0),
            max_concurrent=conf.get("maxConcurrent", 0),
            queue_budget=conf.get("queueBudget", 0),
            sheddable=conf.get("sheddable"),
            principals=match.get("principals") or (),
            roles=match.get("roles") or (),
            kinds=match.get("kinds") or (),
            apis=match.get("apis") or (),
        )

    def matches(
        self,
        principal_id: str,
        roles: Sequence[str],
        kinds: Sequence[str],
        api: str,
    ) -> bool:
        if self.m_principals and not _match_any(self.m_principals, (principal_id,)):
            return False
        if self.m_roles and not _match_any(self.m_roles, roles or ()):
            return False
        if self.m_kinds and not _match_any(self.m_kinds, kinds or ()):
            return False
        if self.m_apis and not _match_any(self.m_apis, (api,)):
            return False
        return True

    def lane_conf(self) -> tuple[str, int, int, int]:
        """(name, priority, weight, queue_budget) for the batcher lanes."""
        return (self.name, self.priority, self.weight, self.queue_budget)


class _ClassState:
    """Runtime admission state for one class: token bucket + inflight."""

    __slots__ = ("tokens", "last", "inflight", "g_inflight")

    def __init__(self, burst: float, gauge_child: Any):
        self.tokens = burst
        self.last: Optional[float] = None
        self.inflight = 0
        self.g_inflight = gauge_child


class AdmissionTicket:
    """Release handle for an admitted request; released in the server
    handler's ``finally`` so concurrency caps can never leak."""

    __slots__ = ("_ctrl", "_cls", "_done")

    def __init__(self, ctrl: "AdmissionController", cls: PriorityClass):
        self._ctrl = ctrl
        self._cls = cls
        self._done = False

    @property
    def pclass(self) -> PriorityClass:
        return self._cls

    def release(self) -> None:
        if self._done:
            return
        self._done = True
        self._ctrl._release(self._cls)


# a permanently-released ticket for the disabled/no-classes fast path: the
# server's `finally: ticket.release()` stays unconditional
class _NullTicket(AdmissionTicket):
    __slots__ = ()

    def __init__(self, cls: PriorityClass):
        self._ctrl = None  # type: ignore[assignment]
        self._cls = cls
        self._done = True


class AdmissionController:
    """Compiled front-door admission: classify, then token-bucket +
    concurrency-cap per class, O(1) under one lock. ``clock`` is injectable
    for tests."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        reg = metrics()
        self.m_total = reg.counter_vec(
            "cerbos_tpu_admission_total",
            "front-door admission decisions by priority class and outcome "
            "(admitted / refused_rate / refused_concurrency / refused_brownout)",
            label=("pclass", "outcome"),
        )
        self.m_inflight = reg.gauge_vec(
            "cerbos_tpu_admission_inflight",
            "admitted requests currently in flight, by priority class",
            label="pclass",
        )
        self.m_refusal_seconds = reg.histogram(
            "cerbos_tpu_admission_refusal_seconds",
            "ingress-to-refusal latency of refused requests (refusing early "
            "must stay cheap: the acceptance bar is p99 < 5 ms)",
            buckets=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.25],
        )
        self._clock = clock
        self._lock = threading.Lock()
        self.enabled = False
        self.classes: list[PriorityClass] = []
        self.default = PriorityClass("default", priority=1)
        self._state: dict[str, _ClassState] = {}
        self._null = _NullTicket(self.default)
        # brownout's shed_low_priority stage flips this; sheddable classes
        # are refused outright while set
        self._shed_low_priority = False

    # -- configuration (bootstrap, once) ------------------------------------

    def configure(self, conf: Optional[dict]) -> None:
        """Compile the ``overload:`` block. Safe to call again on reload."""
        conf = conf or {}
        classes = [PriorityClass.from_conf(c) for c in conf.get("classes") or []]
        classes = [c for c in classes if c.name]
        default_conf = conf.get("default") or {}
        default = PriorityClass.from_conf({"name": "default", "priority": 1, **default_conf})
        with self._lock:
            self.enabled = bool(conf.get("enabled", True)) and bool(
                classes
                or default.rate
                or default.max_concurrent
            )
            self.classes = classes
            self.default = default
            self._null = _NullTicket(default)
            self._state = {
                c.name: _ClassState(c.burst, self.m_inflight.labels(c.name))
                for c in (*classes, default)
            }
            self._shed_low_priority = False

    def lane_confs(self) -> list[tuple[str, int, int, int]]:
        """Lane configs for ``BatchingEvaluator.configure_lanes`` (every
        declared class plus the default catch-all lane)."""
        with self._lock:
            return [c.lane_conf() for c in (*self.classes, self.default)]

    def set_shed(self, flag: bool) -> None:
        """Brownout applier for the ``shed_low_priority`` stage."""
        self._shed_low_priority = bool(flag)

    # -- request path --------------------------------------------------------

    def classify(
        self,
        principal_id: str,
        roles: Sequence[str] = (),
        kinds: Sequence[str] = (),
        api: str = "check",
    ) -> PriorityClass:
        """First matching class in declaration order; the implicit default
        class catches everything else."""
        for c in self.classes:
            if c.matches(principal_id, roles, kinds, api):
                return c
        return self.default

    def try_admit(self, cls: PriorityClass, now: Optional[float] = None) -> AdmissionTicket:
        """Admit or raise ``OverloadRefused``. The returned ticket MUST be
        released (``finally``) when the request finishes."""
        if not self.enabled:
            return self._null
        now = self._clock() if now is None else now
        with self._lock:
            st = self._state.get(cls.name)
            if st is None:  # classes swapped under us: admit, never crash
                return self._null
            if self._shed_low_priority and cls.sheddable:
                self.m_total.inc((cls.name, REFUSED_BROWNOUT))
                raise OverloadRefused(cls.name, "brownout", retry_after=1.0)
            if cls.max_concurrent and st.inflight >= cls.max_concurrent:
                self.m_total.inc((cls.name, REFUSED_CONCURRENCY))
                raise OverloadRefused(cls.name, "concurrency", retry_after=0.1)
            if cls.rate > 0:
                if st.last is not None:
                    st.tokens = min(cls.burst, st.tokens + (now - st.last) * cls.rate)
                st.last = now
                if st.tokens < 1.0:
                    self.m_total.inc((cls.name, REFUSED_RATE))
                    raise OverloadRefused(
                        cls.name, "rate", retry_after=(1.0 - st.tokens) / cls.rate
                    )
                st.tokens -= 1.0
            st.inflight += 1
            st.g_inflight.set(float(st.inflight))
        self.m_total.inc((cls.name, ADMITTED))
        return AdmissionTicket(self, cls)

    def _release(self, cls: PriorityClass) -> None:
        with self._lock:
            st = self._state.get(cls.name)
            if st is None:
                return
            st.inflight = max(0, st.inflight - 1)
            st.g_inflight.set(float(st.inflight))

    # -- observability -------------------------------------------------------

    def observe_refusal(self, seconds: float) -> None:
        self.m_refusal_seconds.observe(max(0.0, seconds))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "shed_low_priority": self._shed_low_priority,
                "classes": [
                    {
                        "name": c.name,
                        "priority": c.priority,
                        "weight": c.weight,
                        "rate": c.rate,
                        "burst": c.burst,
                        "maxConcurrent": c.max_concurrent,
                        "queueBudget": c.queue_budget,
                        "sheddable": c.sheddable,
                        "inflight": self._state[c.name].inflight
                        if c.name in self._state
                        else 0,
                    }
                    for c in (*self.classes, self.default)
                ],
            }


def retry_after_header(e: OverloadRefused) -> str:
    """HTTP ``Retry-After`` delay-seconds: integral, never negative, and at
    least 1 for anything non-trivially in the future (sub-second refusals
    still tell the client to back off, not to hot-loop)."""
    return str(max(1, int(math.ceil(e.retry_after))) if e.retry_after > 0.001 else 1)


_controller = AdmissionController()


def controller() -> AdmissionController:
    return _controller
