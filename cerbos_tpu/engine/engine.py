"""Engine facade: batch dispatch over the rule table.

Behavioral reference: internal/engine/engine.go (Check entry, audit hook).
The reference fans small batches onto a goroutine pool; here the batch path
is the TPU evaluator (cerbos_tpu.tpu) and the CPU oracle serves small
batches serially, mirroring the reference's parallelismThreshold=5 split.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Optional, Sequence

from . import types as T
from .hotrules import recorder as hotrule_recorder

if TYPE_CHECKING:  # avoid circular imports (ruletable.check imports engine.types)
    from ..compile.compiler import CompiledPolicy
    from ..ruletable import RuleTable


class Engine:
    def __init__(
        self,
        rule_table: "RuleTable",
        schema_mgr: Any = None,
        eval_params: Optional[T.EvalParams] = None,
        tpu_evaluator: Any = None,
        tpu_batch_threshold: int = 5,
        on_decision: Optional[Callable[[list[T.CheckInput], list[T.CheckOutput]], None]] = None,
    ):
        self.rule_table = rule_table
        self.schema_mgr = schema_mgr
        self.eval_params = eval_params or T.EvalParams()
        self.tpu_evaluator = tpu_evaluator
        self.tpu_batch_threshold = tpu_batch_threshold
        self.on_decision = on_decision

    @classmethod
    def from_policies(cls, policies: "list[CompiledPolicy]", **kwargs) -> "Engine":
        from ..ruletable import build_rule_table

        return cls(build_rule_table(policies), **kwargs)

    def check(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Any] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        from ..observability import start_span

        params = params or self.eval_params
        with start_span("engine.Check", batch_size=len(inputs)) as span:
            if self.tpu_evaluator is not None and len(inputs) >= self.tpu_batch_threshold:
                span.set_attribute("path", "device")
                kwargs = {}
                if deadline is not None and getattr(self.tpu_evaluator, "supports_deadline", False):
                    # per-request deadline (from the gRPC context) rides down
                    # to the batcher, which drops expired work at drain time
                    kwargs["deadline"] = deadline
                if wf is not None and getattr(self.tpu_evaluator, "supports_waterfall", False):
                    kwargs["wf"] = wf
                if pclass is not None and getattr(self.tpu_evaluator, "supports_pclass", False):
                    # admission class rides down to the batcher's priority
                    # lanes (queue budget + weighted scheduling)
                    kwargs["pclass"] = pclass
                outputs = self.tpu_evaluator.check(list(inputs), params, **kwargs)
                if wf is not None and "wf" not in kwargs:
                    # evaluator without stage bookkeeping: the whole device
                    # call books as one evaluate stage
                    wf.mark("evaluate")
            else:
                from ..ruletable import check_input

                span.set_attribute("path", "serial")
                # read the table once: a rollout cutover between inputs must
                # not split one request across two tables, and the epoch
                # stamp must describe the table actually used
                rt = self.rule_table
                T.set_current_epoch(getattr(rt, "policy_epoch", None))
                outputs = [check_input(rt, i, params, self.schema_mgr) for i in inputs]
                # serial decisions bypass the batcher: fold them into the
                # hot-rule heatmap here so attribution telemetry stays
                # complete on low-traffic hosts (ISSUE 20)
                hotrule_recorder().observe(outputs)
                if wf is not None:
                    wf.mark("evaluate")
        if self.on_decision is not None:
            self.on_decision(list(inputs), outputs)
        return outputs

    @property
    def supports_async(self) -> bool:
        """True when the dispatch evaluator can settle checks on an asyncio
        loop (the RemoteBatcherClient in front-end mode). The HTTP server
        uses this to skip the per-request thread-pool hop entirely."""
        return self.tpu_evaluator is not None and hasattr(self.tpu_evaluator, "check_await")

    async def check_await(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Any] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        """Event-loop-native check: awaits the evaluator's reply future with
        no executor hop. Small batches below the device threshold still take
        the serial oracle inline — at threshold sizes that is cheaper than a
        loop hand-off."""
        from ..observability import start_span

        params = params or self.eval_params
        with start_span("engine.Check", batch_size=len(inputs)) as span:
            if (
                self.tpu_evaluator is not None
                and len(inputs) >= self.tpu_batch_threshold
                and hasattr(self.tpu_evaluator, "check_await")
            ):
                span.set_attribute("path", "device")
                kwargs = {}
                if wf is not None and getattr(self.tpu_evaluator, "supports_waterfall", False):
                    kwargs["wf"] = wf
                if pclass is not None and getattr(self.tpu_evaluator, "supports_pclass", False):
                    kwargs["pclass"] = pclass
                outputs = await self.tpu_evaluator.check_await(
                    list(inputs), params, deadline=deadline, **kwargs
                )
                if wf is not None and "wf" not in kwargs:
                    wf.mark("evaluate")
            else:
                from ..ruletable import check_input

                span.set_attribute("path", "serial")
                # single table read per request — see check() above
                rt = self.rule_table
                T.set_current_epoch(getattr(rt, "policy_epoch", None))
                outputs = [check_input(rt, i, params, self.schema_mgr) for i in inputs]
                hotrule_recorder().observe(outputs)  # see check() above
                if wf is not None:
                    wf.mark("evaluate")
        if self.on_decision is not None:
            self.on_decision(list(inputs), outputs)
        return outputs
