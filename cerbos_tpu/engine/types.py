"""Engine request/response types.

Behavioral reference: api/public/cerbos/engine/v1/engine.proto (CheckInput,
CheckOutput, Principal, Resource, AuxData) and internal/evaluator (EvalParams,
CheckOpts). Attribute values follow protobuf Struct semantics: JSON numbers
become doubles at ingestion so CEL sees the same types as the reference.
"""

from __future__ import annotations

import contextvars
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..util import normalize_attr

# which device lane evaluated the current request — set on the request
# thread by the batcher/shard-pool entry points, read by the service layer
# to stamp audit decision entries (the audit↔flight-recorder join key).
# A ContextVar (not a plain thread-local) so async callers inherit it.
_current_shard: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "cerbos_tpu_current_shard", default=None
)


def set_current_shard(shard: Optional[int]) -> None:
    _current_shard.set(shard)


def current_shard() -> Optional[int]:
    return _current_shard.get()


# which policy epoch the current request was evaluated against — stamped by
# the evaluator that actually resolved the request (batcher device path,
# oracle fallbacks, the serial engine path, or the IPC client from its last
# STATUS frame) and read by the service layer into audit decision entries,
# making mixed-table evaluation directly observable (ISSUE 18).
_current_epoch: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "cerbos_tpu_current_epoch", default=None
)


def set_current_epoch(epoch: Optional[int]) -> None:
    _current_epoch.set(epoch)


def current_epoch() -> Optional[int]:
    return _current_epoch.get()


EFFECT_ALLOW = "EFFECT_ALLOW"
EFFECT_DENY = "EFFECT_DENY"
EFFECT_NO_MATCH = "EFFECT_NO_MATCH"

NO_POLICY_MATCH = "NO_MATCH"
NO_MATCH_SCOPE_PERMISSIONS = "NO_MATCH_FOR_SCOPE_PERMISSIONS"

KIND_PRINCIPAL = "PRINCIPAL"
KIND_RESOURCE = "RESOURCE"


@dataclass
class Principal:
    id: str
    roles: list[str]
    attr: dict[str, Any] = field(default_factory=dict)
    policy_version: str = ""
    scope: str = ""

    def __post_init__(self) -> None:
        self.attr = {k: normalize_attr(v) for k, v in self.attr.items()}


@dataclass
class Resource:
    kind: str
    id: str = ""
    attr: dict[str, Any] = field(default_factory=dict)
    policy_version: str = ""
    scope: str = ""

    def __post_init__(self) -> None:
        self.attr = {k: normalize_attr(v) for k, v in self.attr.items()}


@dataclass
class AuxData:
    jwt: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.jwt = {k: normalize_attr(v) for k, v in self.jwt.items()}


@dataclass
class CheckInput:
    principal: Principal
    resource: Resource
    actions: list[str]
    request_id: str = ""
    aux_data: Optional[AuxData] = None


@dataclass(slots=True)
class ActionEffect:
    effect: str
    policy: str
    scope: str = ""
    # decision provenance (ISSUE 20): the winning rule as `<policy>#<rule>`
    # plus its lowered rule-table row id, and which evaluator produced the
    # decision ("device" | "oracle"). Empty/-1 when no rule matched (default
    # DENY / NO_MATCH) — parity comparisons deliberately exclude these
    # fields (sentinel.effect_rows compares effect/policy/scope only).
    matched_rule: str = ""
    rule_row_id: int = -1
    source: str = ""


@dataclass(slots=True)
class OutputEntry:
    src: str
    action: str = ""
    val: Any = None
    error: str = ""


@dataclass(slots=True)
class ValidationError:
    path: str
    message: str
    source: str  # SOURCE_PRINCIPAL | SOURCE_RESOURCE


@dataclass(slots=True)
class CheckOutput:
    request_id: str
    resource_id: str
    actions: dict[str, ActionEffect] = field(default_factory=dict)
    effective_derived_roles: list[str] = field(default_factory=list)
    validation_errors: list[ValidationError] = field(default_factory=list)
    outputs: list[OutputEntry] = field(default_factory=list)
    # audit-trail provenance (policy key → source attributes); not part of
    # the API response, consumed by the decision log
    # (auditv1.AuditTrail.effectivePolicies)
    effective_policies: dict[str, Any] = field(default_factory=dict)


@dataclass
class EvalParams:
    """Ref: internal/evaluator/evaluator.go:91-97."""

    globals: dict[str, Any] = field(default_factory=dict)
    now_fn: Optional[Callable[[], Any]] = None
    default_policy_version: str = "default"
    default_scope: str = ""
    lenient_scope_search: bool = False


def effective_scope(scope: str, params: EvalParams) -> str:
    if scope == "":
        scope = params.default_scope
    return scope[1:] if scope.startswith(".") else scope


def effective_version(version: str, params: EvalParams) -> str:
    return version or params.default_policy_version
