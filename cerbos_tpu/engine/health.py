"""Device-path circuit breaker: closed → open → half-open.

The serving batcher treats the device pipeline as a supervised fault
domain. Consecutive ``submit()``/``collect()`` failures — or a high
request-timeout rate over a sliding window — trip the breaker OPEN, at
which point ``check()`` routes straight to the CPU oracle with no device
wait at all (a wedged chip must cost zero latency, not a 30 s future
timeout per request). While open, background probe batches paced by
``util.retry.backoff_delay`` move the breaker HALF_OPEN; a probe success
re-CLOSES it and live traffic returns to the device, a probe failure (or
a probe that itself wedges past ``probe_timeout_s``) re-opens it with a
longer backoff.

Breaker state is exported as the ``cerbos_tpu_breaker_state`` gauge
(0 = closed, 1 = open, 2 = half-open) and trips as
``cerbos_tpu_breaker_trips_total`` on ``/_cerbos/metrics``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..util.retry import backoff_delay
from .flight import recorder as flight_recorder

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

_STATE_CODE = {STATE_CLOSED: 0, STATE_OPEN: 1, STATE_HALF_OPEN: 2}

_log = logging.getLogger("cerbos_tpu.engine.health")


class DeviceHealth:
    """Thread-safe breaker state machine shared by the batcher, its drain
    loop and the background probe threads.

    A disabled breaker (``enabled=False``) never trips: ``allow_device()``
    is always True and every record_* call is a no-op, so the batcher's
    pre-breaker behavior is preserved exactly.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        timeout_rate_threshold: float = 0.5,
        timeout_window_s: float = 30.0,
        timeout_min_samples: int = 10,
        probe_backoff_base_s: float = 0.5,
        probe_backoff_cap_s: float = 30.0,
        probe_timeout_s: float = 5.0,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
        shard_id: Optional[int] = None,
    ):
        self.enabled = enabled
        # which lane of the sharded pool this breaker guards; None = the
        # only breaker. Metrics carry shard="0" either way so dashboards
        # see one schema.
        self.shard_id = shard_id
        self._shard_label = str(shard_id) if shard_id is not None else "0"
        self.failure_threshold = max(1, int(failure_threshold))
        self.timeout_rate_threshold = float(timeout_rate_threshold)
        self.timeout_window_s = float(timeout_window_s)
        self.timeout_min_samples = max(1, int(timeout_min_samples))
        self.probe_backoff_base_s = float(probe_backoff_base_s)
        self.probe_backoff_cap_s = float(probe_backoff_cap_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._consecutive_failures = 0
        # sliding window of (ts, timed_out) request outcomes for the
        # timeout-rate trip condition (a device can wedge without raising)
        self._outcomes: deque[tuple[float, bool]] = deque()
        # consecutive open periods without a successful re-close; paces the
        # probe cadence through backoff_delay
        self._trip_streak = 0
        self._next_probe_at = 0.0
        self._probe_token = 0
        self._probe_started_at = 0.0
        self.stats = {"trips": 0, "probes": 0}
        self._init_metrics()

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_state = reg.gauge_vec(
            "cerbos_tpu_breaker_state",
            "device-path breaker state (0=closed, 1=open, 2=half-open), by shard",
            label="shard",
        ).labels(self._shard_label)
        self.m_trips = reg.counter_vec(
            "cerbos_tpu_breaker_trips_total",
            "times the device-path breaker tripped open, by shard",
            label="shard",
        )
        self.m_transitions = reg.counter_vec(
            "cerbos_tpu_breaker_transitions_total",
            "breaker state transitions, labeled from_to (e.g. closed_open), by shard",
            label=("transition", "shard"),
        )
        self.m_state.set(_STATE_CODE[self._state])

    def _set_state_locked(self, new_state: str, cause: str = "") -> None:
        """Single choke point for state changes: gauge, transition counter,
        and a flight-recorder event carrying the from/to edge."""
        old = self._state
        if old == new_state:
            return
        self._state = new_state
        self.m_state.set(_STATE_CODE[new_state])
        self.m_transitions.inc((f"{old}_{new_state}", self._shard_label))
        flight_recorder().record_event(
            "breaker_transition", frm=old, to=new_state, cause=cause, shard=self.shard_id
        )

    # -- state queries ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    def allow_device(self) -> bool:
        """True when live traffic may ride the device path."""
        if not self.enabled:
            return True
        with self._lock:
            self._tick_locked()
            return self._state == STATE_CLOSED

    def probe_due(self) -> bool:
        """Non-consuming peek: OPEN with the probe backoff elapsed. The
        sharded router uses this to trickle one donor request onto a sick
        lane (oracle-served there) so its own ``should_probe`` machinery
        gets inputs to probe with — without claiming the probe token."""
        if not self.enabled:
            return False
        with self._lock:
            self._tick_locked()
            return self._state == STATE_OPEN and self._clock() >= self._next_probe_at

    def should_probe(self) -> Optional[int]:
        """When the breaker is OPEN and the backoff has elapsed, transition
        to HALF_OPEN and return a probe token; the caller runs one probe
        batch off-path and reports back with probe_succeeded/probe_failed.
        Returns None when no probe is due (or one is already in flight)."""
        if not self.enabled:
            return None
        with self._lock:
            self._tick_locked()
            if self._state != STATE_OPEN or self._clock() < self._next_probe_at:
                return None
            self._set_state_locked(STATE_HALF_OPEN, "probe_due")
            self._probe_token += 1
            self._probe_started_at = self._clock()
            self.stats["probes"] += 1
            return self._probe_token

    # -- outcome recording --------------------------------------------------

    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._consecutive_failures = 0
            self._observe_locked(timed_out=False)

    def record_failure(self) -> None:
        """A device submit/collect raised."""
        if not self.enabled:
            return
        with self._lock:
            self._consecutive_failures += 1
            if (
                self._state == STATE_CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trip_locked("consecutive_failures")

    def record_timeout(self) -> None:
        """A request waited out its future timeout (wedged, not raising)."""
        if not self.enabled:
            return
        with self._lock:
            self._observe_locked(timed_out=True)
            if self._state != STATE_CLOSED:
                return
            timeouts = sum(1 for _, t in self._outcomes if t)
            n = len(self._outcomes)
            if n >= self.timeout_min_samples and timeouts / n >= self.timeout_rate_threshold:
                self._trip_locked("timeout_rate")

    def trip(self, cause: str) -> None:
        """Externally-forced trip: the parity sentinel's storm policy calls
        this when a lane keeps returning effects the CPU oracle disagrees
        with — wrong answers are worse than slow ones, so the lane is routed
        to the oracle just as if it were erroring. No-op while already OPEN
        (the probe backoff in progress stays paced)."""
        if not self.enabled:
            return
        with self._lock:
            if self._state == STATE_OPEN:
                return
            self._trip_locked(cause)

    def probe_succeeded(self, token: int) -> None:
        with self._lock:
            if token != self._probe_token or self._state != STATE_HALF_OPEN:
                return  # stale probe (expired or superseded): ignore
            self._set_state_locked(STATE_CLOSED, "probe_succeeded")
            self._consecutive_failures = 0
            self._trip_streak = 0
            self._outcomes.clear()
            _log.info("device-path breaker re-closed after successful probe")

    def probe_failed(self, token: int) -> None:
        with self._lock:
            if token != self._probe_token or self._state != STATE_HALF_OPEN:
                return
            self._reopen_locked()

    # -- internals ----------------------------------------------------------

    def _observe_locked(self, timed_out: bool) -> None:
        now = self._clock()
        self._outcomes.append((now, timed_out))
        horizon = now - self.timeout_window_s
        while self._outcomes and self._outcomes[0][0] < horizon:
            self._outcomes.popleft()

    def _trip_locked(self, cause: str) -> None:
        self._set_state_locked(STATE_OPEN, cause)
        self._trip_streak += 1
        self._next_probe_at = self._clock() + backoff_delay(
            self._trip_streak, self.probe_backoff_base_s, self.probe_backoff_cap_s
        )
        self.stats["trips"] += 1
        self.m_trips.inc(self._shard_label)
        _log.error(
            "device-path breaker tripped open; serving from the CPU oracle",
            extra={"fields": {"cause": cause, "streak": self._trip_streak}},
        )

    def _reopen_locked(self) -> None:
        self._set_state_locked(STATE_OPEN, "probe_failed")
        self._trip_streak += 1
        self._next_probe_at = self._clock() + backoff_delay(
            self._trip_streak, self.probe_backoff_base_s, self.probe_backoff_cap_s
        )

    def _tick_locked(self) -> None:
        """Expire a probe that never reported back (the probe thread is
        wedged in a blocking device call): bump the token so its eventual
        result is ignored and re-open with a longer backoff."""
        if (
            self._state == STATE_HALF_OPEN
            and self._clock() - self._probe_started_at > self.probe_timeout_s
        ):
            self._probe_token += 1
            self._reopen_locked()
