"""Readiness split from liveness.

``/_cerbos/health`` answers "is the process alive" and must stay green the
moment the listeners bind. But a replica whose dominant device layouts are
not compiled yet will hand its first unlucky callers a multi-second XLA
compile — so ``/_cerbos/ready`` (HTTP and the gRPC health service) answers
the different question "is it safe to route traffic here", reporting
``{status, compiled_layouts, expected}``:

- ``warming``  — the warmup driver is still pre-compiling; NOT serving
  (HTTP 503 / gRPC NOT_SERVING) so load balancers hold traffic back;
- ``ready``    — all expected layouts compiled (or no warmup configured);
- ``degraded`` — warm, but the device circuit breaker is open and requests
  are riding the CPU oracle. Still SERVING: degraded-but-live beats a
  restart loop, and the breaker state is exported for alerting.

One process-global instance (the flight-recorder pattern): bootstrap drives
the transitions, both servers read it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..observability import metrics

_STATUS_CODE = {"warming": 0.0, "ready": 1.0, "degraded": 2.0}


class ReadinessState:
    """Thread-safe readiness snapshot: warming → ready (→ degraded while the
    breaker is open). ``clock`` is injectable for tests."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        reg = metrics()
        self.m_state = reg.gauge(
            "cerbos_tpu_readiness_state",
            "0 warming (not serving), 1 ready, 2 degraded (breaker open, oracle serving)",
        )
        self.m_expected = reg.gauge(
            "cerbos_tpu_warmup_expected_layouts",
            "Device layouts the warmup driver intends to pre-compile",
        )
        self.m_compiled = reg.gauge(
            "cerbos_tpu_warmup_compiled_layouts",
            "Device layouts the warmup driver has pre-compiled so far",
        )
        self._clock = clock
        self._lock = threading.Lock()
        # a server with no warmup configured is born ready: readiness must
        # never gate deployments that opted out of pre-compilation
        self._ready = True
        self._expected = 0
        self._compiled = 0
        self._warmup_error: Optional[str] = None
        self._warmed_at: Optional[float] = None
        self._health: Optional[Callable[[], str]] = None
        self._remote: Optional[Callable[[], dict]] = None
        self._parity: Optional[Callable[[], list]] = None
        self._brownout: Optional[Callable[[], str]] = None
        self._epoch: Optional[Callable[[], dict]] = None
        self.m_state.set(_STATUS_CODE["ready"])

    # -- transitions (driven by bootstrap / the warmup driver) -------------

    def begin_warmup(self, expected: int) -> None:
        with self._lock:
            self._ready = False
            self._expected = int(expected)
            self._compiled = 0
            self._warmup_error = None
            self._warmed_at = None
        self.m_expected.set(float(expected))
        self.m_compiled.set(0.0)
        self.m_state.set(_STATUS_CODE["warming"])

    def layout_compiled(self) -> None:
        with self._lock:
            self._compiled += 1
            compiled = self._compiled
        self.m_compiled.set(float(compiled))

    def mark_ready(self, error: Optional[str] = None) -> None:
        """Warmup finished — or failed: a failed warmup still opens the
        gates (with the error recorded), because never-ready is a worse
        failure mode than cold-compiling under traffic."""
        with self._lock:
            self._ready = True
            self._warmup_error = error
            self._warmed_at = self._clock()

    def bind_health(self, provider: Optional[Callable[[], str]]) -> None:
        """Wire the device breaker's state in: an open breaker after warmup
        reports ``degraded`` (still serving). ``provider`` returns the
        breaker state string (``closed`` / ``open`` / ``half_open``)."""
        self._health = provider

    def bind_parity(self, provider: Optional[Callable[[], list]]) -> None:
        """Wire the parity sentinel's storm state in: any shard inside a
        divergence storm reports ``degraded`` with reason ``parity`` (still
        serving — the tripped lane rides the CPU oracle, which is correct by
        definition). ``provider`` returns the storming shard ids."""
        self._parity = provider

    def bind_brownout(self, provider: Optional[Callable[[], str]]) -> None:
        """Wire the brownout controller's stage in: while any shed stage is
        engaged the snapshot carries ``reason: "brownout"`` + the deepest
        stage name (still serving — shedding optional work IS how the
        service stays live). ``provider`` returns the stage name or ''."""
        self._brownout = provider

    def bind_epoch(self, provider: Optional[Callable[[], dict]]) -> None:
        """Wire the rollout controller's epoch block in: ``{"policy_epoch":
        N, "policy_epoch_committed_at": wall_ts, ...}`` merged into every
        snapshot. Because the shared batcher's STATUS frames are built from
        this snapshot, front ends learn about cutovers on their next status
        poll with no IPC frame change — ``committed_at`` is the wall-clock
        reference the skew gauge measures against."""
        self._epoch = provider

    def _epoch_info(self) -> dict:
        provider = getattr(self, "_epoch", None)
        if provider is None:
            return {}
        try:
            return dict(provider() or {})
        except Exception:
            return {}

    def bind_remote(self, provider: Optional[Callable[[], dict]]) -> None:
        """Front-end mode: this process has no device of its own — readiness
        is the SHARED batcher process's readiness, fetched over the ticket
        queue. ``provider`` returns a snapshot dict with at least
        ``{"status": warming|ready|degraded}``; it overrides the local state
        machine entirely (the local process never warms anything)."""
        self._remote = provider

    # -- reads (servers, probes, tests) ------------------------------------

    def status(self) -> str:
        remote = getattr(self, "_remote", None)
        if remote is not None:
            st = "degraded"
            try:
                st = str(remote().get("status", "degraded"))
            except Exception:
                pass
            if st not in _STATUS_CODE:
                st = "degraded"
            self.m_state.set(_STATUS_CODE[st])
            return st
        with self._lock:
            ready = self._ready
        st = "ready"
        if not ready:
            st = "warming"
        else:
            provider = self._health
            if provider is not None:
                try:
                    if provider() == "open":
                        st = "degraded"
                except Exception:
                    pass
            if st == "ready" and self._parity_shards():
                st = "degraded"
        self.m_state.set(_STATUS_CODE[st])
        return st

    def _parity_shards(self) -> list:
        provider = getattr(self, "_parity", None)
        if provider is None:
            return []
        try:
            return list(provider())
        except Exception:
            return []

    def _brownout_stage(self) -> str:
        provider = getattr(self, "_brownout", None)
        if provider is None:
            return ""
        try:
            return str(provider() or "")
        except Exception:
            return ""

    def serving(self) -> bool:
        """Gate decision: warming withholds traffic; degraded is live."""
        return self.status() != "warming"

    def snapshot(self) -> dict:
        remote = getattr(self, "_remote", None)
        if remote is not None:
            snap: dict = {}
            try:
                snap = dict(remote())
            except Exception:
                pass
            st = str(snap.get("status", "degraded"))
            snap["status"] = st if st in _STATUS_CODE else "degraded"
            snap.setdefault("attached", False)
            snap["topology"] = "frontend"
            # the front end runs its OWN brownout ladder (admission-side
            # sheds happen here); the batcher's stage arrives inside the
            # remote snapshot and the deeper of the two wins
            local_stage = self._brownout_stage()
            if local_stage and not snap.get("brownout_stage"):
                snap["brownout_stage"] = local_stage
                snap.setdefault("reason", "brownout")
            self.m_state.set(_STATUS_CODE[snap["status"]])
            return snap
        st = self.status()
        parity_shards = self._parity_shards()
        brownout_stage = self._brownout_stage()
        with self._lock:
            out = {
                "status": st,
                "compiled_layouts": self._compiled,
                "expected": self._expected,
            }
            if self._warmup_error:
                out["warmup_error"] = self._warmup_error
        if parity_shards:
            out["reason"] = "parity"
            out["parity_shards"] = parity_shards
        if brownout_stage:
            # parity keeps the reason slot if both fire (it signals possible
            # wrong answers; brownout only signals shed work)
            out.setdefault("reason", "brownout")
            out["brownout_stage"] = brownout_stage
        out.update(self._epoch_info())
        return out


_state = ReadinessState()


def state() -> ReadinessState:
    return _state
