"""Process-crossing ticket queue: N front-end processes → one shared batcher.

The GIL caps a single PDP process far below what the device batcher can
evaluate (docs/PERF.md "Served-path latency": 586 RPS served vs 64k+ dec/s in
batch form). An SO_REUSEPORT pool of full PDPs doesn't close the gap either:
each forked worker drives its OWN evaluator, fragmenting batches and
multiplying XLA compiles per process. The fix is topological — many HTTP/gRPC
front-end processes parse and validate traffic, ONE batcher process owns the
device — and this module is the seam between them: a per-worker ticket
queue over a unix domain socket carrying compact check tickets in and packed
effect/meta rows out.

Transport: two interchangeable data planes under one control plane.

- The control plane is always a SOCK_STREAM unix socket, one connection per
  front-end process: HELLO negotiation, status/flight/metrics/slow/pressure
  snapshots, and — critically — liveness. A dying peer closes the socket,
  and that close is what fails in-flight tickets instantly and flips the
  front end onto its oracle, whichever data plane carried the tickets.
- ``transport: uds`` (fallback) carries check tickets on that same socket as
  length-prefixed ``marshal`` frames — the kernel socket buffer IS the ring,
  with blocking-read wakeups for free, and it works on pure-Python hosts.
- ``transport: shm`` (default where the native module builds) moves the hot
  frames — CHECK in, RESULT/ERR out — onto a pair of shared-memory byte
  rings (one per direction) with futex wakeups, packed and unpacked by the
  native frame codec (``ticket_pack``/``reply_pack``): no marshal, no
  socket syscall, no intermediate row tuples on the per-request path. The
  front end creates the segment, offers it in HELLO, and the batcher maps
  it or refuses (HELLO_R), so a native-less peer on either end degrades the
  pair to uds automatically.

All padding/stacking of decoded tickets stays on the batcher side via the
evaluator's pooled ``_pad_stack`` staging buffers, so the marshalling cost
the device cares about never leaves the device-owning process.

Fault semantics mirror docs/ROBUSTNESS.md, distributed:

- the batcher's fast-path refusals (breaker open, quarantine hit, dead drain
  loop, full queue) come back as compact ERR frames and the FRONT END serves
  its own COW-shared CPU oracle — the batcher process spends no cycles on
  degraded traffic;
- a dead batcher process settles every in-flight ticket with a connection
  error immediately (no timeout wait); front ends degrade to their oracle and
  a background loop reconnects when the supervisor respawns the batcher;
- per-request deadlines travel as RELATIVE remaining seconds (monotonic
  clocks are not comparable across processes) and re-anchor on arrival.
"""

from __future__ import annotations

import asyncio
import logging
import marshal
import mmap
import os
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Optional, Sequence

from .. import native
from ..observability import current_span_context, parse_traceparent
from ..ruletable import check_input
from . import types as T
from .admission import OverloadRefused
from .batcher import DeadlineExceeded, _BatchFailed
from .budget import STAGE_IPC_ENCODE, STAGE_ORACLE, Waterfall
from .budget import tracker as budget_tracker

_log = logging.getLogger("cerbos_tpu.engine.ipc")

# -- frame protocol ----------------------------------------------------------

_HDR = struct.Struct("<IBQ")  # payload length, frame type, request id

T_HELLO = 1
T_CHECK = 2
T_RESULT = 3
T_ERR = 4
T_STATUS = 5
T_STATUS_R = 6
T_FLIGHT = 7
T_FLIGHT_R = 8
T_METRICS = 9
T_METRICS_R = 10
T_SLOW = 11
T_SLOW_R = 12
T_PRESSURE = 13
T_PRESSURE_R = 14
T_HELLO_R = 15
T_HOTRULES = 16
T_HOTRULES_R = 17

_MAX_FRAME = 64 * 1024 * 1024  # a corrupt length must not allocate the moon


class IpcError(Exception):
    """Transport-level failure (framing, codec, connection)."""


class IpcDisconnected(IpcError):
    """The peer went away; in-flight tickets must settle immediately."""


def _send_frame(sock: socket.socket, mtype: int, req_id: int, payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload), mtype, req_id) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise IpcDisconnected("peer closed the ticket queue")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[int, int, bytes]:
    length, mtype, req_id = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if length > _MAX_FRAME:
        raise IpcError(f"oversized frame ({length} bytes)")
    return mtype, req_id, _recv_exact(sock, length) if length else b""


# -- shared-memory segment ---------------------------------------------------
#
# One file-backed mmap per front-end connection: a 4 KiB descriptor page
# (magic / version / ring size) followed by two native byte rings — tickets
# toward the batcher (c2s) and replies back (s2c). The FRONT END creates and
# sizes the segment, offers its path in HELLO, and unlinks the name as soon
# as the handshake settles either way: from then on the mapping lives exactly
# as long as the two processes that hold it, and a SIGKILL on either side
# cannot leak a name into /dev/shm.

_SHM_MAGIC = 0x43544652
_SHM_VER = 1
_SHM_HDR = struct.Struct("<IIQ")
_RING_HDR_BYTES = 256
_shm_counter = 0


def _align_page(n: int) -> int:
    return (n + 4095) & ~4095


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


class _ShmSegment:
    """The mapped segment plus the two ring memoryviews the native kernels
    operate on. ``create`` is the front-end side, ``attach`` the batcher
    side; both hold identical mappings once the HELLO handshake grants shm."""

    def __init__(self, path: str, mm: mmap.mmap, ring_bytes: int):
        self.path = path
        self.mm = mm
        self.ring_bytes = ring_bytes
        span = _align_page(_RING_HDR_BYTES + ring_bytes)
        view = memoryview(mm)
        self._view = view
        self.c2s = view[4096 : 4096 + _RING_HDR_BYTES + ring_bytes]
        self.s2c = view[4096 + span : 4096 + span + _RING_HDR_BYTES + ring_bytes]

    @classmethod
    def create(cls, name_hint: str, ring_bytes: int) -> "_ShmSegment":
        global _shm_counter
        _shm_counter += 1
        nat = native.get()
        if nat is None:
            raise IpcError("native module unavailable")
        path = os.path.join(
            _shm_dir(), f"cerbos-tpu-ring-{os.getpid()}-{_shm_counter}-{name_hint}"
        )
        span = _align_page(_RING_HDR_BYTES + ring_bytes)
        total = 4096 + 2 * span
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        os.close(fd)
        _SHM_HDR.pack_into(mm, 0, _SHM_MAGIC, _SHM_VER, ring_bytes)
        seg = cls(path, mm, ring_bytes)
        nat.ring_init(seg.c2s)
        nat.ring_init(seg.s2c)
        return seg

    @classmethod
    def attach(cls, path: str) -> "_ShmSegment":
        if native.get() is None:
            raise IpcError("native module unavailable")
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, ver, ring_bytes = _SHM_HDR.unpack_from(mm, 0)
        span = _align_page(_RING_HDR_BYTES + ring_bytes)
        if magic != _SHM_MAGIC or ver != _SHM_VER or size != 4096 + 2 * span:
            mm.close()
            raise IpcError(f"not a cerbos-tpu ring segment: {path}")
        return cls(path, mm, ring_bytes)

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.c2s.release()
            self.s2c.release()
            self._view.release()
            self.mm.close()
        except (BufferError, ValueError, OSError):
            pass


# -- ticket codec ------------------------------------------------------------
#
# CheckInput/CheckOutput → plain tuples marshal can swallow. Attribute values
# were already normalized (structpb double semantics) at the front end's
# ingestion, so decode reconstructs the dataclasses via __new__ and skips
# __post_init__ — re-normalizing on the batcher would double that work.


def encode_inputs(inputs: Sequence[T.CheckInput]) -> list:
    rows = []
    for i in inputs:
        p, r = i.principal, i.resource
        rows.append(
            (
                i.request_id,
                (p.id, list(p.roles or ()), p.attr, p.policy_version, p.scope),
                (r.kind, r.id, r.attr, r.policy_version, r.scope),
                list(i.actions or ()),
                i.aux_data.jwt if i.aux_data is not None else None,
            )
        )
    return rows


def decode_inputs(rows: list) -> list[T.CheckInput]:
    out = []
    for request_id, prow, rrow, actions, jwt in rows:
        p = T.Principal.__new__(T.Principal)
        p.id, p.roles, p.attr, p.policy_version, p.scope = prow
        r = T.Resource.__new__(T.Resource)
        r.kind, r.id, r.attr, r.policy_version, r.scope = rrow
        aux = None
        if jwt is not None:
            aux = T.AuxData.__new__(T.AuxData)
            aux.jwt = jwt
        inp = T.CheckInput.__new__(T.CheckInput)
        inp.request_id, inp.principal, inp.resource = request_id, p, r
        inp.actions, inp.aux_data = actions, aux
        out.append(inp)
    return out


def encode_outputs(outputs: Sequence[T.CheckOutput]) -> list:
    rows = []
    for o in outputs:
        rows.append(
            (
                o.request_id,
                o.resource_id,
                [
                    (a, ae.effect, ae.policy, ae.scope, ae.matched_rule, ae.rule_row_id, ae.source)
                    for a, ae in o.actions.items()
                ],
                list(o.effective_derived_roles),
                [(v.path, v.message, v.source) for v in o.validation_errors],
                [(e.src, e.action, e.val, e.error) for e in o.outputs],
                o.effective_policies,
            )
        )
    return rows


def decode_outputs(rows: list) -> list[T.CheckOutput]:
    out = []
    for request_id, resource_id, actions, edr, verrs, oents, epols in rows:
        out.append(
            T.CheckOutput(
                request_id=request_id,
                resource_id=resource_id,
                actions={
                    a: T.ActionEffect(
                        effect=e, policy=pol, scope=sc,
                        matched_rule=rule, rule_row_id=row, source=src,
                    )
                    for a, e, pol, sc, rule, row, src in actions
                },
                effective_derived_roles=list(edr),
                validation_errors=[
                    T.ValidationError(path=p, message=m, source=s) for p, m, s in verrs
                ],
                outputs=[
                    T.OutputEntry(src=src, action=act, val=val, error=err)
                    for src, act, val, err in oents
                ],
                effective_policies=epols,
            )
        )
    return out


# -- batcher-side server -----------------------------------------------------


class _ConnWriter:
    """Per-connection outbound queue + writer thread: reply encoding and
    socket writes never run on the batcher's drain loop (future callbacks
    fire there) or block the reader."""

    def __init__(self, sock: socket.socket, name: str):
        self._sock = sock
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True, name=name)
        self._thread.start()

    def send(self, mtype: int, req_id: int, encode: Callable[[], bytes]) -> None:
        with self._cond:
            if self._closed:
                return
            self._queue.append((mtype, req_id, encode))
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                mtype, req_id, encode = self._queue.popleft()
            try:
                _send_frame(self._sock, mtype, req_id, encode())
            except Exception:  # noqa: BLE001  (dead peer: drop replies, reader cleans up)
                self.close()
                return


class _ShmWriter:
    """The shm counterpart of ``_ConnWriter``: reply encoding (native
    ``reply_pack``) and ring pushes happen on this thread, never on the
    batcher's drain loop, and the single thread keeps the s2c ring SPSC no
    matter how many device lanes settle futures concurrently. A full ring
    gets a bounded space-futex wait; a consumer that stays gone past the
    budget costs a dropped reply (the front end times out onto its oracle
    exactly as for a wedged uds socket)."""

    def __init__(self, seg: _ShmSegment, name: str, on_frame=None, on_drop=None):
        self._seg = seg
        self._on_frame = on_frame
        self._on_drop = on_drop
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True, name=name)
        self._thread.start()

    def send(self, mtype: int, req_id: int, encode: Callable[[], bytes]) -> None:
        with self._cond:
            if self._closed:
                return
            self._queue.append((mtype, req_id, encode))
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        nat = native.get()
        if nat is not None:
            try:
                nat.ring_wake(self._seg.s2c, 1)  # unblock a space wait
            except (ValueError, OSError):
                pass

    def _loop(self) -> None:
        nat = native.get()
        mv = self._seg.s2c
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                mtype, req_id, encode = self._queue.popleft()
            try:
                payload = encode()
            except Exception:  # noqa: BLE001  (unpackable reply: front end times out → oracle)
                continue
            pushed = False
            try:
                for _ in range(20):  # ~1s of space waits before dropping
                    seq = nat.ring_seq(mv, 1)
                    if nat.ring_push(mv, mtype, req_id, payload):
                        pushed = True
                        break
                    if self._closed:
                        return
                    nat.ring_wait(mv, 1, seq, 50)
            except (ValueError, OSError):
                return  # segment gone mid-teardown
            if pushed:
                if self._on_frame is not None:
                    self._on_frame(len(payload))
            elif self._on_drop is not None:
                self._on_drop()


class BatcherIpcServer:
    """The device-owning process's end of the ticket queue.

    Listens on a unix socket; each front-end process holds one connection.
    CHECK tickets decode into the shared ``BatchingEvaluator.check_async``
    queue (the same drain loop, breaker, quarantine, and deadline machinery
    as the single-process path); control frames serve the batcher's
    readiness snapshot, flight-recorder dump, and metrics text so the
    front ends can re-export them (docs/OBSERVABILITY.md).
    """

    def __init__(
        self,
        socket_path: str,
        batcher: Any,
        readiness: Optional[Callable[[], dict]] = None,
        max_outstanding: int = 4096,
        faults: Optional[dict] = None,
        transport: str = "shm",
    ):
        self.socket_path = socket_path
        self.batcher = batcher
        self.readiness = readiness
        self.max_outstanding = max(1, int(max_outstanding))
        self.faults = dict(faults or {})
        # the transport this server is WILLING to grant; a front end still
        # has to offer a segment, and either side without the native module
        # degrades the pair to uds
        self.transport = transport if transport in ("shm", "uds") else "shm"
        self._listener: Optional[socket.socket] = None
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self._outstanding = 0
        self._out_by = {"uds": 0, "shm": 0}
        self._checks_seen = 0
        self._stop = False
        self.stats = {
            "connections": 0,
            "checks": 0,
            "rejected_full": 0,
            "wedged_drops": 0,
            "shm_conns": 0,
            "reply_drops": 0,
        }
        self._init_metrics()

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_depth = reg.gauge_vec(
            "cerbos_tpu_ipc_ring_depth",
            "check tickets accepted from front ends and not yet answered",
            label="transport",
            track_max=True,
        )
        self._g_depth = {t: self.m_depth.labels(t) for t in ("uds", "shm")}
        self.m_full = reg.counter_vec(
            "cerbos_tpu_ipc_full_total",
            "tickets refused because the shared batcher queue or ring was full (front end served its oracle)",
            label="transport",
        )
        self.m_frame_bytes = reg.histogram_vec(
            "cerbos_tpu_ipc_frame_bytes",
            "check/reply frame payload sizes crossing the ticket queue",
            label=("transport", "dir"),
            buckets=[64, 128, 256, 512, 1024, 4096, 16384, 65536, 1 << 20],
        )
        self.m_enqueue = reg.histogram_vec(
            "cerbos_tpu_ipc_enqueue_seconds",
            "ticket decode + batcher enqueue latency on the batcher process, per front-end worker",
            label="worker",
            buckets=[0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05],
        )
        self.m_conns = reg.gauge(
            "cerbos_tpu_ipc_connections", "front-end processes currently attached"
        )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.socket_path)
        listener.listen(64)
        self._listener = listener
        threading.Thread(target=self._accept_loop, daemon=True, name="ipc-accept").start()

    def close(self) -> None:
        self._stop = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            self.stats["connections"] += 1
            self.m_conns.set(len(self._conns))
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True, name="ipc-conn"
            ).start()

    # -- per-connection protocol --------------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 20)
        writer = _ConnWriter(conn, "ipc-writer")
        worker = "?"
        seg: Optional[_ShmSegment] = None
        shm_writer: Optional[_ShmWriter] = None
        shm_stop = threading.Event()
        try:
            while True:
                mtype, req_id, payload = _recv_frame(conn)
                if mtype == T_HELLO:
                    hello = marshal.loads(payload)
                    worker = str(hello.get("worker", "?"))
                    grant = "uds"
                    if (
                        seg is None
                        and self.transport == "shm"
                        and hello.get("transport") == "shm"
                        and hello.get("shm_path")
                        and native.get() is not None
                    ):
                        try:
                            seg = _ShmSegment.attach(str(hello["shm_path"]))
                            grant = "shm"
                        except (IpcError, OSError, struct.error):
                            seg = None
                    if seg is not None:
                        self.stats["shm_conns"] += 1
                        shm_writer = _ShmWriter(
                            seg,
                            "ipc-shm-writer",
                            on_frame=lambda n: self.m_frame_bytes.observe(("shm", "out"), n),
                            on_drop=self._count_reply_drop,
                        )
                        threading.Thread(
                            target=self._shm_serve_loop,
                            args=(worker, seg, shm_writer, shm_stop),
                            daemon=True,
                            name="ipc-shm-serve",
                        ).start()
                    # HELLO_R must be the first frame back on this connection:
                    # the client blocks on it before sending any traffic, so
                    # the writer queue is empty here by construction
                    writer.send(T_HELLO_R, req_id, lambda g=grant: marshal.dumps({"transport": g}))
                elif mtype == T_CHECK:
                    self._handle_check(worker, req_id, payload, writer)
                elif mtype == T_STATUS:
                    snap = self._status_snapshot()
                    writer.send(T_STATUS_R, req_id, lambda s=snap: marshal.dumps(s))
                elif mtype == T_FLIGHT:
                    dump = self._flight_snapshot()
                    writer.send(T_FLIGHT_R, req_id, lambda d=dump: marshal.dumps(d))
                elif mtype == T_METRICS:
                    from ..observability import metrics

                    text = metrics().render()
                    writer.send(T_METRICS_R, req_id, lambda t=text: t.encode())
                elif mtype == T_SLOW:
                    dump = self._slow_snapshot(payload)
                    writer.send(T_SLOW_R, req_id, lambda d=dump: marshal.dumps(d))
                elif mtype == T_PRESSURE:
                    snap = self._pressure_snapshot()
                    writer.send(T_PRESSURE_R, req_id, lambda s=snap: marshal.dumps(s))
                elif mtype == T_HOTRULES:
                    snap = self._hotrules_snapshot(payload)
                    writer.send(T_HOTRULES_R, req_id, lambda s=snap: marshal.dumps(s))
        except (IpcError, OSError, EOFError, ValueError, TypeError):
            pass
        finally:
            writer.close()
            shm_stop.set()
            if shm_writer is not None:
                shm_writer.close()
            if seg is not None:
                nat = native.get()
                if nat is not None:
                    try:
                        nat.ring_wake(seg.c2s, 0)  # unblock the shm serve loop
                    except (ValueError, OSError):
                        pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            self.m_conns.set(len(self._conns))
            try:
                conn.close()
            except OSError:
                pass

    def _count_reply_drop(self) -> None:
        self.stats["reply_drops"] += 1

    def _shm_serve_loop(
        self,
        worker: str,
        seg: _ShmSegment,
        writer: _ShmWriter,
        stop: threading.Event,
    ) -> None:
        """Ticket consumer for one front end's c2s ring. The socket reader
        (`_serve_conn`) owns lifecycle: when the connection drops it sets
        ``stop`` and wakes the ring, and THIS loop must release its
        memoryview references before the segment closes under it — hence
        the stop checks on both sides of the pop."""
        nat = native.get()
        mv = seg.c2s
        try:
            while not stop.is_set():
                seq = nat.ring_seq(mv, 0)
                item = nat.ring_pop(mv)
                if item is None:
                    nat.ring_wait(mv, 0, seq, 200)
                    continue
                mtype, req_id, payload = item
                if stop.is_set():
                    return
                if mtype == T_CHECK:
                    self._handle_check(worker, req_id, payload, writer, transport="shm")
        except (ValueError, OSError):
            return  # segment torn down mid-pop
        finally:
            seg.close()

    def _wedged(self) -> bool:
        wedge_after = self.faults.get("ipc_wedge_after")
        if wedge_after is None:
            return False
        return self._checks_seen > int(wedge_after)

    def _handle_check(
        self,
        worker: str,
        req_id: int,
        payload: bytes,
        writer: Any,
        transport: str = "uds",
    ) -> None:
        t0 = time.perf_counter()
        self._checks_seen += 1
        self.stats["checks"] += 1
        self.m_frame_bytes.observe((transport, "in"), len(payload))
        if self._wedged():
            # simulated wedged ring (engine/faults.py ipc_wedge_after): the
            # ticket is swallowed whichever transport carried it; the front
            # end times out onto its oracle
            self.stats["wedged_drops"] += 1
            return
        if transport == "shm":
            # shm ERR payloads are the raw utf-8 reason (no codec at all);
            # outbound sizes are observed by the _ShmWriter push loop
            def err(reason: str) -> Callable[[], bytes]:
                return lambda r=str(reason): r.encode()

        else:

            def err(reason: str) -> Callable[[], bytes]:
                return lambda r=reason: self._sized("uds", marshal.dumps(r))

        try:
            if transport == "shm":
                nat = native.get()
                deadline_rel, traceparent, inputs, carry = nat.ticket_unpack(
                    payload, T.Principal, T.Resource, T.AuxData, T.CheckInput
                )
            else:
                decoded = marshal.loads(payload)
                deadline_rel, traceparent, rows = decoded[0], decoded[1], decoded[2]
                # 4th element: latency-budget carry spec (age, attributed) —
                # absent from pre-waterfall front ends, None when disabled
                carry = decoded[3] if len(decoded) > 3 else None
                inputs = decode_inputs(rows)
        except Exception:  # noqa: BLE001
            writer.send(T_ERR, req_id, err("codec"))
            return
        with self._lock:
            if self._outstanding >= self.max_outstanding:
                full = True
            else:
                full = False
                self._outstanding += 1
                self._out_by[transport] += 1
                depth = self._out_by[transport]
        if full:
            # counted ONCE per pool, in the front end that receives this ERR
            # (RemoteBatcherClient incs its m_full on the remote-origin
            # reason): a merged scrape across the worker pool must not see
            # the same refusal from both sides of the socket
            self.stats["rejected_full"] += 1
            writer.send(T_ERR, req_id, err("ipc_full"))
            return
        self._g_depth[transport].set(depth)
        deadline = time.monotonic() + deadline_rel if deadline_rel is not None else None
        ctx = parse_traceparent(traceparent) if traceparent else None
        # 3rd carry element: the admission priority class (absent from
        # pre-overload front ends; (None, None, pclass) when the waterfall
        # is off but a class rides along)
        pclass = None
        if carry is not None and len(carry) > 2:
            pclass = str(carry[2]) if carry[2] else None
            carry = carry[:2] if carry[0] is not None else None
        # rebuild the waterfall from the carried relative spec; the
        # unattributed remainder (encode + ring/socket + decode) books as
        # transit
        wf = budget_tracker().resume(
            carry, trace_id=getattr(ctx, "trace_id", "") or "", deadline=deadline
        )
        fut = self.batcher.check_async(
            inputs, deadline=deadline, ctx=ctx, wf=wf, pclass=pclass
        )
        self.m_enqueue.observe(worker, time.perf_counter() - t0)

        def settle(f: Future) -> None:
            with self._lock:
                self._outstanding -= 1
                self._out_by[transport] -= 1
                depth = self._out_by[transport]
            self._g_depth[transport].set(depth)
            try:
                outs = f.result()
            except DeadlineExceeded:
                writer.send(T_ERR, req_id, err("deadline"))
            except _BatchFailed as e:
                writer.send(T_ERR, req_id, err(e.reason))
            except BaseException as e:  # noqa: BLE001
                writer.send(T_ERR, req_id, err(f"batch_error:{type(e).__name__}"))
            else:
                # reply spec is snapshotted here (the drain thread is done
                # with the record); writer-queue time lands in the front
                # end's ipc_return residual. Encode runs on the writer
                # thread, not here (the callback fires on the batcher drain
                # loop, which must stay hot).
                spec = wf.reply_spec() if wf is not None else None
                if transport == "shm":
                    writer.send(
                        T_RESULT,
                        req_id,
                        lambda o=outs, s=spec: native.get().reply_pack(o, s),
                    )
                else:
                    writer.send(
                        T_RESULT,
                        req_id,
                        lambda o=outs, s=spec: self._sized(
                            "uds", marshal.dumps((encode_outputs(o), s))
                        ),
                    )

        fut.add_done_callback(settle)

    def _sized(self, transport: str, data: bytes) -> bytes:
        self.m_frame_bytes.observe((transport, "out"), len(data))
        return data

    def _status_snapshot(self) -> dict:
        snap: dict = {"pid": os.getpid()}
        if self.readiness is not None:
            try:
                snap.update(self.readiness())
            except Exception:  # noqa: BLE001
                snap.setdefault("status", "ready")
        else:
            snap["status"] = "ready"
        health = getattr(self.batcher, "health", None)
        if health is not None:
            snap["breaker"] = health.state
        stats = getattr(self.batcher, "stats", None)
        if isinstance(stats, dict):
            snap["batcher_stats"] = dict(stats)
        snap["ipc"] = dict(self.stats)
        return snap

    def _flight_snapshot(self) -> dict:
        from .flight import recorder

        out = {"flight": recorder().dump(), "pid": os.getpid()}
        try:
            from ..tpu import jitcache

            out["jitcache"] = jitcache.status()
        except Exception:  # noqa: BLE001
            pass
        return out

    def _slow_snapshot(self, payload: bytes) -> dict:
        """Slow-request ring dump for `/_cerbos/debug/slow` on a front end
        (the ring lives here, where requests actually settle)."""
        shard = None
        try:
            args = marshal.loads(payload) if payload else {}
            if isinstance(args, dict) and args.get("shard") is not None:
                shard = int(args["shard"])
        except Exception:  # noqa: BLE001
            pass
        out = budget_tracker().slow_dump(shard=shard)
        out["pid"] = os.getpid()
        return out

    def _pressure_snapshot(self) -> dict:
        from .pressure import monitor

        try:
            out = monitor().sample()
        except Exception:  # noqa: BLE001
            out = {"score": 0.0, "components": {}}
        out["pid"] = os.getpid()
        return out

    def _hotrules_snapshot(self, payload: bytes) -> dict:
        """Hot-rule heatmap for `/_cerbos/debug/hotrules` on a front end:
        the hit array aggregates in this (batcher) process, where decisions
        settle; rule labels resolve against the batcher's current table."""
        from .hotrules import recorder as hotrule_recorder

        k = 20
        try:
            args = marshal.loads(payload) if payload else {}
            if isinstance(args, dict) and args.get("k"):
                k = int(args["k"])
        except Exception:  # noqa: BLE001
            pass
        rt = getattr(getattr(self.batcher, "evaluator", None), "rule_table", None)
        out = hotrule_recorder().snapshot(k=k, rule_table=rt)
        out["pid"] = os.getpid()
        return out


# -- front-end client --------------------------------------------------------


class RemoteBatcherClient:
    """``Engine.check()``-compatible evaluator that forwards to the shared
    batcher process, with the PR 3 degradation ladder preserved end to end:
    deadline propagation (as relative remaining time), ERR fast paths and
    timeouts falling back to this process's COW-shared CPU oracle, and a
    background reconnect loop so a respawned batcher picks traffic back up
    without restarting the front end.

    Also exposes ``check_await`` — the asyncio-native path the HTTP front
    end uses to await tickets directly on the event loop, with no
    thread-pool hop per request (the single biggest per-call overhead the
    multi-process front door removes on small hosts).
    """

    supports_deadline = True
    supports_waterfall = True
    supports_pclass = True

    def __init__(
        self,
        socket_path: str,
        rule_table: Any,
        schema_mgr: Any = None,
        params: Optional[T.EvalParams] = None,
        request_timeout_s: float = 30.0,
        worker_label: str = "fe",
        status_poll_s: float = 0.5,
        connect_retry_s: float = 0.25,
        transport: str = "shm",
        ring_kib: int = 1024,
    ):
        self.socket_path = socket_path
        self.rule_table = rule_table
        self.schema_mgr = schema_mgr
        self.params = params or T.EvalParams()
        self.request_timeout = request_timeout_s
        self.worker_label = worker_label
        self.status_poll_s = status_poll_s
        self.connect_retry_s = connect_retry_s
        # requested transport; the ACTIVE one is renegotiated per attach
        # (native module present on both ends, server willing) and visible
        # as .transport for bench/loadtest reporting
        self.transport_requested = transport if transport in ("shm", "uds") else "shm"
        self.ring_bytes = max(64 * 1024, int(ring_kib) * 1024)
        self._transport_active = "uds"
        self._shm: Optional[_ShmSegment] = None
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._next_id = 0
        self._connected = threading.Event()
        self._ever_ready = False
        self._last_status: Optional[dict] = None
        self._stop = False
        self.stats = {
            "oracle_fallbacks": 0,
            "reconnects": 0,
            "checks": 0,
            "enc_ns": 0,
            "enc_frames": 0,
            "dec_ns": 0,
            "dec_frames": 0,
            "ring_full": 0,
        }
        self._init_metrics()
        self._conn_thread = threading.Thread(
            target=self._connection_loop, daemon=True, name="ipc-client"
        )
        self._conn_thread.start()
        self._status_thread = threading.Thread(
            target=self._status_loop, daemon=True, name="ipc-client-status"
        )
        self._status_thread.start()

    @property
    def transport(self) -> str:
        """The data plane actually carrying tickets right now."""
        return self._transport_active if self._connected.is_set() else "none"

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_rtt = reg.histogram_vec(
            "cerbos_tpu_ipc_client_rtt_seconds",
            "front-end round trip through the shared batcher (encode to decode)",
            label="transport",
            buckets=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0],
        )
        self.m_reconnects = reg.counter_vec(
            "cerbos_tpu_ipc_client_reconnects_total",
            "times the front end (re)attached to the shared batcher, by granted transport",
            label="transport",
        )
        # shares the server's family name, but ALL full refusals are counted
        # here: local ring-full pushes directly, and batcher queue-full
        # refusals when their remote-origin "ipc_full" ERR lands. One
        # decisions view per worker — a merged scrape never double-counts a
        # refusal that crossed the socket
        self.m_full = reg.counter_vec(
            "cerbos_tpu_ipc_full_total",
            "tickets refused because the shared batcher queue or ring was full (front end served its oracle)",
            label="transport",
        )
        # same family the in-process batcher exports, so existing fallback
        # dashboards keep working against front-end processes
        self.m_fallbacks = reg.counter_vec(
            "cerbos_tpu_batcher_oracle_fallbacks_total",
            "requests served from the CPU oracle instead of the device path, by reason",
            label="reason",
        )
        # rollout visibility (engine/rollout.py): the batcher's committed
        # epoch as observed from this front end, and how long each cutover
        # took to become visible here — the "bounded, measured skew window"
        # the epoch design promises. Same family names the device-owning
        # process exports, so a merged scrape tells the fleet-wide story.
        self.m_policy_epoch = reg.gauge(
            "cerbos_tpu_policy_epoch",
            "policy epoch currently serving (monotone except across a rollback)",
        )
        self.m_epoch_skew = reg.gauge(
            "cerbos_tpu_policy_epoch_skew_seconds",
            "delay between the batcher committing a policy epoch and this front end observing it",
        )
        self._epoch_seen: Optional[int] = None

    # -- connection management ----------------------------------------------

    def _connection_loop(self) -> None:
        while not self._stop:
            # until the FIRST attach succeeds, retry fast: at boot the
            # batcher's listen() and this loop race, and a front end that
            # loses by a millisecond must not serve warming 503s for a
            # full steady-state retry period after its HTTP listener opens
            retry_s = self.connect_retry_s if self.stats["reconnects"] else min(
                0.025, self.connect_retry_s
            )
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(self.socket_path)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                time.sleep(retry_s)
                continue
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 20)
            seg: Optional[_ShmSegment] = None
            hello = {"worker": self.worker_label, "pid": os.getpid()}
            if self.transport_requested == "shm" and native.get() is not None:
                try:
                    seg = _ShmSegment.create(self.worker_label, self.ring_bytes)
                    hello.update(
                        {"transport": "shm", "shm_path": seg.path, "ring_bytes": self.ring_bytes}
                    )
                except (IpcError, OSError):
                    seg = None  # no /dev/shm headroom etc.: run uds
            granted = "uds"
            try:
                _send_frame(sock, T_HELLO, 0, marshal.dumps(hello))
                # synchronous handshake: HELLO_R is the first frame the
                # server sends on a connection, so a blocking read here
                # races nothing — and no traffic may enter either plane
                # until the grant decides which one carries it
                sock.settimeout(5.0)
                try:
                    mtype, _, payload = _recv_frame(sock)
                finally:
                    sock.settimeout(None)
                if mtype == T_HELLO_R:
                    granted = str(marshal.loads(payload).get("transport", "uds"))
            except (IpcError, OSError, socket.timeout, ValueError, TypeError, EOFError):
                if seg is not None:
                    seg.unlink()
                    seg.close()
                try:
                    sock.close()
                except OSError:
                    pass
                time.sleep(retry_s)
                continue
            if seg is not None:
                # the name has served its purpose: both ends hold the
                # mapping (or the grant fell back) — unlink so a SIGKILL on
                # either side cannot leak segments into /dev/shm
                seg.unlink()
                if granted != "shm":
                    seg.close()
                    seg = None
            shm_stop = threading.Event()
            shm_thread: Optional[threading.Thread] = None
            if seg is not None:
                shm_thread = threading.Thread(
                    target=self._shm_read_loop,
                    args=(seg, shm_stop),
                    daemon=True,
                    name="ipc-client-shm",
                )
            self._shm = seg
            self._transport_active = "shm" if seg is not None else "uds"
            self._sock = sock
            if shm_thread is not None:
                shm_thread.start()
            self._connected.set()
            self.stats["reconnects"] += 1
            self.m_reconnects.inc(self._transport_active)
            _log.info(
                "attached to shared batcher at %s (transport=%s)",
                self.socket_path,
                self._transport_active,
            )
            try:
                self._read_loop(sock)
            except (IpcError, OSError):
                pass
            finally:
                self._connected.clear()
                self._sock = None
                self._shm = None
                shm_stop.set()
                if seg is not None:
                    nat = native.get()
                    if nat is not None:
                        try:
                            nat.ring_wake(seg.s2c, 0)  # unblock the shm reader
                        except (ValueError, OSError):
                            pass
                    if shm_thread is not None:
                        shm_thread.join(timeout=2.0)
                    seg.close()
                try:
                    sock.close()
                except OSError:
                    pass
                self._fail_all_pending(IpcDisconnected("shared batcher connection lost"))
                if not self._stop:
                    _log.warning(
                        "shared batcher connection lost; serving from the CPU oracle "
                        "until it returns"
                    )
            time.sleep(self.connect_retry_s)

    def _read_loop(self, sock: socket.socket) -> None:
        while True:
            mtype, req_id, payload = _recv_frame(sock)
            self._settle_frame(mtype, req_id, payload)

    def _shm_read_loop(self, seg: _ShmSegment, stop: threading.Event) -> None:
        """Reply consumer for the s2c ring: pops RESULT/ERR frames and
        settles the matching futures, exactly as ``_read_loop`` does for
        socket frames. Liveness still belongs to the socket — a dead
        batcher is noticed there, and the connection loop wakes this thread
        to exit before closing the segment under it."""
        nat = native.get()
        mv = seg.s2c
        try:
            while not stop.is_set():
                seq = nat.ring_seq(mv, 0)
                item = nat.ring_pop(mv)
                if item is None:
                    nat.ring_wait(mv, 0, seq, 200)
                    continue
                self._settle_frame(*item)
        except (ValueError, OSError):
            return  # segment torn down mid-pop

    def _settle_frame(self, mtype: int, req_id: int, payload: bytes) -> None:
        with self._plock:
            fut = self._pending.pop(req_id, None)
        if fut is None:
            return  # abandoned (timed-out) ticket: drop the late reply
        try:
            if fut.set_running_or_notify_cancel():
                fut.set_result((mtype, payload))
        except Exception:  # noqa: BLE001
            pass

    def _fail_all_pending(self, err: Exception) -> None:
        with self._plock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            try:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(err)
            except Exception:  # noqa: BLE001
                pass

    def _status_loop(self) -> None:
        while not self._stop:
            if not self._connected.is_set():
                # block on the attach event rather than sleeping a full
                # steady-state period: front-end readiness hinges on the
                # first status frame, so a boot-order race between the
                # batcher's listen() and this loop must not cost 500ms
                self._connected.wait(timeout=self.status_poll_s)
                if self._stop:
                    return
            if self._connected.is_set():
                try:
                    mtype, payload = self._request(T_STATUS, b"", timeout=2.0)
                    if mtype == T_STATUS_R:
                        snap = marshal.loads(payload)
                        self._last_status = snap
                        if snap.get("status") in ("ready", "degraded"):
                            self._ever_ready = True
                        self._note_epoch(snap)
                except (IpcError, OSError, FutureTimeoutError, TimeoutError, ValueError):
                    pass
            # fast cadence until the first frame lands, configured cadence after
            time.sleep(self.status_poll_s if self._last_status is not None else 0.05)

    def _note_epoch(self, snap: dict) -> None:
        """Track the batcher's committed epoch as it becomes visible here.
        The skew gauge is measured on the observing edge: wall-clock now
        minus the commit timestamp the STATUS frame carried — bounded by
        the status poll cadence plus the cutover itself."""
        epoch = snap.get("policy_epoch")
        if epoch is None:
            return
        try:
            self.m_policy_epoch.set(epoch)
            if epoch != self._epoch_seen:
                self._epoch_seen = epoch
                committed_at = snap.get("policy_epoch_committed_at")
                if committed_at:
                    self.m_epoch_skew.set(max(0.0, time.time() - float(committed_at)))
        except Exception:  # noqa: BLE001 — status bookkeeping never kills the poll loop
            pass

    # -- raw request/response -----------------------------------------------

    def _register(self) -> tuple[int, Future]:
        with self._plock:
            self._next_id += 1
            req_id = self._next_id
            fut: Future = Future()
            self._pending[req_id] = fut
        return req_id, fut

    def _unregister(self, req_id: int) -> None:
        with self._plock:
            self._pending.pop(req_id, None)

    def _send(self, mtype: int, req_id: int, payload: bytes) -> None:
        sock = self._sock
        if sock is None:
            raise IpcDisconnected("not attached to the shared batcher")
        try:
            with self._send_lock:
                _send_frame(sock, mtype, req_id, payload)
        except OSError as e:
            raise IpcDisconnected(str(e)) from e

    def _request(self, mtype: int, payload: bytes, timeout: float) -> tuple[int, bytes]:
        req_id, fut = self._register()
        try:
            self._send(mtype, req_id, payload)
            return fut.result(timeout=timeout)
        finally:
            self._unregister(req_id)

    # -- oracle fallback ----------------------------------------------------

    def _serve_oracle(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams],
        reason: str,
        wf: Optional[Waterfall] = None,
    ) -> list[T.CheckOutput]:
        self.stats["oracle_fallbacks"] += 1
        self.m_fallbacks.inc(reason)
        if wf is not None:
            wf.note_fallback(reason)
        p = params or self.params
        # single table read per request; the local COW table is never epoch-
        # committed (the batcher owns epoch authority), so local fallbacks
        # stamp None — honestly unversioned — rather than a guessed epoch
        rt = self.rule_table
        T.set_current_epoch(getattr(rt, "policy_epoch", None))
        out = [check_input(rt, i, p, self.schema_mgr) for i in inputs]
        if wf is not None:
            # books everything since the last mark — including any dead
            # round trip that preceded the fallback — as the oracle stage
            wf.mark(STAGE_ORACLE)
        return out

    # -- check surface ------------------------------------------------------

    @staticmethod
    def _carry_spec(
        wf: Optional[Waterfall], pclass: Optional[str]
    ) -> Optional[tuple]:
        """The ticket's carry: (age, attributed) from the waterfall, plus
        the admission priority class as an optional 3rd element. A class
        with no waterfall ships ``(None, None, pclass)`` — the batcher reads
        the class and resumes no budget record."""
        carry = wf.carry() if wf is not None else None
        if pclass:
            return (carry[0], carry[1], pclass) if carry is not None else (None, None, pclass)
        return carry

    def _encode_check(
        self,
        inputs: Sequence[T.CheckInput],
        deadline: Optional[float],
        wf: Optional[Waterfall] = None,
        transport: str = "uds",
        pclass: Optional[str] = None,
    ) -> Optional[bytes]:
        deadline_rel = None
        if deadline is not None:
            deadline_rel = max(0.0, deadline - time.monotonic())
        ctx = current_span_context()
        traceparent = ctx.to_traceparent() if ctx is not None else ""
        try:
            if transport == "shm":
                # the native pack runs AFTER the carry snapshot (the carry
                # rides inside the frame), so its cost books into the
                # batcher's transit stage — transit genuinely is
                # "pack + ring + unpack" on this plane, and ipc_encode
                # shrinks to the admission bookkeeping above it
                if wf is not None:
                    wf.mark(STAGE_IPC_ENCODE)
                carry = self._carry_spec(wf, pclass)
                t0 = time.perf_counter_ns()
                frame = native.get().ticket_pack(inputs, deadline_rel, traceparent, carry)
                self.stats["enc_ns"] += time.perf_counter_ns() - t0
                self.stats["enc_frames"] += 1
                return frame
            t0 = time.perf_counter_ns()
            rows = encode_inputs(inputs)
            # book the row conversion as ipc_encode BEFORE taking the carry
            # spec, so the batcher's transit stage (age-at-receipt minus
            # attributed-at-carry) covers only marshal + socket + decode and
            # never double-counts the encode
            if wf is not None:
                wf.mark(STAGE_IPC_ENCODE)
            carry = self._carry_spec(wf, pclass)
            frame = marshal.dumps((deadline_rel, traceparent, rows, carry))
            self.stats["enc_ns"] += time.perf_counter_ns() - t0
            self.stats["enc_frames"] += 1
            return frame
        except Exception:  # noqa: BLE001  (unencodable attr value: oracle handles it)
            return None

    def _send_check(self, req_id: int, payload: bytes, transport: str) -> bool:
        """Dispatch one CHECK ticket on the active plane. Returns False when
        the shm ring stayed full through the bounded space wait — the caller
        serves its oracle under the ``ipc_full`` reason, the same degradation
        the batcher signals for a full admission queue."""
        if transport != "shm":
            self._send(T_CHECK, req_id, payload)
            return True
        seg = self._shm
        nat = native.get()
        if seg is None or nat is None:
            raise IpcDisconnected("shm plane detached")
        try:
            mv = seg.c2s
            for _ in range(3):  # immediate try + two bounded space waits
                seq = nat.ring_seq(mv, 1)
                if nat.ring_push(mv, T_CHECK, req_id, payload):
                    return True
                nat.ring_wait(mv, 1, seq, 50)
            self.stats["ring_full"] += 1
            self.m_full.inc("shm")
            return False
        except ValueError:
            # frame larger than the ring, or segment torn down mid-push:
            # either way this ticket cannot cross — the oracle serves it
            self.stats["ring_full"] += 1
            self.m_full.inc("shm")
            return False

    def _wait_budget(self, deadline: Optional[float]) -> float:
        wait = self.request_timeout
        if deadline is not None:
            wait = min(wait, max(0.0, deadline - time.monotonic()))
        return wait

    def _decode_result(
        self, payload: bytes, wf: Optional[Waterfall], transport: str = "uds"
    ) -> list[T.CheckOutput]:
        # the batcher evaluated this ticket under its current epoch; the
        # nearest view this side of the socket is the last STATUS frame —
        # exact to within the measured skew window the epoch gauges expose
        last = self._last_status
        if last is not None:
            T.set_current_epoch(last.get("policy_epoch"))
        t0 = time.perf_counter_ns()
        if transport == "shm":
            outs, spec = native.get().reply_unpack(
                payload, T.CheckOutput, T.ActionEffect, T.ValidationError, T.OutputEntry
            )
        else:
            obj = marshal.loads(payload)
            if isinstance(obj, tuple):
                rows, spec = obj
            else:  # pre-waterfall batcher: bare row list
                rows, spec = obj, None
            outs = decode_outputs(rows)
        self.stats["dec_ns"] += time.perf_counter_ns() - t0
        self.stats["dec_frames"] += 1
        if wf is not None and spec is not None:
            try:
                wf.splice_reply(spec)
            except Exception:  # noqa: BLE001 — a malformed spec must not fail the request
                pass
        return outs

    @staticmethod
    def _err_reason(payload: bytes, transport: str) -> str:
        if transport == "shm":
            return payload.decode("utf-8", "replace")
        return str(marshal.loads(payload))

    def _remote_err(
        self, reason: str, transport: str, pclass: Optional[str]
    ) -> None:
        """Shared handling for remote-origin ERR reasons that do NOT fall
        back to the oracle. ``queue_budget`` is a true refusal — the lane's
        queue budget said no — raised to the server layer, which maps it to
        429/RESOURCE_EXHAUSTED and books ``outcome=refused`` in THIS
        worker's decisions view. A remote ``ipc_full`` counts against the
        shared family here (the batcher only tallies its internal
        ``rejected_full`` stat)."""
        if reason == "deadline":
            raise DeadlineExceeded("request deadline expired in the shared batcher")
        if reason == "queue_budget":
            raise OverloadRefused(pclass or "default", "queue_budget", retry_after=0.1)
        if reason == "ipc_full":
            self.m_full.inc(transport)

    def _settle_reply(
        self,
        mtype: int,
        payload: bytes,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams],
        wf: Optional[Waterfall] = None,
        transport: str = "uds",
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        if mtype == T_RESULT:
            return self._decode_result(payload, wf, transport)
        if mtype == T_ERR:
            reason = self._err_reason(payload, transport)
            self._remote_err(reason, transport, pclass)
            return self._serve_oracle(inputs, params, reason, wf=wf)
        return self._serve_oracle(inputs, params, "protocol", wf=wf)

    def check(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Waterfall] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        if deadline is not None and time.monotonic() >= deadline:
            raise DeadlineExceeded("request deadline expired before evaluation")
        self.stats["checks"] += 1
        if not self._connected.is_set():
            return self._serve_oracle(inputs, params, "batcher_down", wf=wf)
        # pin the plane for this request: a reconnect mid-flight may
        # renegotiate, but reconnects also fail every pending future, so a
        # reply never arrives encoded for a different transport than pinned
        tr = self._transport_active
        payload = self._encode_check(inputs, deadline, wf=wf, transport=tr, pclass=pclass)
        if payload is None:
            return self._serve_oracle(inputs, params, "codec", wf=wf)
        t0 = time.perf_counter()
        req_id, fut = self._register()
        try:
            if not self._send_check(req_id, payload, tr):
                self._unregister(req_id)
                return self._serve_oracle(inputs, params, "ipc_full", wf=wf)
            mtype, data = fut.result(timeout=self._wait_budget(deadline))
        except IpcDisconnected:
            self._unregister(req_id)
            return self._serve_oracle(inputs, params, "batcher_down", wf=wf)
        except (TimeoutError, FutureTimeoutError):
            self._unregister(req_id)
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded("request deadline expired while queued") from None
            return self._serve_oracle(inputs, params, "ipc_timeout", wf=wf)
        self._unregister(req_id)
        self.m_rtt.observe(tr, time.perf_counter() - t0)
        return self._settle_reply(
            mtype, data, inputs, params, wf=wf, transport=tr, pclass=pclass
        )

    async def check_await(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Waterfall] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        """Event-loop-native check: awaits the reply future with zero
        thread-pool hops; only degraded-path oracle work leaves the loop."""
        loop = asyncio.get_running_loop()

        def oracle(reason: str):
            return loop.run_in_executor(
                None, self._serve_oracle, list(inputs), params, reason, wf
            )

        if deadline is not None and time.monotonic() >= deadline:
            raise DeadlineExceeded("request deadline expired before evaluation")
        self.stats["checks"] += 1
        if not self._connected.is_set():
            return await oracle("batcher_down")
        tr = self._transport_active
        payload = self._encode_check(inputs, deadline, wf=wf, transport=tr, pclass=pclass)
        if payload is None:
            return await oracle("codec")
        t0 = time.perf_counter()
        req_id, fut = self._register()
        try:
            if not self._send_check(req_id, payload, tr):
                self._unregister(req_id)
                return await oracle("ipc_full")
            mtype, data = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=self._wait_budget(deadline)
            )
        except IpcDisconnected:
            self._unregister(req_id)
            return await oracle("batcher_down")
        except asyncio.TimeoutError:
            self._unregister(req_id)
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded("request deadline expired while queued") from None
            return await oracle("ipc_timeout")
        self._unregister(req_id)
        self.m_rtt.observe(tr, time.perf_counter() - t0)
        if mtype == T_RESULT:
            return self._decode_result(data, wf, tr)
        if mtype == T_ERR:
            reason = self._err_reason(data, tr)
            self._remote_err(reason, tr, pclass)
            return await oracle(reason)
        return await oracle("protocol")

    # -- pool observability surfaces ----------------------------------------

    def transport_stats(self) -> dict:
        """The ``transport`` block loadtest/bench report: which plane carried
        tickets, frame counts, and mean encode/decode ns per frame."""
        s = self.stats
        return {
            "transport": self.transport,
            "requested": self.transport_requested,
            "ring_kib": self.ring_bytes // 1024,
            "frames_out": s["enc_frames"],
            "frames_in": s["dec_frames"],
            "encode_ns_per_frame": (s["enc_ns"] // s["enc_frames"]) if s["enc_frames"] else 0,
            "decode_ns_per_frame": (s["dec_ns"] // s["dec_frames"]) if s["dec_frames"] else 0,
            "ring_full_events": s["ring_full"],
        }

    def remote_status(self) -> dict:
        """Front-end readiness provider (engine/readiness.bind_remote):

        - ``warming`` until the shared batcher has reported SERVING once
          (its PR 5 warmup pre-compiles gate the whole pool's readiness);
        - the batcher's own status (``ready``/``degraded``) while attached;
        - ``degraded`` — live, oracle-serving — when the batcher is down or
          re-warming after a respawn: a once-ready pool never 503s again.
        """
        last = self._last_status
        if self._connected.is_set() and last is not None:
            st = str(last.get("status", "ready"))
            if st in ("ready", "degraded"):
                return {**last, "status": st, "attached": True}
            if not self._ever_ready:
                return {**last, "status": "warming", "attached": True}
            return {**last, "status": "degraded", "attached": True}
        if not self._ever_ready:
            return {"status": "warming", "attached": False}
        return {"status": "degraded", "attached": False}

    def fetch_flight(self, timeout: float = 5.0) -> dict:
        """The PR 4 debug surface under the new topology: the flight
        recorder lives in the batcher process; front ends fetch its dump."""
        mtype, payload = self._request(T_FLIGHT, b"", timeout=timeout)
        if mtype != T_FLIGHT_R:
            raise IpcError("unexpected reply to flight request")
        return marshal.loads(payload)

    def fetch_slow(self, shard: Optional[int] = None, timeout: float = 5.0) -> dict:
        """Slow-request ring dump from the batcher process — requests settle
        there, so that is where the ring fills."""
        payload = marshal.dumps({"shard": shard} if shard is not None else {})
        mtype, data = self._request(T_SLOW, payload, timeout=timeout)
        if mtype != T_SLOW_R:
            raise IpcError("unexpected reply to slow-ring request")
        return marshal.loads(data)

    def fetch_pressure(self, timeout: float = 5.0) -> dict:
        """Pressure snapshot from the batcher process (queue, inflight, and
        breaker signals live there; the front end has only its own view)."""
        mtype, data = self._request(T_PRESSURE, b"", timeout=timeout)
        if mtype != T_PRESSURE_R:
            raise IpcError("unexpected reply to pressure request")
        return marshal.loads(data)

    def fetch_hotrules(self, k: int = 20, timeout: float = 5.0) -> dict:
        """Hot-rule heatmap from the batcher process — the hit array
        aggregates there, where decisions settle (ISSUE 20)."""
        payload = marshal.dumps({"k": int(k)})
        mtype, data = self._request(T_HOTRULES, payload, timeout=timeout)
        if mtype != T_HOTRULES_R:
            raise IpcError("unexpected reply to hotrules request")
        return marshal.loads(data)

    def fetch_metrics_text(self, timeout: float = 5.0) -> str:
        mtype, payload = self._request(T_METRICS, b"", timeout=timeout)
        if mtype != T_METRICS_R:
            raise IpcError("unexpected reply to metrics request")
        return payload.decode()

    def refresh_table(self, rule_table: Any) -> None:
        """Policy-reload hook: keep the local oracle on the latest table."""
        self.rule_table = rule_table

    def close(self) -> None:
        self._stop = True
        self._connected.clear()
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._fail_all_pending(IpcDisconnected("client closed"))


def default_socket_path(config_val: str = "") -> str:
    """Socket path resolution: config wins; otherwise a per-pool temp path
    keyed by the supervisor pid (two pools on one host must not collide)."""
    if config_val:
        return config_val
    import tempfile

    return os.path.join(tempfile.gettempdir(), f"cerbos-tpu-batcher-{os.getpid()}.sock")
