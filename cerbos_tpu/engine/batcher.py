"""Request micro-batcher: packs concurrent requests into streamed device batches.

The north-star BatchEvaluator (BASELINE.json): the reference fans requests
onto a goroutine pool (engine.go:74-144); here concurrent CheckResources
calls enqueue and a batcher thread drains them into padded device batches.
Requests block on a future and get their slice of the batch output back.

The batcher drives the evaluator's STREAMING pipeline, not its blocking
``check()``: each drained group is queued on the device via ``submit()``
(async dispatch — the call returns before the device runs) and its ticket
joins an in-flight window of up to ``max_inflight`` batches. While earlier
tickets' transfers + compute are in flight, the batcher keeps draining and
submitting newer requests; ``collect()`` settles each ticket's futures as
its results land. Wall-clock under concurrent load approaches
max(host pack/assembly, device work) instead of their sum — the same
double-buffering bench.py measures, now on the serving path.

The device path is a supervised fault domain (docs/ROBUSTNESS.md):

- a ``DeviceHealth`` breaker routes ``check()`` straight to the CPU oracle
  while open (no request ever waits out the future timeout against a dead
  device) and re-closes via background probe batches;
- a failed device batch is never surfaced to its co-batched requests:
  each waiter re-serves its own inputs from the oracle, and the group is
  bisected off-path to find and quarantine the poison input;
- per-request deadlines ride in ``_Pending`` and expire at drain time with
  ``DeadlineExceeded`` instead of spending device work on dead requests;
- a dead drain loop fails fast: waiters are settled immediately and new
  requests take the oracle, instead of hanging until timeout forever.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..observability import SpanContext, export_span, start_span
from ..ruletable import check_input
from . import types as T
from .admission import OverloadRefused
from .budget import (
    POINT_DEVICE_SUBMIT,
    POINT_ENQUEUE,
    STAGE_ADMISSION,
    STAGE_COLLECT,
    STAGE_DEVICE,
    STAGE_ORACLE,
    STAGE_PACK,
    STAGE_QUEUE_WAIT,
    STAGE_SETTLE,
    Waterfall,
)
from . import hotrules
from .budget import tracker as budget_tracker
from .flight import recorder as flight_recorder
from .health import DeviceHealth  # noqa: F401  (re-exported for wiring/tests)

_log = logging.getLogger("cerbos_tpu.engine.batcher")


class DeadlineExceeded(Exception):
    """The request's deadline expired before a decision was produced.

    Maps to gRPC DEADLINE_EXCEEDED / HTTP 504 at the server layer."""


class _BatchFailed(Exception):
    """Internal: the device batch carrying this request failed. The waiting
    ``check()`` thread catches this and re-serves its own inputs from the
    CPU oracle — co-batched requests each recover independently instead of
    all erroring together."""

    def __init__(self, cause: Optional[BaseException], reason: str = "batch_error"):
        super().__init__(reason)
        self.cause = cause
        self.reason = reason


@dataclass
class _Pending:
    inputs: list[T.CheckInput]
    params: Optional[T.EvalParams]
    future: Future
    enqueued_at: float = field(default_factory=time.perf_counter)
    deadline: Optional[float] = None  # absolute time.monotonic() deadline
    # the request's span context, detached on the request thread so the
    # batcher drain thread can parent/link device-batch spans into the
    # request's trace (span parenting via observability._current is
    # thread-local and dies at this hop otherwise)
    ctx: Optional[SpanContext] = None
    # the request's latency-budget waterfall (engine/budget.py); like ctx it
    # migrates with the request across the thread hop, and the drain thread
    # books queue_wait/pack/device/collect/settle into it at settle time
    wf: Optional[Waterfall] = None
    # admission priority class ('' = unclassified → the default lane);
    # selects the weighted priority lane this request queues in
    pclass: str = ""
    # "check" pendings carry CheckInputs for the device evaluator; "plan"
    # pendings carry PlanInputs for the attached batched planner and ride
    # the dedicated low-priority plan lane
    kind: str = "check"
    # the policy epoch this request's batch was submitted under — assigned
    # on the drain thread at submit time (happens-before the future
    # resolves), read back on the request thread to stamp the decision
    epoch: Optional[int] = None


class _Lane:
    """One priority lane: a FIFO deque plus its scheduling parameters."""

    __slots__ = ("name", "priority", "weight", "budget", "q", "credit")

    def __init__(self, name: str, priority: int = 0, weight: int = 1, budget: int = 0):
        self.name = name
        self.priority = int(priority)          # lower preempts
        self.weight = max(1, int(weight))      # fair share within a band
        self.budget = max(0, int(budget))      # max queued; 0 = unlimited
        self.q: deque[_Pending] = deque()
        self.credit = 0.0                      # smooth-WRR accumulator


class _PriorityLanes:
    """Weighted priority lanes over the pending queue.

    Selection is strict priority across bands (the lowest ``priority``
    value with work wins — interactive traffic preempts bulk outright at
    overload, which is the point) and smooth weighted round-robin within a
    band (deterministic nginx-style credit counters, no RNG). Per-class
    queue budgets bound each lane so one class's backlog cannot starve the
    ring for everyone else.

    Unconfigured, everything rides one default lane — byte-for-byte the
    old FIFO behavior. Every method runs under the batcher lock; ``peek``
    and ``popleft`` agree because ``_pick`` is pure and nothing interleaves
    between them.
    """

    __slots__ = ("_lanes", "_order", "_default", "_len")

    def __init__(self):
        self._default = _Lane("default")
        self._lanes: dict[str, _Lane] = {"default": self._default}
        self._order: list[_Lane] = [self._default]
        self._len = 0

    def configure(self, lane_confs) -> None:
        """Rebuild lanes from (name, priority, weight, budget) tuples;
        anything already queued migrates into the new lanes."""
        queued = list(self)
        lanes: dict[str, _Lane] = {}
        order: list[_Lane] = []
        default: Optional[_Lane] = None
        for name, priority, weight, budget in lane_confs or ():
            lane = _Lane(str(name), priority, weight, budget)
            lanes[lane.name] = lane
            order.append(lane)
            if lane.name == "default":
                default = lane
        if default is None:
            default = _Lane("default", priority=1)
            lanes["default"] = default
            order.append(default)
        self._lanes, self._order, self._default = lanes, order, default
        self._len = 0
        for p in queued:
            self.append(p)

    def _lane(self, pclass: str) -> _Lane:
        return self._lanes.get(pclass or "default", self._default)

    def over_budget(self, pclass: str) -> bool:
        lane = self._lane(pclass)
        return lane.budget > 0 and len(lane.q) >= lane.budget

    def append(self, p: _Pending) -> None:
        self._lane(p.pclass).q.append(p)
        self._len += 1

    def _pick(self) -> Optional[_Lane]:
        band_prio: Optional[int] = None
        band: list[_Lane] = []
        for lane in self._order:
            if not lane.q:
                continue
            if band_prio is None or lane.priority < band_prio:
                band_prio, band = lane.priority, [lane]
            elif lane.priority == band_prio:
                band.append(lane)
        if not band:
            return None
        if len(band) == 1:
            return band[0]
        # max() is stable: ties resolve to declaration order
        return max(band, key=lambda ln: ln.credit + ln.weight)

    def peek(self) -> _Pending:
        lane = self._pick()
        if lane is None:
            raise IndexError("peek from empty lanes")
        return lane.q[0]

    def popleft(self) -> _Pending:
        lane = self._pick()
        if lane is None:
            raise IndexError("pop from empty lanes")
        band = [ln for ln in self._order if ln.q and ln.priority == lane.priority]
        if len(band) > 1:
            # smooth WRR advance: credit += weight for the whole band, the
            # winner pays back the band's total
            total = 0
            for ln in band:
                ln.credit += ln.weight
                total += ln.weight
            lane.credit -= total
        self._len -= 1
        return lane.q.popleft()

    def remove(self, p: _Pending) -> None:
        self._lane(p.pclass).q.remove(p)  # ValueError if absent, like deque
        self._len -= 1

    def clear(self) -> None:
        for lane in self._order:
            lane.q.clear()
        self._len = 0

    def __iter__(self):
        for lane in self._order:
            yield from lane.q

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def depths(self) -> dict[str, int]:
        return {lane.name: len(lane.q) for lane in self._order if lane.q}


@dataclass
class _Inflight:
    """One submitted device batch awaiting collection."""

    ticket: Any
    group: list[_Pending]
    batch_id: int = 0
    n_inputs: int = 0
    batch_ctx: Optional[SpanContext] = None  # the batch.submit span
    timings: dict = field(default_factory=dict)  # stage -> seconds
    submitted_at: float = 0.0  # perf_counter at submit return
    submitted_wall_ns: int = 0
    occupancy: float = 1.0
    layout_key: Optional[str] = None
    kind: str = "check"


class _ShardStageView:
    """Binds the shard dimension of the (stage, shard)-labeled stage-latency
    HistogramVec so hot-path call sites keep the one-argument
    ``observe(stage, v)`` shape."""

    __slots__ = ("vec", "shard")

    def __init__(self, vec: Any, shard: str):
        self.vec = vec
        self.shard = shard

    def observe(self, stage: str, v: float) -> None:
        self.vec.observe((stage, self.shard), v)


def _settle(fut: Future, result: Any = None, error: Optional[BaseException] = None) -> None:
    """Resolve a future without ever raising out of the batcher thread — the
    waiter may have timed out and abandoned it."""
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
    except Exception:  # noqa: BLE001  (InvalidStateError and kin)
        pass


def _fingerprint(inp: T.CheckInput) -> int:
    """Stable identity of a check input for the quarantine set (attrs may
    hold unhashable values, so they hash via a sorted repr)."""
    pr, rs = inp.principal, inp.resource
    return hash(
        (
            pr.id,
            tuple(pr.roles or ()),
            pr.policy_version,
            pr.scope,
            repr(sorted((pr.attr or {}).items())),
            rs.kind,
            rs.id,
            rs.policy_version,
            rs.scope,
            repr(sorted((rs.attr or {}).items())),
            tuple(inp.actions or ()),
        )
    )


class BatchingEvaluator:
    """Wraps a batch evaluator (TpuEvaluator) with cross-request batching
    and an in-flight streaming window over its submit/collect pipeline."""

    # Engine forwards per-request deadlines only to evaluators that opt in.
    supports_deadline = True
    # Engine forwards latency-budget waterfalls only to evaluators that
    # book their own stages (admission/queue/pack/device/collect/settle).
    supports_waterfall = True
    # Engine forwards the admission priority class only to evaluators with
    # priority lanes (engine/admission.py classifies at ingress).
    supports_pclass = True

    def __init__(
        self,
        evaluator: Any,
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
        min_batch_to_wait: int = 2,
        request_timeout_s: float = 30.0,
        max_inflight: int = 3,
        health: Optional[DeviceHealth] = None,
        quarantine_max: int = 128,
        bisect_budget: int = 64,
        shard_id: Optional[int] = None,
    ):
        self.evaluator = evaluator
        # shard identity: which lane of the sharded pool this batcher drives.
        # None means "the only batcher" (single-evaluator serving); metrics
        # and flight records are still labeled shard="0" so dashboards see
        # one schema either way.
        self.shard_id = shard_id
        self._shard_label = str(shard_id) if shard_id is not None else "0"
        self.max_batch = max_batch
        self.request_timeout = request_timeout_s
        self.max_wait = max_wait_ms / 1000.0
        self.min_batch_to_wait = min_batch_to_wait
        self.max_inflight = max(1, int(max_inflight))
        self.health = health
        # parity sentinel (engine/sentinel.py), attached post-construction;
        # when set, completed device batches are offered for shadow-oracle
        # sampling from the drain thread
        self.sentinel: Optional[Any] = None
        # batched planner (plan/batch.py BatchPlanner), attached
        # post-construction; when set, plan() coalesces PlanResources
        # queries into vectorized partial-evaluation flights on the same
        # drain loop, riding the low-priority "plan" lane
        self.plan_planner: Optional[Any] = None
        self.quarantine_max = max(1, int(quarantine_max))
        self.bisect_budget = max(3, int(bisect_budget))
        self._queue = _PriorityLanes()
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._stop = False
        self._dead: Optional[BaseException] = None
        self._draining: list[_Pending] = []
        # policy epoch this lane is serving (rollout.py stamps it inside the
        # cutover barrier); None until a RolloutController seeds/commits one
        self.epoch: Optional[int] = None
        # pending cutover barrier (rollout.SwapBarrier): when set, the drain
        # loop submits nothing new, collects every in-flight batch, then
        # parks at the flight boundary until the controller releases it —
        # the mechanism that guarantees no request spans two rule tables
        self._swap_barrier: Optional[Any] = None
        self._qlock = threading.Lock()
        self._quarantine: dict[int, bool] = {}  # insertion-ordered, bounded
        self._bisect_busy = False
        self.stats = {
            "batches": 0,
            "batched_requests": 0,
            "inflight_peak": 0,
            "oracle_fallbacks": 0,
            "batch_errors": 0,
            "deadline_drops": 0,
            "quarantined": 0,
            "lane_refusals": 0,
            "plan_batches": 0,
            "plan_requests": 0,
            "plan_fallbacks": 0,
        }
        self._init_metrics()
        # instantiate the process-global hot-rule recorder eagerly so its
        # metric families exist from bootstrap (scrapes see zeroed series
        # before the first decision, and the registry lint covers them)
        hotrules.recorder()
        tname = "check-batcher" if shard_id is None else f"check-batcher-s{shard_id}"
        self._thread = threading.Thread(target=self._loop, daemon=True, name=tname)
        self._thread.start()

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_batch_size = reg.histogram(
            "cerbos_tpu_batcher_batch_size",
            "inputs per device batch",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096],
        )
        self.m_queue_wait = reg.histogram(
            "cerbos_tpu_batcher_queue_wait_seconds",
            "request wait from enqueue to device submit",
            buckets=[0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0],
        )
        self.m_inflight = reg.gauge_vec(
            "cerbos_tpu_batcher_inflight",
            "device batches currently in flight, by shard",
            label="shard",
            track_max=True,
        ).labels(self._shard_label)
        self.m_oracle_fallbacks = reg.counter_vec(
            "cerbos_tpu_batcher_oracle_fallbacks_total",
            "requests served from the CPU oracle instead of the device path, by reason",
            label="reason",
        )
        self.m_batches = reg.counter(
            "cerbos_tpu_batcher_batches_total", "device batches submitted"
        )
        self.m_requests = reg.counter(
            "cerbos_tpu_batcher_requests_total", "requests coalesced into device batches"
        )
        self.m_deadline_drops = reg.counter(
            "cerbos_tpu_batcher_deadline_drops_total",
            "requests dropped with DEADLINE_EXCEEDED before device work",
        )
        self.m_quarantined = reg.counter(
            "cerbos_tpu_batcher_quarantined_total",
            "poison inputs quarantined after batch bisection",
        )
        # device-economics: how full the padded device layouts actually are,
        # and the per-stage latency attribution the traces aggregate over
        self.m_occupancy = reg.gauge_vec(
            "cerbos_tpu_batch_occupancy",
            "real rows / padded rows of the last device batch (1.0 = no padding waste), by shard",
            label="shard",
        ).labels(self._shard_label)
        self.m_padding_waste = reg.counter_vec(
            "cerbos_tpu_batch_padding_waste_rows_total",
            "padded device rows that carried no real input, by shard",
            label="shard",
        )
        self.m_queue_budget = reg.counter_vec(
            "cerbos_tpu_admission_queue_budget_total",
            "requests refused because their priority class's lane queue budget was full, by class",
            label="pclass",
        )
        self._m_stage_vec = reg.histogram_vec(
            "cerbos_tpu_batch_stage_seconds",
            "device-batch pipeline stage latency (pack/submit/device/collect/settle), by shard",
            label=("stage", "shard"),
            buckets=[0.0001, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0],
        )
        self.m_stage_seconds = _ShardStageView(self._m_stage_vec, self._shard_label)

    # -- oracle fallback ----------------------------------------------------

    def _serve_oracle(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams],
        reason: str,
        wf: Optional[Waterfall] = None,
    ) -> list[T.CheckOutput]:
        self.stats["oracle_fallbacks"] += 1
        self.m_oracle_fallbacks.inc(reason)
        if wf is not None:
            wf.note_fallback(reason)
        ev = self.evaluator
        # read the table once: a cutover between inputs must not split this
        # request across two tables; the epoch stamp travels with the table
        rt = ev.rule_table
        T.set_current_epoch(getattr(rt, "policy_epoch", None))
        out = [
            check_input(rt, i, params or T.EvalParams(), ev.schema_mgr)
            for i in inputs
        ]
        # oracle-served decisions carry source="oracle" from check_input;
        # fold them into the hot-rule heatmap so attribution-rate and
        # device-vs-oracle splits cover the degraded path too
        hotrules.recorder().observe(out)
        if wf is not None:
            wf.mark(STAGE_ORACLE)
        return out

    # -- request path -------------------------------------------------------

    # queued plan queries beyond this refuse with OverloadRefused instead of
    # growing an unbounded analytical backlog behind interactive checks
    PLAN_QUEUE_BUDGET = 256

    def configure_lanes(self, lane_confs) -> None:
        """Install the weighted priority lanes (one per admission class,
        plus the default catch-all) from (name, priority, weight,
        queue_budget) tuples — ``AdmissionController.lane_confs()``. A
        "plan" lane is appended below every configured band unless the
        config names one explicitly: plan queries are analytical traffic
        that must never preempt an interactive check."""
        confs = list(lane_confs or ())
        if confs and not any(str(c[0]) == "plan" for c in confs):
            floor = max(int(c[1]) for c in confs)
            confs.append(("plan", floor + 1, 1, self.PLAN_QUEUE_BUDGET))
        with self._wakeup:
            self._queue.configure(confs)

    def lane_depths(self) -> dict[str, int]:
        with self._lock:
            return self._queue.depths()

    def _enqueue(self, pending: _Pending) -> bool:
        """Enqueue under the lane's queue budget; False = budget full (the
        caller refuses — per-class backlog must not starve the ring)."""
        with self._wakeup:
            if self._queue.over_budget(pending.pclass):
                self.stats["lane_refusals"] += 1
                self.m_queue_budget.inc(pending.pclass or "default")
                return False
            self._queue.append(pending)
            self._wakeup.notify()
            return True

    def check(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Waterfall] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        T.set_current_shard(self.shard_id if self.shard_id is not None else 0)
        if wf is not None:
            wf.shard = self.shard_id if self.shard_id is not None else 0
        if deadline is not None and time.monotonic() >= deadline:
            self._count_deadline_drop()
            raise DeadlineExceeded("request deadline expired before evaluation")
        if self._quarantine and self._has_quarantined(inputs):
            return self._serve_oracle(inputs, params, "quarantine", wf=wf)
        health = self.health
        if health is not None and not health.allow_device():
            # breaker open: serve from the oracle with NO device wait; a due
            # probe rides this request's inputs off-path to test re-close
            token = health.should_probe()
            if token is not None:
                self._spawn_probe(token, list(inputs)[:16], params)
            return self._serve_oracle(inputs, params, "breaker_open", wf=wf)
        if self._stop or self._dead is not None or not self._thread.is_alive():
            # drain loop gone (shutdown or crash): fail fast to the oracle
            return self._serve_oracle(inputs, params, "batcher_dead", wf=wf)
        with start_span("batcher.enqueue", inputs=len(inputs)) as span:
            fut: Future = Future()
            # the span context crosses the batcher thread hop in _Pending so
            # the device batch's spans land in this request's trace
            pending = _Pending(
                list(inputs), params, fut, deadline=deadline, ctx=span.context, wf=wf,
                pclass=pclass or "",
            )
            self._admit_wf(wf, deadline)
            if not self._enqueue(pending):
                span.set_attribute("outcome", "queue_budget")
                raise OverloadRefused(pending.pclass, "queue_budget", retry_after=0.1)
            wait = self.request_timeout
            if deadline is not None:
                wait = min(wait, max(0.0, deadline - time.monotonic()))
            try:
                outs = fut.result(timeout=wait)
                # assigned on the drain thread at submit time (after the
                # cutover-barrier check): the epoch this batch actually ran on
                T.set_current_epoch(pending.epoch)
                return outs
            except DeadlineExceeded:
                span.set_attribute("outcome", "deadline_exceeded")
                raise
            except _BatchFailed as e:
                # the device batch failed (or the batcher is shutting down /
                # dead, or the breaker opened while queued): recover this
                # request's own inputs from the oracle
                span.set_attribute("outcome", e.reason)
                return self._serve_oracle(pending.inputs, params, e.reason, wf=wf)
            except (TimeoutError, FutureTimeoutError):  # distinct classes before 3.11
                # a wedged device must not block server threads forever: drop the
                # request from the queue (if still there) and serve it from the
                # CPU oracle. The future is NOT cancelled — if the device call
                # eventually returns, _collect's set_result on it must stay legal.
                with self._wakeup:
                    try:
                        self._queue.remove(pending)
                    except ValueError:
                        pass
                if deadline is not None and time.monotonic() >= deadline:
                    self._count_deadline_drop()
                    raise DeadlineExceeded("request deadline expired while queued") from None
                if health is not None:
                    health.record_timeout()
                span.set_attribute("outcome", "timeout")
                return self._serve_oracle(pending.inputs, params, "timeout", wf=wf)

    def check_async(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        ctx: Optional[SpanContext] = None,
        wf: Optional[Waterfall] = None,
        pclass: Optional[str] = None,
    ) -> Future:
        """Non-blocking enqueue for callers that hold many tickets at once
        (the IPC server fronting N worker processes cannot burn a thread per
        ticket). Same admission ladder as ``check()``, but refusals settle
        the returned future with the exception instead of serving the oracle
        here — the front-end process owns its own COW-shared oracle and the
        batcher process keeps its cycles for device work. The future resolves
        to ``list[CheckOutput]`` or raises ``DeadlineExceeded``/``_BatchFailed``.
        """
        fut: Future = Future()
        if wf is not None:
            wf.shard = self.shard_id if self.shard_id is not None else 0
        if deadline is not None and time.monotonic() >= deadline:
            self._count_deadline_drop()
            _settle(fut, error=DeadlineExceeded("request deadline expired before evaluation"))
            return fut
        if self._quarantine and self._has_quarantined(inputs):
            _settle(fut, error=_BatchFailed(None, "quarantine"))
            return fut
        health = self.health
        if health is not None and not health.allow_device():
            token = health.should_probe()
            if token is not None:
                self._spawn_probe(token, list(inputs)[:16], params)
            _settle(fut, error=_BatchFailed(None, "breaker_open"))
            return fut
        if self._stop or self._dead is not None or not self._thread.is_alive():
            _settle(fut, error=_BatchFailed(self._dead, "batcher_dead"))
            return fut
        pending = _Pending(
            list(inputs), params, fut, deadline=deadline, ctx=ctx, wf=wf,
            pclass=pclass or "",
        )
        self._admit_wf(wf, deadline)
        if not self._enqueue(pending):
            # rides the existing ERR-frame path: the front end turns this
            # into HTTP 429 / RESOURCE_EXHAUSTED, costing the batcher nothing
            _settle(fut, error=_BatchFailed(None, "queue_budget"))
        return fut

    # -- plan path ----------------------------------------------------------

    def plan(
        self,
        inputs: Sequence[Any],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Waterfall] = None,
    ) -> list[Any]:
        """Batched PlanResources: enqueue PlanInputs on the low-priority
        "plan" lane and let the drain loop coalesce concurrent queries into
        one vectorized partial-evaluation flight (plan/batch.py). Failures
        fall back to the sequential planner per query — a plan query never
        errors because a co-batched sibling did."""
        planner = self.plan_planner
        if planner is None:
            raise RuntimeError("no batched planner attached to this batcher")
        if deadline is not None and time.monotonic() >= deadline:
            self._count_deadline_drop()
            raise DeadlineExceeded("plan deadline expired before evaluation")
        if self._stop or self._dead is not None or not self._thread.is_alive():
            return self._serve_plan_sequential(inputs, params, "batcher_dead", wf=wf)
        with start_span("batcher.plan_enqueue", inputs=len(inputs)) as span:
            fut: Future = Future()
            pending = _Pending(
                list(inputs), params, fut, deadline=deadline, ctx=span.context, wf=wf,
                pclass="plan", kind="plan",
            )
            self._admit_wf(wf, deadline)
            if not self._enqueue(pending):
                span.set_attribute("outcome", "queue_budget")
                raise OverloadRefused("plan", "queue_budget", retry_after=0.1)
            wait = self.request_timeout
            if deadline is not None:
                wait = min(wait, max(0.0, deadline - time.monotonic()))
            try:
                return fut.result(timeout=wait)
            except DeadlineExceeded:
                span.set_attribute("outcome", "deadline_exceeded")
                raise
            except _BatchFailed as e:
                span.set_attribute("outcome", e.reason)
                return self._serve_plan_sequential(pending.inputs, params, e.reason, wf=wf)
            except (TimeoutError, FutureTimeoutError):
                with self._wakeup:
                    try:
                        self._queue.remove(pending)
                    except ValueError:
                        pass
                if deadline is not None and time.monotonic() >= deadline:
                    self._count_deadline_drop()
                    raise DeadlineExceeded("plan deadline expired while queued") from None
                span.set_attribute("outcome", "timeout")
                return self._serve_plan_sequential(pending.inputs, params, "timeout", wf=wf)

    def _serve_plan_sequential(
        self,
        inputs: Sequence[Any],
        params: Optional[T.EvalParams],
        reason: str,
        wf: Optional[Waterfall] = None,
    ) -> list[Any]:
        """Per-query recovery through the sequential walk of the attached
        planner (BatchPlanner extends Planner; without a batch context every
        rule routes symbolically, which is exactly the reference path)."""
        self.stats["plan_fallbacks"] += 1
        self.m_oracle_fallbacks.inc(f"plan_{reason}")
        if wf is not None:
            wf.note_fallback(f"plan_{reason}")
        planner = self.plan_planner
        out = [planner.plan(i, params) for i in inputs]
        if wf is not None:
            wf.mark(STAGE_ORACLE)
        return out

    def _admit_wf(self, wf: Optional[Waterfall], deadline: Optional[float]) -> None:
        """Book the admission stage at enqueue and sample the remaining
        deadline budget at the enqueue point."""
        shard = self.shard_id if self.shard_id is not None else 0
        if wf is not None:
            wf.mark(STAGE_ADMISSION)
        if deadline is not None:
            budget_tracker().observe_budget(
                POINT_ENQUEUE, deadline - time.monotonic(), shard=shard
            )

    def _count_deadline_drop(self) -> None:
        self.stats["deadline_drops"] += 1
        self.m_deadline_drops.inc()

    # -- shard-pool routing surface -----------------------------------------

    def load(self) -> int:
        """Requests queued + in flight on this lane — the least-loaded
        routing signal for the sharded pool. Reads are racy by design (a
        routing decision needs a hint, not a barrier)."""
        return len(self._queue) + int(self.m_inflight.value)

    def routable(self, inputs: Optional[Sequence[T.CheckInput]] = None) -> bool:
        """Can this lane take device traffic right now? False while its
        breaker refuses, its drain loop is gone, or (when ``inputs`` are
        given) this lane has quarantined one of them — the pool then prefers
        a sibling shard over this lane's oracle fallback."""
        if self._stop or self._dead is not None or not self._thread.is_alive():
            return False
        if self.health is not None and not self.health.allow_device():
            return False
        if inputs is not None and self._quarantine and self._has_quarantined(inputs):
            return False
        return True

    def _queue_nonempty(self) -> bool:
        with self._lock:
            return bool(self._queue)

    # -- cutover barrier ----------------------------------------------------

    def request_swap(self, barrier: Any) -> bool:
        """Ask the drain loop to park at its next flight boundary: it stops
        submitting, collects every in-flight batch, then calls
        ``barrier.park(self)`` until the rollout controller has swapped the
        shared tables (rollout.SwapBarrier). Returns False when the drain
        loop is dead or stopping — no flight can race the swap then, and
        the controller must not wait for a thread that will never park."""
        with self._wakeup:
            if self._stop or self._dead is not None or not self._thread.is_alive():
                return False
            self._swap_barrier = barrier
            self._wakeup.notify_all()
        return True

    # -- drain loop ---------------------------------------------------------

    def _loop(self) -> None:
        inflight: deque[_Inflight] = deque()
        try:
            self._loop_inner(inflight)
        except BaseException as e:  # noqa: BLE001  (watchdog: fail fast, not hang)
            self._dead = e
            _log.exception("check-batcher drain loop died; requests fail over to the CPU oracle")
            draining, self._draining = self._draining, []
            for p in draining:
                _settle(p.future, error=_BatchFailed(e, "batcher_dead"))
        # drain on shutdown: settle everything still in flight, then any
        # requests still queued (waiters must not sleep out their timeout
        # against a thread that no longer exists)
        while inflight:
            flight = inflight.popleft()
            try:
                self._collect(flight)
            except BaseException as e:  # noqa: BLE001
                for p in flight.group:
                    _settle(p.future, error=_BatchFailed(e, "batcher_dead"))
            self.m_inflight.set(len(inflight))
        self._settle_residual_queue()

    def _loop_inner(self, inflight: deque) -> None:
        while True:
            with self._wakeup:
                if self._stop:
                    break
                barrier = self._swap_barrier
                if barrier is None and not self._queue:
                    if not inflight:
                        self._wakeup.wait()
                        continue
                elif barrier is None and not inflight and self.max_wait > 0:
                    # small wait to let concurrent requests coalesce (only
                    # while the pipeline is empty: with batches in flight the
                    # collect below provides the coalescing window for free)
                    deadline = time.monotonic() + self.max_wait
                    while (
                        len(self._queue) < self.min_batch_to_wait
                        and not self._stop
                        and self._swap_barrier is None
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._wakeup.wait(remaining)
                    barrier = self._swap_barrier
                pending: list[_Pending] = []
                total = 0
                now = time.monotonic()
                # with a cutover barrier pending, submit nothing new: the
                # queue keeps admitting (requests just wait out the barrier),
                # while the collect loop below drains the device pipeline to
                # the flight boundary the swap requires
                while barrier is None and self._queue and total < self.max_batch:
                    p = self._queue.peek()
                    if pending and total + len(p.inputs) > self.max_batch:
                        break
                    self._queue.popleft()
                    if p.deadline is not None and now >= p.deadline:
                        # expired while queued: don't spend device work on it
                        self._count_deadline_drop()
                        _settle(
                            p.future,
                            error=DeadlineExceeded("request deadline expired while queued"),
                        )
                        continue
                    pending.append(p)
                    total += len(p.inputs)
            if pending:
                health = self.health
                if health is not None and not health.allow_device():
                    # breaker opened while these were queued: bounce them to
                    # their waiters, which recover in parallel via the oracle
                    for p in pending:
                        _settle(p.future, error=_BatchFailed(None, "breaker_open"))
                else:
                    self._draining = pending
                    self._submit(pending, inflight)
                    self._draining = []
            # Collect when the window is full, or when there's nothing left
            # to submit (the pipeline drains while new requests may still
            # arrive; re-check the queue between collects so a fresh burst
            # re-enters the submit path with batches still in flight).
            while inflight:
                if (
                    barrier is None
                    and len(inflight) < self.max_inflight
                    and self._queue_nonempty()
                ):
                    break
                self._collect(inflight.popleft())
                self.m_inflight.set(len(inflight))
            if barrier is not None:
                # flight boundary reached: nothing in flight, nothing mid-
                # submit. Park here while the controller swaps the shared
                # tables and stamps the new epoch, then resume draining.
                barrier.park(self)
                with self._wakeup:
                    if self._swap_barrier is barrier:
                        self._swap_barrier = None

    def _submit(self, pending: list[_Pending], inflight: deque) -> None:
        # group by (kind, params identity): globals etc. must match within a
        # batch, and plan pendings must never mix into a device check batch
        groups: dict[tuple[str, int], list[_Pending]] = {}
        for p in pending:
            # the epoch pin: everything submitted between two cutover
            # barriers ran against exactly this lane epoch's tables
            p.epoch = self.epoch
            groups.setdefault((p.kind, id(p.params)), []).append(p)
        now = time.perf_counter()
        shard = self.shard_id if self.shard_id is not None else 0
        for group in groups.values():
            if group[0].kind == "plan":
                self._submit_plan(group, inflight, now)
                continue
            all_inputs: list[T.CheckInput] = []
            for p in group:
                all_inputs.extend(p.inputs)
                self.m_queue_wait.observe(now - p.enqueued_at)
                if p.wf is not None:
                    p.wf.mark(STAGE_QUEUE_WAIT)
                if p.deadline is not None:
                    # the second budget sample point: requests that reach the
                    # device already near-expired show up here, not at enqueue
                    budget_tracker().observe_budget(
                        POINT_DEVICE_SUBMIT, p.deadline - time.monotonic(), shard=shard
                    )
            batch_id = flight_recorder().next_batch_id()
            submit = getattr(self.evaluator, "submit", None)
            # parent the batch under the first co-batched request's trace and
            # link the rest: one trace gets real descendants, every other
            # co-batched trace still reaches the batch via its link
            links = [p.ctx for p in group if p.ctx is not None]
            parent = links[0] if links else None
            try:
                with start_span(
                    "batch.submit",
                    parent=parent,
                    links=links,
                    batch_id=batch_id,
                    requests=len(group),
                    inputs=len(all_inputs),
                ) as span:
                    batch_ctx = span.context
                    t0 = time.perf_counter()
                    if submit is not None:
                        ticket = submit(all_inputs, group[0].params)
                    else:
                        # plain evaluator without a streaming API: evaluate
                        # synchronously and carry the result as a ready ticket
                        ticket = _ReadyTicket(self.evaluator.check(all_inputs, group[0].params))
                    submit_s = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001
                self._batch_failed(group, all_inputs, e, batch_id=batch_id)
                continue
            self.stats["batches"] += 1
            self.stats["batched_requests"] += len(group)
            self.m_batches.inc()
            self.m_requests.inc(len(group))
            self.m_batch_size.observe(len(all_inputs))
            # stage timings: pack happens inside the evaluator's submit, which
            # reports it (plus layout economics) as ticket attributes; sync
            # evaluators have no packed device layout, so occupancy is 1.0
            pack_s = float(getattr(ticket, "pack_s", 0.0) or 0.0)
            occupancy = getattr(ticket, "occupancy", None)
            if occupancy is None:
                occupancy = 1.0
            padded_rows = getattr(ticket, "padded_rows", None)
            flight = _Inflight(
                ticket,
                group,
                batch_id=batch_id,
                n_inputs=len(all_inputs),
                batch_ctx=batch_ctx,
                timings={"pack": pack_s, "submit": max(0.0, submit_s - pack_s)},
                submitted_at=time.perf_counter(),
                submitted_wall_ns=time.time_ns(),
                occupancy=float(occupancy),
                layout_key=getattr(ticket, "layout_key", None),
            )
            self.m_stage_seconds.observe("pack", flight.timings["pack"])
            self.m_stage_seconds.observe("submit", flight.timings["submit"])
            self.m_occupancy.set(float(occupancy))
            if padded_rows:
                waste = int(round(padded_rows * (1.0 - float(occupancy))))
                if waste > 0:
                    self.m_padding_waste.inc(self._shard_label, waste)
            inflight.append(flight)
            depth = len(inflight)
            self.m_inflight.set(depth)
            if depth > self.stats["inflight_peak"]:
                self.stats["inflight_peak"] = depth

    def _submit_plan(self, group: list[_Pending], inflight: deque, now: float) -> None:
        """One coalesced plan flight: run the batched planner synchronously
        (plan_batch is host-driven — there is no streaming ticket to overlap)
        and park the ready outputs in the inflight window for settle."""
        all_inputs: list[Any] = []
        for p in group:
            all_inputs.extend(p.inputs)
            self.m_queue_wait.observe(now - p.enqueued_at)
            if p.wf is not None:
                p.wf.mark(STAGE_QUEUE_WAIT)
        batch_id = flight_recorder().next_batch_id()
        links = [p.ctx for p in group if p.ctx is not None]
        parent = links[0] if links else None
        try:
            with start_span(
                "plan_batch.submit",
                parent=parent,
                links=links,
                batch_id=batch_id,
                requests=len(group),
                inputs=len(all_inputs),
            ) as span:
                batch_ctx = span.context
                ticket = _ReadyTicket(self.plan_planner.plan_batch(all_inputs, group[0].params))
        except Exception as e:  # noqa: BLE001
            self._batch_failed(group, all_inputs, e, batch_id=batch_id)
            return
        self.stats["plan_batches"] += 1
        self.stats["plan_requests"] += len(group)
        flight = _Inflight(
            ticket,
            group,
            batch_id=batch_id,
            n_inputs=len(all_inputs),
            batch_ctx=batch_ctx,
            submitted_at=time.perf_counter(),
            submitted_wall_ns=time.time_ns(),
            kind="plan",
        )
        inflight.append(flight)
        self.m_inflight.set(len(inflight))

    def _collect_plan(self, flight: _Inflight) -> None:
        group = flight.group
        outputs = flight.ticket.outputs
        settle_start = time.perf_counter()
        with start_span(
            "plan_batch.settle", parent=flight.batch_ctx, batch_id=flight.batch_id
        ):
            offset = 0
            for p in group:
                _settle(p.future, result=outputs[offset : offset + len(p.inputs)])
                offset += len(p.inputs)
                if p.wf is not None:
                    p.wf.mark(STAGE_SETTLE)
        flight.timings["settle"] = time.perf_counter() - settle_start
        self._record_flight(flight, outcome="ok")
        sentinel = self.sentinel
        if sentinel is not None:
            all_inputs: list[Any] = []
            for p in group:
                all_inputs.extend(p.inputs)
            # after settle so plan parity replays never add request latency
            sentinel.observe_plan_batch(self, all_inputs, group[0].params, outputs)

    def _collect(self, flight: _Inflight) -> None:
        if flight.kind == "plan":
            self._collect_plan(flight)
            return
        group = flight.group
        collect_start = time.perf_counter()
        # the window between submit returning and collect starting is device
        # transfer + compute time no host thread executes; synthesize it as
        # a span so the trace shows where the latency actually went
        if flight.submitted_at:
            device_s = max(0.0, collect_start - flight.submitted_at)
            flight.timings["device"] = device_s
            self.m_stage_seconds.observe("device", device_s)
            export_span(
                "batch.device",
                flight.batch_ctx,
                flight.submitted_wall_ns,
                device_s,
                batch_id=flight.batch_id,
            )
        try:
            with start_span(
                "batch.collect", parent=flight.batch_ctx, batch_id=flight.batch_id
            ):
                if isinstance(flight.ticket, _ReadyTicket):
                    outputs = flight.ticket.outputs
                else:
                    outputs = self.evaluator.collect(flight.ticket)
        except Exception as e:  # noqa: BLE001
            flight.timings["collect"] = time.perf_counter() - collect_start
            all_inputs: list[T.CheckInput] = []
            for p in group:
                all_inputs.extend(p.inputs)
            self._batch_failed(group, all_inputs, e, flight=flight)
            return
        collect_s = time.perf_counter() - collect_start
        flight.timings["collect"] = collect_s
        self.m_stage_seconds.observe("collect", collect_s)
        if self.health is not None:
            self.health.record_success()
        settle_start = time.perf_counter()
        with start_span(
            "request.settle", parent=flight.batch_ctx, batch_id=flight.batch_id
        ):
            offset = 0
            for p in group:
                if p.wf is not None:
                    # batch-level stage durations attributed to every rider;
                    # the residual (inflight-slot waits, scheduling) folds
                    # into the settle mark so the stage sum still tiles the
                    # request's wall clock
                    p.wf.add(
                        STAGE_PACK,
                        flight.timings.get("pack", 0.0) + flight.timings.get("submit", 0.0),
                    )
                    p.wf.add(STAGE_DEVICE, flight.timings.get("device", 0.0))
                    p.wf.add(STAGE_COLLECT, collect_s)
                _settle(p.future, result=outputs[offset : offset + len(p.inputs)])
                offset += len(p.inputs)
                if p.wf is not None:
                    p.wf.mark(STAGE_SETTLE)
        settle_s = time.perf_counter() - settle_start
        flight.timings["settle"] = settle_s
        self.m_stage_seconds.observe("settle", settle_s)
        self._record_flight(flight, outcome="ok")
        # hot-rule heatmap (ISSUE 20): after settle like the sentinel, so
        # attribution accounting never adds to request latency
        hotrules.recorder().observe(outputs)
        sentinel = self.sentinel
        if sentinel is not None:
            # after settle so the sentinel never adds to request latency;
            # observe_batch is guaranteed non-raising and non-blocking
            sentinel.observe_batch(self, flight, outputs)

    def _record_flight(self, flight: _Inflight, outcome: str) -> None:
        health = self.health
        flight_recorder().record_batch(
            flight.batch_id,
            trace_ids=sorted({p.ctx.trace_id for p in flight.group if p.ctx is not None}),
            requests=len(flight.group),
            inputs=flight.n_inputs,
            timings=flight.timings,
            outcome=outcome,
            occupancy=flight.occupancy,
            layout_key=flight.layout_key,
            breaker_state=health.state if health is not None else None,
            shard=self.shard_id,
        )

    def _batch_failed(
        self,
        group: list[_Pending],
        all_inputs: list[T.CheckInput],
        e: Exception,
        batch_id: int = 0,
        flight: Optional[_Inflight] = None,
    ) -> None:
        """A device batch raised: settle each co-batched waiter with
        _BatchFailed so they each re-serve from the oracle (never a 5xx),
        feed the breaker, and bisect the batch off-path for poison. Plan
        flights settle the same way (waiters re-plan sequentially) but
        never feed the breaker or bisect — a planner bug is not a device
        health signal, and PlanInputs have no check fingerprint."""
        is_plan = bool(group) and group[0].kind == "plan"
        self.stats["batch_errors"] += 1
        if self.health is not None and not is_plan:
            self.health.record_failure()
        _log.warning(
            "device batch failed; co-batched requests fall back to the CPU oracle",
            extra={"fields": {"inputs": len(all_inputs), "error": repr(e)}},
        )
        if flight is None:
            flight = _Inflight(None, group, batch_id=batch_id, n_inputs=len(all_inputs))
        self._record_flight(flight, outcome=f"error:{type(e).__name__}")
        flight_recorder().record_event(
            "batch_failed",
            batch_id=flight.batch_id,
            inputs=len(all_inputs),
            error=repr(e),
            shard=self.shard_id,
        )
        for p in group:
            _settle(p.future, error=_BatchFailed(e))
        if not is_plan:
            self._schedule_bisect(all_inputs, group[0].params)

    # -- poison bisection + quarantine --------------------------------------

    def _schedule_bisect(self, inputs: list[T.CheckInput], params) -> None:
        # a lone failing input has no sibling to prove the device itself is
        # healthy, so it can't be told apart from a device-wide failure
        if len(inputs) < 2 or self._bisect_busy:
            return
        with self._qlock:
            if self._bisect_busy:
                return
            self._bisect_busy = True
        threading.Thread(
            target=self._bisect,
            args=(list(inputs), params),
            daemon=True,
            name="check-batcher-bisect",
        ).start()

    def _bisect(self, inputs: list[T.CheckInput], params) -> None:
        """Off-path halving search over a failed batch. Quarantine single
        inputs that still fail ONLY when some sibling sub-batch succeeded —
        otherwise the device is simply down and nothing is poisoned."""
        try:
            stack: list[list[T.CheckInput]] = [inputs]
            budget = self.bisect_budget
            ok_any = False
            poisons: list[T.CheckInput] = []
            while stack and budget > 0:
                part = stack.pop()
                budget -= 1
                try:
                    self.evaluator.check(part, params)
                    ok_any = True
                    continue
                except Exception:  # noqa: BLE001
                    pass
                if len(part) == 1:
                    poisons.append(part[0])
                else:
                    mid = len(part) // 2
                    stack.append(part[:mid])
                    stack.append(part[mid:])
            if ok_any:
                for inp in poisons:
                    self._quarantine_add(inp)
            flight_recorder().record_event(
                "bisect_done",
                inputs=len(inputs),
                sibling_ok=ok_any,
                poisons=len(poisons) if ok_any else 0,
            )
        except Exception:  # noqa: BLE001  (bisect is best-effort, off-path)
            pass
        finally:
            self._bisect_busy = False

    def _quarantine_add(self, inp: T.CheckInput) -> None:
        fp = _fingerprint(inp)
        with self._qlock:
            if fp in self._quarantine:
                return
            self._quarantine[fp] = True
            while len(self._quarantine) > self.quarantine_max:
                self._quarantine.pop(next(iter(self._quarantine)))
        self.stats["quarantined"] += 1
        self.m_quarantined.inc()
        flight_recorder().record_event(
            "quarantine_add",
            principal=inp.principal.id,
            resource_kind=inp.resource.kind,
            resource_id=inp.resource.id,
            shard=self.shard_id,
        )
        _log.error(
            "quarantined poison input: it crashes device batches and will be "
            "served by the CPU oracle",
            extra={
                "fields": {
                    "principal": inp.principal.id,
                    "resourceKind": inp.resource.kind,
                    "resourceId": inp.resource.id,
                    "actions": list(inp.actions or ()),
                }
            },
        )

    def _has_quarantined(self, inputs: Sequence[T.CheckInput]) -> bool:
        with self._qlock:
            return any(_fingerprint(i) in self._quarantine for i in inputs)

    # -- breaker probes -----------------------------------------------------

    def _spawn_probe(self, token: int, inputs: list[T.CheckInput], params) -> None:
        threading.Thread(
            target=self._probe,
            args=(token, inputs, params),
            daemon=True,
            name="check-batcher-probe",
        ).start()

    def _probe(self, token: int, inputs: list[T.CheckInput], params) -> None:
        health = self.health
        if health is None:
            return
        try:
            submit = getattr(self.evaluator, "submit", None)
            if submit is not None:
                self.evaluator.collect(submit(inputs, params))
            else:
                self.evaluator.check(inputs, params)
        except Exception:  # noqa: BLE001
            health.probe_failed(token)
        else:
            health.probe_succeeded(token)

    # -- shutdown -----------------------------------------------------------

    def _settle_residual_queue(self) -> None:
        with self._wakeup:
            residual = list(self._queue)
            self._queue.clear()
        for p in residual:
            _settle(p.future, error=_BatchFailed(None, "shutdown"))

    def close(self) -> None:
        with self._wakeup:
            self._stop = True
            self._wakeup.notify_all()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # drain loop is wedged in a device call: settle queued waiters
            # from here so shutdown doesn't strand them for request_timeout
            self._settle_residual_queue()


class _ReadyTicket:
    __slots__ = ("outputs",)

    def __init__(self, outputs):
        self.outputs = outputs
