"""Request micro-batcher: packs concurrent requests into device batches.

The north-star BatchEvaluator (BASELINE.json): the reference fans requests
onto a goroutine pool (engine.go:74-144); here concurrent CheckResources
calls enqueue and a batcher thread drains them into one padded device batch
— request count amortizes the per-dispatch cost. Requests block on a future
and get their slice of the batch output back.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from . import types as T


@dataclass
class _Pending:
    inputs: list[T.CheckInput]
    params: Optional[T.EvalParams]
    future: Future


def _settle(fut: Future, result: Any = None, error: Optional[BaseException] = None) -> None:
    """Resolve a future without ever raising out of the batcher thread — the
    waiter may have timed out and abandoned it."""
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
    except Exception:  # noqa: BLE001  (InvalidStateError and kin)
        pass


class BatchingEvaluator:
    """Wraps a batch evaluator (TpuEvaluator) with cross-request batching."""

    def __init__(
        self,
        evaluator: Any,
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
        min_batch_to_wait: int = 2,
        request_timeout_s: float = 30.0,
    ):
        self.evaluator = evaluator
        self.max_batch = max_batch
        self.request_timeout = request_timeout_s
        self.max_wait = max_wait_ms / 1000.0
        self.min_batch_to_wait = min_batch_to_wait
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True, name="check-batcher")
        self._thread.start()
        self.stats = {"batches": 0, "batched_requests": 0}

    def check(self, inputs: Sequence[T.CheckInput], params: Optional[T.EvalParams] = None) -> list[T.CheckOutput]:
        fut: Future = Future()
        pending = _Pending(list(inputs), params, fut)
        with self._wakeup:
            self._queue.append(pending)
            self._wakeup.notify()
        try:
            return fut.result(timeout=self.request_timeout)
        except TimeoutError:
            # a wedged device must not block server threads forever: drop the
            # request from the queue (if still there) and serve it from the
            # CPU oracle. The future is NOT cancelled — if the device call
            # eventually returns, _run's set_result on it must stay legal.
            with self._wakeup:
                try:
                    self._queue.remove(pending)
                except ValueError:
                    pass
            from ..ruletable import check_input

            ev = self.evaluator
            return [
                check_input(ev.rule_table, i, params or T.EvalParams(), ev.schema_mgr)
                for i in pending.inputs
            ]

    def _loop(self) -> None:
        while True:
            with self._wakeup:
                while not self._queue and not self._stop:
                    self._wakeup.wait()
                if self._stop:
                    return
                # small wait to let concurrent requests coalesce
                if len(self._queue) < self.min_batch_to_wait and self.max_wait > 0:
                    self._wakeup.wait(self.max_wait)
                pending: list[_Pending] = []
                total = 0
                while self._queue and total < self.max_batch:
                    p = self._queue[0]
                    if pending and total + len(p.inputs) > self.max_batch:
                        break
                    pending.append(self._queue.pop(0))
                    total += len(p.inputs)
            self._run(pending)

    def _run(self, pending: list[_Pending]) -> None:
        # group by params identity (globals etc. must match within a batch)
        groups: dict[int, list[_Pending]] = {}
        for p in pending:
            groups.setdefault(id(p.params), []).append(p)
        for group in groups.values():
            all_inputs: list[T.CheckInput] = []
            for p in group:
                all_inputs.extend(p.inputs)
            try:
                outputs = self.evaluator.check(all_inputs, group[0].params)
            except Exception as e:  # noqa: BLE001
                for p in group:
                    _settle(p.future, error=e)
                continue
            self.stats["batches"] += 1
            self.stats["batched_requests"] += len(group)
            offset = 0
            for p in group:
                _settle(p.future, result=outputs[offset : offset + len(p.inputs)])
                offset += len(p.inputs)

    def close(self) -> None:
        with self._wakeup:
            self._stop = True
            self._wakeup.notify_all()
        self._thread.join(timeout=5)
