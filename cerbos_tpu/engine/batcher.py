"""Request micro-batcher: packs concurrent requests into streamed device batches.

The north-star BatchEvaluator (BASELINE.json): the reference fans requests
onto a goroutine pool (engine.go:74-144); here concurrent CheckResources
calls enqueue and a batcher thread drains them into padded device batches.
Requests block on a future and get their slice of the batch output back.

The batcher drives the evaluator's STREAMING pipeline, not its blocking
``check()``: each drained group is queued on the device via ``submit()``
(async dispatch — the call returns before the device runs) and its ticket
joins an in-flight window of up to ``max_inflight`` batches. While earlier
tickets' transfers + compute are in flight, the batcher keeps draining and
submitting newer requests; ``collect()`` settles each ticket's futures as
its results land. Wall-clock under concurrent load approaches
max(host pack/assembly, device work) instead of their sum — the same
double-buffering bench.py measures, now on the serving path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from . import types as T


@dataclass
class _Pending:
    inputs: list[T.CheckInput]
    params: Optional[T.EvalParams]
    future: Future
    enqueued_at: float = field(default_factory=time.perf_counter)


@dataclass
class _Inflight:
    """One submitted device batch awaiting collection."""

    ticket: Any
    group: list[_Pending]


def _settle(fut: Future, result: Any = None, error: Optional[BaseException] = None) -> None:
    """Resolve a future without ever raising out of the batcher thread — the
    waiter may have timed out and abandoned it."""
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
    except Exception:  # noqa: BLE001  (InvalidStateError and kin)
        pass


class BatchingEvaluator:
    """Wraps a batch evaluator (TpuEvaluator) with cross-request batching
    and an in-flight streaming window over its submit/collect pipeline."""

    def __init__(
        self,
        evaluator: Any,
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
        min_batch_to_wait: int = 2,
        request_timeout_s: float = 30.0,
        max_inflight: int = 3,
    ):
        self.evaluator = evaluator
        self.max_batch = max_batch
        self.request_timeout = request_timeout_s
        self.max_wait = max_wait_ms / 1000.0
        self.min_batch_to_wait = min_batch_to_wait
        self.max_inflight = max(1, int(max_inflight))
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._stop = False
        self.stats = {
            "batches": 0,
            "batched_requests": 0,
            "inflight_peak": 0,
            "oracle_fallbacks": 0,
        }
        self._init_metrics()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="check-batcher")
        self._thread.start()

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_batch_size = reg.histogram(
            "cerbos_tpu_batcher_batch_size",
            "inputs per device batch",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096],
        )
        self.m_queue_wait = reg.histogram(
            "cerbos_tpu_batcher_queue_wait_seconds",
            "request wait from enqueue to device submit",
            buckets=[0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0],
        )
        self.m_inflight = reg.gauge(
            "cerbos_tpu_batcher_inflight",
            "device batches currently in flight",
            track_max=True,
        )
        self.m_oracle_fallbacks = reg.counter(
            "cerbos_tpu_batcher_oracle_fallbacks_total",
            "requests served from the CPU oracle after a device timeout",
        )
        self.m_batches = reg.counter(
            "cerbos_tpu_batcher_batches_total", "device batches submitted"
        )
        self.m_requests = reg.counter(
            "cerbos_tpu_batcher_requests_total", "requests coalesced into device batches"
        )

    def check(self, inputs: Sequence[T.CheckInput], params: Optional[T.EvalParams] = None) -> list[T.CheckOutput]:
        fut: Future = Future()
        pending = _Pending(list(inputs), params, fut)
        with self._wakeup:
            self._queue.append(pending)
            self._wakeup.notify()
        try:
            return fut.result(timeout=self.request_timeout)
        except (TimeoutError, FutureTimeoutError):  # distinct classes before 3.11
            # a wedged device must not block server threads forever: drop the
            # request from the queue (if still there) and serve it from the
            # CPU oracle. The future is NOT cancelled — if the device call
            # eventually returns, _collect's set_result on it must stay legal.
            with self._wakeup:
                try:
                    self._queue.remove(pending)
                except ValueError:
                    pass
            self.stats["oracle_fallbacks"] += 1
            self.m_oracle_fallbacks.inc()
            from ..ruletable import check_input

            ev = self.evaluator
            return [
                check_input(ev.rule_table, i, params or T.EvalParams(), ev.schema_mgr)
                for i in pending.inputs
            ]

    def _queue_nonempty(self) -> bool:
        with self._lock:
            return bool(self._queue)

    def _loop(self) -> None:
        inflight: deque[_Inflight] = deque()
        while True:
            with self._wakeup:
                if self._stop:
                    break
                if not self._queue:
                    if not inflight:
                        self._wakeup.wait()
                        continue
                elif not inflight and self.max_wait > 0:
                    # small wait to let concurrent requests coalesce (only
                    # while the pipeline is empty: with batches in flight the
                    # collect below provides the coalescing window for free)
                    deadline = time.monotonic() + self.max_wait
                    while len(self._queue) < self.min_batch_to_wait and not self._stop:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._wakeup.wait(remaining)
                pending: list[_Pending] = []
                total = 0
                while self._queue and total < self.max_batch:
                    p = self._queue[0]
                    if pending and total + len(p.inputs) > self.max_batch:
                        break
                    pending.append(self._queue.pop(0))
                    total += len(p.inputs)
            if pending:
                self._submit(pending, inflight)
            # Collect when the window is full, or when there's nothing left
            # to submit (the pipeline drains while new requests may still
            # arrive; re-check the queue between collects so a fresh burst
            # re-enters the submit path with batches still in flight).
            while inflight:
                if len(inflight) < self.max_inflight and self._queue_nonempty():
                    break
                self._collect(inflight.popleft())
                self.m_inflight.set(len(inflight))
        # drain on shutdown: settle everything still in flight
        while inflight:
            self._collect(inflight.popleft())
            self.m_inflight.set(len(inflight))

    def _submit(self, pending: list[_Pending], inflight: deque) -> None:
        # group by params identity (globals etc. must match within a batch)
        groups: dict[int, list[_Pending]] = {}
        for p in pending:
            groups.setdefault(id(p.params), []).append(p)
        now = time.perf_counter()
        for group in groups.values():
            all_inputs: list[T.CheckInput] = []
            for p in group:
                all_inputs.extend(p.inputs)
                self.m_queue_wait.observe(now - p.enqueued_at)
            submit = getattr(self.evaluator, "submit", None)
            try:
                if submit is not None:
                    ticket = submit(all_inputs, group[0].params)
                else:
                    # plain evaluator without a streaming API: evaluate
                    # synchronously and carry the result as a ready ticket
                    ticket = _ReadyTicket(self.evaluator.check(all_inputs, group[0].params))
            except Exception as e:  # noqa: BLE001
                for p in group:
                    _settle(p.future, error=e)
                continue
            self.stats["batches"] += 1
            self.stats["batched_requests"] += len(group)
            self.m_batches.inc()
            self.m_requests.inc(len(group))
            self.m_batch_size.observe(len(all_inputs))
            inflight.append(_Inflight(ticket, group))
            depth = len(inflight)
            self.m_inflight.set(depth)
            if depth > self.stats["inflight_peak"]:
                self.stats["inflight_peak"] = depth

    def _collect(self, flight: _Inflight) -> None:
        group = flight.group
        try:
            if isinstance(flight.ticket, _ReadyTicket):
                outputs = flight.ticket.outputs
            else:
                outputs = self.evaluator.collect(flight.ticket)
        except Exception as e:  # noqa: BLE001
            for p in group:
                _settle(p.future, error=e)
            return
        offset = 0
        for p in group:
            _settle(p.future, result=outputs[offset : offset + len(p.inputs)])
            offset += len(p.inputs)

    def close(self) -> None:
        with self._wakeup:
            self._stop = True
            self._wakeup.notify_all()
        self._thread.join(timeout=5)


class _ReadyTicket:
    __slots__ = ("outputs",)

    def __init__(self, outputs):
        self.outputs = outputs
