"""Batch flight recorder: a bounded ring buffer of recent device batches.

A production incident on the device path (a breaker trip, a poisoned batch,
a latency cliff) is reconstructable after the fact only if the server kept
the evidence: which requests were co-batched, how long each pipeline stage
took, how full the padded device layout actually was, and what the fault
machinery did about failures. The recorder keeps the last N batch records
plus a parallel ring of discrete events (breaker transitions, bisect
outcomes, quarantine additions), dumpable as JSON via the
``/_cerbos/debug/flight`` endpoint and printed to stderr on ``SIGQUIT``.

Recording is a dict append under a lock — never an allocation spike, never
I/O — so it is safe on the batcher drain loop's hot path.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Optional

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Thread-safe bounded ring of batch records + events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.capacity = max(1, int(capacity))
        self.enabled = enabled
        self._records: deque[dict] = deque(maxlen=self.capacity)
        self._events: deque[dict] = deque(maxlen=self.capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def next_batch_id(self) -> int:
        return next(self._ids)

    def record_batch(
        self,
        batch_id: int,
        *,
        trace_ids: list[str],
        requests: int,
        inputs: int,
        timings: dict[str, float],
        outcome: str,
        occupancy: Optional[float] = None,
        layout_key: Optional[str] = None,
        breaker_state: Optional[str] = None,
        shard: Optional[int] = None,
    ) -> None:
        if not self.enabled:
            return
        rec = {
            "batch_id": batch_id,
            "ts": time.time(),
            "trace_ids": trace_ids,
            "requests": requests,
            "inputs": inputs,
            "timings": {k: round(v, 6) for k, v in timings.items()},
            "outcome": outcome,
            "occupancy": round(occupancy, 4) if occupancy is not None else None,
            "layout_key": layout_key,
            "breaker_state": breaker_state,
            # which lane of the sharded pool carried this batch; None when a
            # single evaluator serves (pre-shard records keep their shape)
            "shard": shard,
        }
        with self._lock:
            self._records.append(rec)

    def lane(self, shard: int) -> list[dict]:
        """The recent batch records for one shard lane, oldest first."""
        with self._lock:
            return [r for r in self._records if r.get("shard") == shard]

    def record_event(self, kind: str, **fields: Any) -> None:
        """Discrete device-path events: breaker transitions, bisect results,
        quarantine additions, deadline storms."""
        if not self.enabled:
            return
        ev = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._events.append(ev)

    def dump(self) -> dict:
        with self._lock:
            records = list(self._records)
            events = list(self._events)
        return {
            "capacity": self.capacity,
            "batches": records,
            "events": events,
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._events.clear()


_recorder = FlightRecorder()

# optional provider of the latency-budget slow-request ring, bound by
# bootstrap to BudgetTracker.slow_dump so the SIGQUIT forensics dump
# carries the worst recent waterfalls next to the batch records
_slow_provider: Optional[Any] = None


def recorder() -> FlightRecorder:
    return _recorder


def bind_slow_requests(provider: Optional[Any]) -> None:
    global _slow_provider
    _slow_provider = provider


def configure(capacity: int = DEFAULT_CAPACITY, enabled: bool = True) -> FlightRecorder:
    """Re-bound the process-wide recorder (bootstrap wiring). Existing
    references keep working: the instance is mutated, not replaced."""
    rec = _recorder
    with rec._lock:
        rec.capacity = max(1, int(capacity))
        rec.enabled = enabled
        rec._records = deque(rec._records, maxlen=rec.capacity)
        rec._events = deque(rec._events, maxlen=rec.capacity)
    return rec


def install_sigquit_dump() -> bool:
    """Print the flight dump to stderr on SIGQUIT (the classic "what was the
    server just doing" signal). Returns False off-main-thread or where the
    signal doesn't exist; the HTTP debug endpoint still works there."""
    if not hasattr(signal, "SIGQUIT"):
        return False

    prev = signal.getsignal(signal.SIGQUIT)

    def dump(_sig, _frm):
        try:
            out = _recorder.dump()
            if _slow_provider is not None:
                with contextlib.suppress(Exception):
                    out["slow_requests"] = _slow_provider()
            sys.stderr.write(json.dumps(out, default=str) + "\n")
            sys.stderr.flush()
        except Exception:  # noqa: BLE001  (diagnostics must never kill serving)
            pass
        if callable(prev):
            prev(_sig, _frm)

    with contextlib.suppress(ValueError):  # non-main threads can't set handlers
        signal.signal(signal.SIGQUIT, dump)
        return True
    return False
