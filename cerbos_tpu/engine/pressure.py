"""Saturation pressure signals: the input surface for admission control.

Today the only pressure valves are deadline expiry (a 504 after the queue
time is already spent) and the IPC ring filling — both fire *after* the
damage. ROADMAP item 5 (admission control, priority lanes, brownout) needs
a signal that rises *before* deadlines start dying. This module aggregates
the rolling saturation signals the serving path already produces into
normalized 0..1 components and one headline ``cerbos_tpu_pressure_score``:

- ``queue``    — rolling p90 of batcher queue+inflight load against the
                 admission capacity (the earliest overload symptom: work
                 piling up faster than the device drains it);
- ``inflight`` — device batches in flight against ``inflightDepth`` (the
                 device is the bottleneck when this pins at 1.0);
- ``ipc``      — shared-batcher ticket ring occupancy against
                 ``maxOutstanding`` (front-door topology);
- ``fallback`` — fraction of decisions served by the CPU oracle over the
                 window (capacity silently degrading);
- ``degraded`` — breaker open (1.0) / half-open (0.5) or a parity storm
                 (the lane is refusing device traffic outright);
- ``compile``  — a recompile storm fired inside the window (the device is
                 spending its time in XLA instead of serving).

``score = max(components)``: saturation is not additive — any one
saturated dimension saturates the service, and a max never dilutes a
critical signal with healthy ones. Signal sources are bound as zero-arg
callables (the readiness ``bind_*`` pattern) so the monitor carries no
topology knowledge; bootstrap wires whatever the role actually has, and
every read is defensive — a dead source reads as 0, never as an error on
the sampling path.

Sampling is both pulled (each ``/_cerbos/metrics`` render and
``/_cerbos/debug/pressure`` hit samples first, so scrapes are always
fresh) and pushed (a daemon ticker at ``pressure.intervalMs`` keeps the
rolling windows warm between scrapes). One process-global instance, the
flight-recorder pattern.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..observability import metrics
from . import flight

# score at/above which a rising edge records a flight event — the "it was
# already red before the expiries" breadcrumb for incident forensics
HIGH_WATER = 0.8


def _read(fn: Optional[Callable], default=0.0):
    if fn is None:
        return default
    try:
        v = fn()
        return default if v is None else v
    except Exception:  # noqa: BLE001 — sampling must never throw
        return default


class PressureMonitor:
    """Rolling aggregation of saturation signals into pressure gauges."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        reg = metrics()
        self.m_score = reg.gauge(
            "cerbos_tpu_pressure_score",
            "Aggregate saturation pressure 0..1 (max over components; >=0.8 is the act-now line)",
        )
        self.m_queue = reg.gauge(
            "cerbos_tpu_pressure_queue",
            "Queue pressure: rolling p90 of batcher queue+inflight load vs capacity",
        )
        self.m_inflight = reg.gauge(
            "cerbos_tpu_pressure_inflight",
            "Device in-flight pressure: batches in flight vs inflightDepth",
        )
        self.m_ipc = reg.gauge(
            "cerbos_tpu_pressure_ipc",
            "IPC ring pressure: shared-batcher tickets outstanding vs maxOutstanding",
        )
        self.m_fallback = reg.gauge(
            "cerbos_tpu_pressure_fallback",
            "Oracle-fallback pressure: fraction of windowed decisions served by the CPU oracle",
        )
        self.m_degraded = reg.gauge(
            "cerbos_tpu_pressure_degraded",
            "Degradation pressure: 1 breaker open or parity storm, 0.5 half-open",
        )
        self.m_compile = reg.gauge(
            "cerbos_tpu_pressure_compile",
            "Compile pressure: 1 while a recompile storm fired inside the window",
        )
        self.enabled = True
        self.window_s = 30.0
        self.interval_s = 0.5
        self._clock = clock
        self._lock = threading.Lock()
        self._queue_samples: deque = deque()   # (t, load fraction)
        self._counter_samples: deque = deque()  # (t, fallbacks, decisions, storms)
        # last aggregate score, readable without triggering a sample (the
        # rollout canary polls this — calling sample() from outside the
        # ticker would double-fire the brownout observers)
        self.last_score = 0.0
        self._high = False
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # observers fired after every sample with (score, components, now) —
        # the brownout controller's drive shaft (engine/brownout.py)
        self._observers: list[Callable] = []
        # signal sources; all optional, bound by bootstrap per role
        self._queue_fn: Optional[Callable] = None      # -> (depth, capacity)
        self._inflight_fn: Optional[Callable] = None   # -> (inflight, depth limit)
        self._ipc_fn: Optional[Callable] = None        # -> (outstanding, max)
        self._fallbacks_fn: Optional[Callable] = None  # -> cumulative fallback count
        self._decisions_fn: Optional[Callable] = None  # -> cumulative decision count
        self._breaker_fn: Optional[Callable] = None    # -> state str (closed/open/half_open)
        self._parity_fn: Optional[Callable] = None     # -> storming shard ids
        self._storms_fn: Optional[Callable] = None     # -> cumulative recompile storms

    def configure(
        self,
        enabled: Optional[bool] = None,
        window_s: Optional[float] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_s is not None:
            self.window_s = max(1.0, float(window_s))
        if interval_s is not None:
            self.interval_s = max(0.05, float(interval_s))

    def bind(
        self,
        queue: Optional[Callable] = None,
        inflight: Optional[Callable] = None,
        ipc: Optional[Callable] = None,
        fallbacks: Optional[Callable] = None,
        decisions: Optional[Callable] = None,
        breaker: Optional[Callable] = None,
        parity: Optional[Callable] = None,
        storms: Optional[Callable] = None,
    ) -> None:
        """Attach signal sources; None leaves the existing binding alone."""
        if queue is not None:
            self._queue_fn = queue
        if inflight is not None:
            self._inflight_fn = inflight
        if ipc is not None:
            self._ipc_fn = ipc
        if fallbacks is not None:
            self._fallbacks_fn = fallbacks
        if decisions is not None:
            self._decisions_fn = decisions
        if breaker is not None:
            self._breaker_fn = breaker
        if parity is not None:
            self._parity_fn = parity
        if storms is not None:
            self._storms_fn = storms

    def add_observer(self, fn: Callable) -> None:
        """Register ``fn(score, components, now)`` to run after every
        sample. Idempotent by identity (bound methods compare equal), so
        repeated bootstrap wiring never double-drives an observer."""
        if fn not in self._observers:
            self._observers.append(fn)

    def remove_observer(self, fn: Callable) -> None:
        try:
            self._observers.remove(fn)
        except ValueError:
            pass

    def unbind(self) -> None:
        """Drop every source and rolling window (re-initialization, tests)."""
        with self._lock:
            self._queue_fn = self._inflight_fn = self._ipc_fn = None
            self._fallbacks_fn = self._decisions_fn = None
            self._breaker_fn = self._parity_fn = self._storms_fn = None
            self._queue_samples.clear()
            self._counter_samples.clear()
            self._high = False
            self._observers.clear()

    # -- sampling -----------------------------------------------------------

    @staticmethod
    def _frac(pair, default=0.0) -> float:
        try:
            depth, cap = pair
            cap = float(cap)
            if cap <= 0:
                return default
            return max(0.0, min(1.0, float(depth) / cap))
        except Exception:  # noqa: BLE001
            return default

    def sample(self, now: Optional[float] = None) -> dict:
        """Read every bound source, roll the windows, publish the gauges,
        and return the snapshot the debug endpoint serves."""
        now = self._clock() if now is None else now
        queue_frac = self._frac(_read(self._queue_fn, (0, 0)))
        inflight_frac = self._frac(_read(self._inflight_fn, (0, 0)))
        ipc_frac = self._frac(_read(self._ipc_fn, (0, 0)))
        fallbacks = float(_read(self._fallbacks_fn, 0.0))
        decisions = float(_read(self._decisions_fn, 0.0))
        storms = float(_read(self._storms_fn, 0.0))
        breaker = str(_read(self._breaker_fn, "") or "")
        parity_shards = _read(self._parity_fn, []) or []

        with self._lock:
            horizon = now - self.window_s
            self._queue_samples.append((now, queue_frac))
            while self._queue_samples and self._queue_samples[0][0] < horizon:
                self._queue_samples.popleft()
            self._counter_samples.append((now, fallbacks, decisions, storms))
            while len(self._counter_samples) > 1 and self._counter_samples[0][0] < horizon:
                self._counter_samples.popleft()
            fracs = sorted(f for _, f in self._queue_samples)
            queue_p90 = fracs[min(len(fracs) - 1, int(0.9 * len(fracs)))] if fracs else 0.0
            t0, fb0, dec0, st0 = self._counter_samples[0]
            d_fb = max(0.0, fallbacks - fb0)
            d_dec = max(0.0, decisions - dec0)

        fallback_frac = d_fb / d_dec if d_dec > 0 else (1.0 if d_fb > 0 else 0.0)
        fallback_frac = min(1.0, fallback_frac)
        compile_frac = 1.0 if storms - st0 > 0 else 0.0
        degraded = 0.0
        if breaker == "open" or list(parity_shards):
            degraded = 1.0
        elif breaker == "half_open":
            degraded = 0.5

        components = {
            "queue": round(queue_p90, 4),
            "inflight": round(inflight_frac, 4),
            "ipc": round(ipc_frac, 4),
            "fallback": round(fallback_frac, 4),
            "degraded": degraded,
            "compile": compile_frac,
        }
        score = max(components.values())
        self.m_queue.set(components["queue"])
        self.m_inflight.set(components["inflight"])
        self.m_ipc.set(components["ipc"])
        self.m_fallback.set(components["fallback"])
        self.m_degraded.set(degraded)
        self.m_compile.set(compile_frac)
        self.m_score.set(score)
        self.last_score = score

        if score >= HIGH_WATER and not self._high:
            self._high = True
            flight.recorder().record_event(
                "pressure_high",
                score=round(score, 4),
                components=components,
            )
        elif score < HIGH_WATER and self._high:
            # the matching falling edge: one pressure_recovered per
            # excursion, so forensics see the full red window, not just
            # its start
            self._high = False
            flight.recorder().record_event(
                "pressure_recovered",
                score=round(score, 4),
                components=components,
            )

        for fn in tuple(self._observers):
            try:
                fn(score, components, now)
            except Exception:  # noqa: BLE001 — observers never break sampling
                pass

        return {
            "score": round(score, 4),
            "components": components,
            "window_sec": self.window_s,
            "signals": {
                "queue_load": queue_frac,
                "ipc_ring": ipc_frac,
                "breaker": breaker or None,
                "parity_shards": list(parity_shards),
                "fallbacks_total": fallbacks,
                "decisions_total": decisions,
                "recompile_storms_total": storms,
            },
        }

    # -- background ticker ---------------------------------------------------

    def start_ticker(self) -> None:
        """Keep the rolling windows warm between scrapes. Idempotent."""
        if not self.enabled or (self._ticker is not None and self._ticker.is_alive()):
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                if self.enabled:
                    try:
                        self.sample()
                    except Exception:  # noqa: BLE001
                        pass

        self._ticker = threading.Thread(target=loop, daemon=True, name="pressure-monitor")
        self._ticker.start()

    def stop_ticker(self) -> None:
        self._stop.set()
        self._ticker = None


_monitor = PressureMonitor()


def monitor() -> PressureMonitor:
    return _monitor
