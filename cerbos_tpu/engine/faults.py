"""Config/env-gated fault injection for the device path.

``FaultInjector`` wraps a device evaluator (``TpuEvaluator`` or anything
with the same ``check``/``submit``/``collect`` surface) and injects
deterministic failures per a small comma-separated grammar, e.g.::

    CERBOS_TPU_FAULTS=submit_raise:0.1,collect_delay_ms:200,wedge_after:50

Knobs (all optional; unknown names are a hard error so typos don't
silently disable a chaos run):

- ``submit_raise:P`` / ``collect_raise:P`` / ``check_raise:P`` — raise
  ``DeviceFault`` with probability P (0..1) on the respective call.
- ``submit_delay_ms:N`` / ``collect_delay_ms:N`` — sleep N ms before the
  real call.
- ``wedge_after:N`` — after N successful device calls, every subsequent
  ``submit``/``collect`` blocks for ``wedge_sleep_s`` (default 3600)
  before raising, simulating a hung device.
- ``wedge_sleep_s:S`` — how long a wedged call blocks.
- ``poison_attr:KEY`` — any batch containing an input whose resource attr
  has KEY raises ``DeviceFault`` (submit and check, so off-path bisection
  reproduces the failure).
- ``flip_effect:P`` — post-collect, flip each returned effect row
  (ALLOW↔DENY) with probability P (0..1). Unlike the raising knobs this
  is a *silent* corruption: the batch succeeds, the caller gets wrong
  answers, and nothing errors — exactly the failure class the parity
  sentinel exists to catch. Only the device path is corrupted; the CPU
  oracle bypasses the injector, so sentinel replays see the true effects.
- ``ipc_wedge_after:N`` — consumed by ``engine/ipc.BatcherIpcServer``, not
  this wrapper: after N CHECK tickets the ticket queue swallows every
  subsequent one without replying, simulating a wedged ring so front ends
  exercise their timeout → oracle fallback.
- ``shard:N`` — consumed by ``engine/shards.build_shard_pool``, not this
  wrapper: scope the whole spec to shard lane N of the sharded serving
  pool (the shard-kill chaos drill: one sick chip, N-1 healthy siblings).
  Without it, every lane gets the injector.
- ``swap_fail:STAGE`` — consumed by ``engine/rollout.RolloutController``,
  not this wrapper: force the named rollout stage to fail. ``build``,
  ``lower``, and ``gate`` raise at that stage (the last valid epoch keeps
  serving); ``canary`` trips the canary watcher, driving an automatic
  rollback drill. Scopable with ``shard:N`` like every other knob (the
  scope is recorded in the rollout report).
- ``seed:N`` — PRNG seed for the probabilistic knobs (default 1337).

The wrapper delegates every other attribute (``rule_table``,
``schema_mgr``, ``stats``, ``refresh`` ...) to the wrapped evaluator, so
the CPU-oracle fallback and policy reload are unaffected by injection.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional


class DeviceFault(RuntimeError):
    """An injected device-path failure."""


_FLOAT_KNOBS = {"submit_raise", "collect_raise", "check_raise", "wedge_sleep_s", "flip_effect"}
_INT_KNOBS = {"submit_delay_ms", "collect_delay_ms", "wedge_after", "ipc_wedge_after", "seed", "shard"}
_STR_KNOBS = {"poison_attr", "swap_fail"}

# legal swap_fail stages (validated at parse time so a typo'd stage name
# fails the run instead of silently never firing)
_SWAP_STAGES = {"build", "lower", "gate", "canary"}


def parse_fault_spec(spec: str) -> Dict[str, Any]:
    """Parse ``name:value,name:value`` into a knob dict; ValueError on
    unknown names or malformed values."""
    out: Dict[str, Any] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, raw = part.partition(":")
        name = name.strip()
        raw = raw.strip()
        if not sep or not raw:
            raise ValueError(f"malformed fault spec entry {part!r} (want name:value)")
        if name in _FLOAT_KNOBS:
            out[name] = float(raw)
        elif name in _INT_KNOBS:
            out[name] = int(raw)
        elif name in _STR_KNOBS:
            if name == "swap_fail" and raw not in _SWAP_STAGES:
                raise ValueError(
                    f"unknown swap_fail stage {raw!r} (want one of "
                    f"{'|'.join(sorted(_SWAP_STAGES))})"
                )
            out[name] = raw
        else:
            raise ValueError(f"unknown fault knob {name!r} in spec {spec!r}")
    return out


class FaultInjector:
    """Evaluator wrapper applying the parsed fault spec to the device
    calls the batcher makes. The spec dict is mutable at runtime (the
    chaos tests flip faults off to exercise breaker re-close)."""

    def __init__(self, evaluator, spec):
        self._ev = evaluator
        self.spec = parse_fault_spec(spec) if isinstance(spec, str) else dict(spec or {})
        self._rng = random.Random(self.spec.get("seed", 1337))
        self._lock = threading.Lock()
        self._calls = 0
        self.stats = getattr(evaluator, "stats", None)
        self.injected = {"raises": 0, "delays": 0, "wedges": 0, "poisoned": 0, "flipped": 0}

    def __getattr__(self, name):
        return getattr(self._ev, name)

    # -- injection plumbing -------------------------------------------------

    def _roll(self, p: Optional[float]) -> bool:
        if not p:
            return False
        with self._lock:
            return self._rng.random() < p

    def _count_call(self) -> int:
        with self._lock:
            self._calls += 1
            return self._calls

    def _maybe_wedge(self, op: str) -> None:
        wedge_after = self.spec.get("wedge_after")
        if wedge_after is None:
            return
        if self._count_call() > wedge_after:
            self.injected["wedges"] += 1
            time.sleep(float(self.spec.get("wedge_sleep_s", 3600.0)))
            raise DeviceFault(f"injected wedge on {op}")

    def _maybe_delay(self, knob: str) -> None:
        delay_ms = self.spec.get(knob)
        if delay_ms:
            self.injected["delays"] += 1
            time.sleep(delay_ms / 1000.0)

    def _maybe_raise(self, knob: str, op: str) -> None:
        if self._roll(self.spec.get(knob)):
            self.injected["raises"] += 1
            raise DeviceFault(f"injected {op} failure")

    def _check_poison(self, inputs) -> None:
        key = self.spec.get("poison_attr")
        if not key:
            return
        for i in inputs:
            attr = getattr(getattr(i, "resource", None), "attr", None) or {}
            if key in attr:
                self.injected["poisoned"] += 1
                raise DeviceFault(f"injected poison input (resource attr {key!r})")

    def _maybe_flip(self, outputs):
        """Silent-corruption knob: flip sampled effect rows ALLOW↔DENY after
        the device returned them. Mutates copies, not the originals — the
        evaluator may cache or share output objects."""
        p = self.spec.get("flip_effect")
        if not p or not outputs:
            return outputs
        from . import types as T

        flipped = []
        for o in outputs:
            if not self._roll(p):
                flipped.append(o)
                continue
            actions = {
                a: T.ActionEffect(
                    effect=(
                        T.EFFECT_DENY if e.effect == T.EFFECT_ALLOW else T.EFFECT_ALLOW
                    ),
                    policy=e.policy,
                    scope=e.scope,
                    # keep the device's claimed provenance: a divergence
                    # record then names the rule the device *said* won,
                    # which is exactly what corpus triage needs
                    matched_rule=e.matched_rule,
                    rule_row_id=e.rule_row_id,
                    source=e.source,
                )
                for a, e in o.actions.items()
            }
            self.injected["flipped"] += 1
            flipped.append(
                T.CheckOutput(
                    request_id=o.request_id,
                    resource_id=o.resource_id,
                    actions=actions,
                    effective_derived_roles=list(o.effective_derived_roles),
                    validation_errors=list(o.validation_errors),
                    outputs=list(o.outputs),
                    effective_policies=dict(o.effective_policies),
                )
            )
        return flipped

    # -- evaluator surface --------------------------------------------------

    def check(self, inputs, params=None):
        self._check_poison(inputs)
        self._maybe_raise("check_raise", "check")
        return self._maybe_flip(self._ev.check(inputs, params))

    def submit(self, inputs, params=None):
        self._maybe_wedge("submit")
        self._check_poison(inputs)
        self._maybe_raise("submit_raise", "submit")
        self._maybe_delay("submit_delay_ms")
        return self._ev.submit(inputs, params)

    def collect(self, ticket):
        self._maybe_wedge("collect")
        self._maybe_raise("collect_raise", "collect")
        self._maybe_delay("collect_delay_ms")
        return self._maybe_flip(self._ev.collect(ticket))
