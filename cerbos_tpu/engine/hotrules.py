"""Bounded hot-rule telemetry: which rules live traffic actually lands on.

Decision provenance (ISSUE 20) stamps every ActionEffect with the winning
rule-table row id. This module aggregates those ids into a fixed-size hit
array indexed by ``rule_row_id`` — one int64 per lowered rule row, ZERO
label-cardinality risk — and exposes:

* a top-K snapshot for ``/_cerbos/debug/hotrules`` (rule FQN, analyzer
  class, hit count, traffic share), the ranking input for the oracle-
  extinction burn-down (ROADMAP item 5);
* a ``cerbos_tpu_rule_hits_total{class}`` rollup keyed by the PR-14 static
  analyzer class (device / tagged-fallback / oracle-only / unknown) plus
  the per-source split (device vs oracle) and the unattributed remainder —
  operators see what fraction of live decisions lands on device-eligible
  rules without per-rule metric series.

The recorder is process-global (mirrors engine/flight.py): every batcher
lane feeds the same array, the IPC control plane snapshots it from the
batcher process, and the counts survive batcher restarts within the
process. Aggregation happens after request settle (alongside the parity
sentinel's observe hook), so it never adds to request latency.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Sequence

import numpy as np

from .. import observability as obs
from . import types as T

# hard cap on the hit array: a rule table bigger than this only tracks the
# first _MAX_ROWS rows (counts beyond fold into "unattributed")
_MAX_ROWS = 1 << 20

# observe() buffers raw counts in plain dicts and defers the numpy fold +
# metric increments until this many decisions accumulate: at small batch
# sizes (the served path coalesces 1-4 requests per flight) the per-batch
# fold cost would not amortize, and the drain thread shares the core with
# serving on 1-core hosts
_FLUSH_EVERY = 256

_CLASS_UNKNOWN = "unknown"


class HotRuleRecorder:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hits = np.zeros(0, dtype=np.int64)
        self._decisions = 0
        self._unattributed = 0
        self._by_source: dict[str, int] = {}
        # pending micro-buffer (raw counts, folded on flush): rid -> count
        # (rid outside [0, _MAX_ROWS) folds into "unattributed"), src -> count
        self._pend_rows: dict[int, int] = {}
        self._pend_src: dict[str, int] = {}
        self._pend_n = 0
        # analyzer-class cache: rebuilt lazily whenever tpu.analyze publishes
        # a new report (identity-compared — publish() swaps the object)
        self._cls_report: Any = None
        self._cls_by_row: dict[int, str] = {}
        reg = obs.metrics()
        self.m_rule_hits = reg.counter_vec(
            "cerbos_tpu_rule_hits_total",
            "decisions attributed to a winning rule, by static-analyzer class "
            "(device/tagged-fallback/oracle-only; 'unknown' when no analysis "
            "report is published, 'unattributed' when no rule fired)",
            label="class",
        )
        self.m_decision_source = reg.counter_vec(
            "cerbos_tpu_decision_source_total",
            "decisions by evaluator provenance (device vs CPU-oracle)",
            label="source",
        )

    # -- ingest --------------------------------------------------------------

    def observe(self, outputs: Sequence[T.CheckOutput]) -> None:
        """Fold one settled batch's decisions into the hit array. Never
        raises; called after futures settle so it adds no request latency.
        ``CERBOS_TPU_NO_PROVENANCE=1`` disables aggregation entirely — the
        loadtest A/B baseline leg for the <=2% overhead gate."""
        if os.environ.get("CERBOS_TPU_NO_PROVENANCE"):
            return
        try:
            self._observe(outputs)
        except Exception:  # noqa: BLE001 - telemetry must never break serving
            pass

    def _observe(self, outputs: Sequence[T.CheckOutput]) -> None:
        # hot path: dict increments only — the numpy fold, analyzer-class
        # resolution, and metric increments happen at flush (every
        # _FLUSH_EVERY decisions or on snapshot), so per-batch cost stays
        # a few microseconds even at 1-2 decisions per flight
        flush = None
        with self._lock:
            pr, ps = self._pend_rows, self._pend_src
            n = 0
            for o in outputs:
                for ae in o.actions.values():
                    rid = getattr(ae, "rule_row_id", -1)
                    src = getattr(ae, "source", "") or "unknown"
                    pr[rid] = pr.get(rid, 0) + 1
                    ps[src] = ps.get(src, 0) + 1
                    n += 1
            self._pend_n += n
            if self._pend_n >= _FLUSH_EVERY:
                flush = self._flush_locked()
        if flush:
            self._publish(flush)

    def _flush_locked(self) -> Optional[tuple[dict[str, int], int, dict[str, int]]]:
        """Fold the pending micro-buffer into the hit array and the aggregate
        counters. Caller holds the lock; returns the (class, unattributed,
        source) rollup for _publish(), or None when nothing was pending."""
        if not self._pend_n:
            return None
        rows: dict[int, int] = {}
        unattributed = 0
        for rid, n in self._pend_rows.items():
            if 0 <= rid < _MAX_ROWS:
                rows[rid] = n
            else:
                unattributed += n
        src_counts = self._pend_src
        self._pend_rows, self._pend_src, self._pend_n = {}, {}, 0
        self._decisions += sum(rows.values()) + unattributed
        self._unattributed += unattributed
        for s, n in src_counts.items():
            self._by_source[s] = self._by_source.get(s, 0) + n
        cls_counts: dict[str, int] = {}
        if rows:
            top = max(rows)
            if top >= self._hits.size:
                grown = np.zeros(max(top + 1, self._hits.size * 2, 256), dtype=np.int64)
                grown[: self._hits.size] = self._hits
                self._hits = grown
            cls_map = self._class_map()
            for rid, n in rows.items():
                self._hits[rid] += n
                cls = cls_map.get(rid, _CLASS_UNKNOWN) if cls_map else _CLASS_UNKNOWN
                cls_counts[cls] = cls_counts.get(cls, 0) + n
        return (cls_counts, unattributed, src_counts)

    def _publish(self, flush: tuple[dict[str, int], int, dict[str, int]]) -> None:
        """Metric rollups for one flush: one inc per class/source, not per
        decision. Outside the lock — the registry has its own."""
        cls_counts, unattributed, src_counts = flush
        for cls, n in cls_counts.items():
            self.m_rule_hits.inc(cls, n)
        if unattributed:
            self.m_rule_hits.inc("unattributed", unattributed)
        for s, n in src_counts.items():
            self.m_decision_source.inc(s, n)

    # -- class + rule resolution ---------------------------------------------

    def _class_map(self) -> dict[int, str]:
        """row_id → analyzer eligibility class, from the latest published
        static-analysis report (tpu/analyze.py). Rebuilt when the report
        object changes (bootstrap publish / policy-swap republish)."""
        try:
            from ..tpu import analyze as analyze_mod

            report = analyze_mod.latest()
        except Exception:  # noqa: BLE001
            report = None
        if report is self._cls_report:
            return self._cls_by_row
        mapping: dict[int, str] = {}
        if report is not None:
            for rep in getattr(report, "rules", ()):
                rid = getattr(rep, "row_id", -1)
                if rid >= 0:
                    mapping[rid] = rep.eligibility
        self._cls_by_row = mapping
        self._cls_report = report
        return mapping

    @staticmethod
    def _rule_label(rule_table: Any, rid: int) -> dict[str, Any]:
        """Resolve a row id to its rule FQN against the CURRENT table. After
        an epoch swap old-row hits may resolve to a different (or no) rule —
        acceptable for a debug heatmap, called out in the endpoint payload."""
        row = None
        if rule_table is not None:
            try:
                rows = rule_table.idx.rows  # list indexed by row id
                row = rows[rid] if 0 <= rid < len(rows) else None
            except Exception:  # noqa: BLE001
                row = None
        if row is None:
            return {"rule_row_id": rid, "rule": None, "policy": None}
        from ..ruletable.check import _rule_src

        try:
            meta = rule_table.get_meta(row.origin_fqn)
            src = _rule_src(meta, row)
        except Exception:  # noqa: BLE001
            src = f"{row.origin_fqn}#{getattr(row, 'name', '')}"
        policy, _, rule = src.partition("#")
        return {"rule_row_id": rid, "rule": src, "policy": policy, "rule_name": rule}

    # -- snapshot ------------------------------------------------------------

    def snapshot(self, k: int = 20, rule_table: Any = None) -> dict[str, Any]:
        """Top-K hit rows plus the aggregate split — the
        ``/_cerbos/debug/hotrules`` payload and the ``analyze --hot`` input."""
        with self._lock:
            flush = self._flush_locked()
            hits = self._hits.copy()
            decisions = self._decisions
            unattributed = self._unattributed
            by_source = dict(self._by_source)
        if flush:
            self._publish(flush)
        k = max(1, min(int(k), 1000))
        nz = np.nonzero(hits)[0]
        order = nz[np.argsort(hits[nz])[::-1][:k]]
        cls_map = self._class_map()
        attributed = int(hits.sum())
        top = []
        for rid in order.tolist():
            entry = self._rule_label(rule_table, rid)
            entry["hits"] = int(hits[rid])
            entry["share"] = round(entry["hits"] / attributed, 6) if attributed else 0.0
            entry["class"] = cls_map.get(rid, _CLASS_UNKNOWN) if cls_map else _CLASS_UNKNOWN
            top.append(entry)
        by_class: dict[str, int] = {}
        for rid in nz.tolist():
            cls = cls_map.get(rid, _CLASS_UNKNOWN) if cls_map else _CLASS_UNKNOWN
            by_class[cls] = by_class.get(cls, 0) + int(hits[rid])
        return {
            "decisions": decisions,
            "attributed": attributed,
            "unattributed": unattributed,
            "attribution_rate": round(attributed / decisions, 6) if decisions else 0.0,
            "by_source": by_source,
            "by_class": by_class,
            "tracked_rows": int(hits.size),
            "top": top,
            # labels resolve against the current table: counts recorded
            # under an older policy epoch may rename after a swap
            "note": "row labels resolved against the current policy epoch",
        }

    def reset(self) -> None:
        with self._lock:
            self._hits = np.zeros(0, dtype=np.int64)
            self._decisions = 0
            self._unattributed = 0
            self._by_source = {}
            self._pend_rows, self._pend_src, self._pend_n = {}, {}, 0


_recorder: Optional[HotRuleRecorder] = None
_recorder_lock = threading.Lock()


def recorder() -> HotRuleRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = HotRuleRecorder()
    return _recorder
