"""Sharded serving pool: N per-device batcher lanes behind one front door.

MULTICHIP_r01–r05 proved evaluation scales across the 8-device mesh, but
the serving path drove a single evaluator — the mesh was a benchmark
artifact, not capacity. Here the pool owns one ``BatchingEvaluator`` lane
per shard, each wrapping a ``TpuEvaluator`` clone pinned to its device (or
per-shard mesh slice) via ``parallel.mesh.shard_devices``. The clones share
the expensive read-only artifacts — rule table, lowered device tables —
and own everything the hot path mutates (packer, jit cache, memos), so the
lanes run lock-free against each other.

Routing is per request at admission: ``least_loaded`` picks the routable
lane with the fewest queued + in-flight requests (ties broken round-robin),
``round_robin`` rotates blindly. A lane is routable when its drain loop is
alive, its breaker admits device traffic, and it has not quarantined any of
the request's inputs — so the pool steers around a sick shard instead of
letting that lane's oracle fallback eat the request.

Fault isolation is the point (docs/ROBUSTNESS.md): every lane carries its
own ``DeviceHealth`` breaker, quarantine set, bisect thread, and
flight-recorder lane (``shard=`` on metrics and flight records). One sick
chip trips ONE breaker; the router sends traffic to the other N-1 lanes and
service degrades to (N-1)/N device capacity instead of 0/N. Requests
already queued or in flight on the sick lane recover individually through
the lane's own ``_BatchFailed`` → oracle machinery — zero lost requests.
Recovery is also per-lane: probe batches half-open only the sick shard.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future
from typing import Any, Optional, Sequence

from ..observability import SpanContext
from . import types as T
from .batcher import BatchingEvaluator
from .health import STATE_CLOSED, STATE_HALF_OPEN, STATE_OPEN

_log = logging.getLogger("cerbos_tpu.engine.shards")

ROUTING_LEAST_LOADED = "least_loaded"
ROUTING_ROUND_ROBIN = "round_robin"


class ShardedBatchingEvaluator:
    """Routes each request to one of N ``BatchingEvaluator`` shard lanes.

    Implements the same dispatch surface as a single BatchingEvaluator
    (``check``/``check_async``/``close``/``stats``), so the engine, the IPC
    ticket server, and ``Core.batcher`` plumbing are shard-count agnostic.
    """

    supports_deadline = True
    supports_waterfall = True
    supports_pclass = True

    def __init__(
        self,
        shards: Sequence[BatchingEvaluator],
        routing: str = ROUTING_LEAST_LOADED,
    ):
        if not shards:
            raise ValueError("sharded pool needs at least one shard lane")
        self.shards = list(shards)
        self.routing = routing if routing in (ROUTING_LEAST_LOADED, ROUTING_ROUND_ROBIN) else ROUTING_LEAST_LOADED
        self._rr = 0
        self._rr_lock = threading.Lock()
        # per-shard routed-request counts: the imbalance signal bench.py and
        # loadtest publish (max/min over these ≈ 1.0 means fair routing)
        self.routed = [0] * len(self.shards)

    # -- routing ------------------------------------------------------------

    def _next_rr(self) -> int:
        with self._rr_lock:
            i = self._rr
            self._rr += 1
        return i

    def route(self, inputs: Optional[Sequence[T.CheckInput]] = None) -> BatchingEvaluator:
        """Pick the lane for one request. Prefers routable lanes (alive,
        breaker closed, inputs not quarantined there); if none qualify, falls
        back to round-robin over ALL lanes so the chosen lane's own admission
        ladder serves its oracle / runs its probe machinery."""
        n = len(self.shards)
        if n == 1:
            lane = self.shards[0]
            self.routed[0] += 1
            return lane
        start = self._next_rr()
        # probe trickle: a breaker-open lane whose backoff has elapsed gets
        # this one request as a probe donor — the lane serves it via its
        # oracle and rides its inputs on the probe batch, so recovery
        # half-opens ONLY the sick shard while the router keeps live
        # traffic on the healthy ones
        for k in range(n):
            i = (start + k) % n
            h = self.shards[i].health
            if h is not None and h.probe_due():
                self.routed[i] += 1
                return self.shards[i]
        if self.routing == ROUTING_ROUND_ROBIN:
            order = [(start + k) % n for k in range(n)]
            idx = next((i for i in order if self.shards[i].routable(inputs)), order[0])
        else:
            best: Optional[int] = None
            best_load = None
            for k in range(n):
                i = (start + k) % n  # rotate tie-breaks across lanes
                lane = self.shards[i]
                if not lane.routable(inputs):
                    continue
                load = lane.load()
                if best_load is None or load < best_load:
                    best, best_load = i, load
            idx = best if best is not None else start % n
        self.routed[idx] += 1
        return self.shards[idx]

    # -- dispatch surface ---------------------------------------------------

    def check(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        wf: Optional[Any] = None,
        pclass: Optional[str] = None,
    ) -> list[T.CheckOutput]:
        return self.route(inputs).check(
            inputs, params, deadline=deadline, wf=wf, pclass=pclass
        )

    def check_async(
        self,
        inputs: Sequence[T.CheckInput],
        params: Optional[T.EvalParams] = None,
        deadline: Optional[float] = None,
        ctx: Optional[SpanContext] = None,
        wf: Optional[Any] = None,
        pclass: Optional[str] = None,
    ) -> Future:
        return self.route(inputs).check_async(
            inputs, params, deadline=deadline, ctx=ctx, wf=wf, pclass=pclass
        )

    def configure_lanes(self, lane_confs: Sequence[tuple]) -> None:
        """Install the admission controller's priority-lane layout on every
        shard lane: each shard schedules its own queue, but the class →
        (priority, weight, budget) map is pool-wide."""
        for lane in self.shards:
            lane.configure_lanes(lane_confs)

    def lane_depths(self) -> dict:
        """Pool-wide queued depth per priority lane (debug/overload view)."""
        out: dict = {}
        for lane in self.shards:
            for name, depth in lane.lane_depths().items():
                out[name] = out.get(name, 0) + depth
        return out

    def close(self) -> None:
        for lane in self.shards:
            lane.close()

    # -- policy reload ------------------------------------------------------

    def refresh_shards(self, rule_table: Any) -> None:
        """After a policy swap re-lowered the SHARED lowered table (the base
        evaluator's refresh hook), point every clone at the new rule table
        and drop its derived caches."""
        for lane in self.shards:
            # unwrap a FaultInjector: setattr on the wrapper would shadow,
            # not update, the real evaluator's table
            ev = getattr(lane.evaluator, "_ev", lane.evaluator)
            ev.rule_table = rule_table
            ev.invalidate()

    def swap_lanes(self) -> list[Any]:
        """The per-shard BatchingEvaluators a rollout cutover must park at a
        flight boundary before mutating the shared lowered tables — the
        clones all read those tables, so the barrier is pool-wide."""
        return list(self.shards)

    # -- aggregate views ----------------------------------------------------

    @property
    def evaluator(self) -> Any:
        """The first lane's evaluator — gives shard-count-agnostic plumbing
        (oracle fallbacks, table reads) something to hold."""
        return self.shards[0].evaluator

    @property
    def stats(self) -> dict:
        """Pool-wide totals in the single-batcher stats shape, plus the
        routing distribution."""
        keys = self.shards[0].stats.keys()
        out = {k: sum(lane.stats[k] for lane in self.shards) for k in keys}
        out["inflight_peak"] = max(lane.stats["inflight_peak"] for lane in self.shards)
        out["routed"] = list(self.routed)
        return out

    def shard_stats(self) -> list[dict]:
        """Per-lane serving stats (the bench/loadtest topology block)."""
        out = []
        for i, lane in enumerate(self.shards):
            health = lane.health
            ev = lane.evaluator
            out.append(
                {
                    "shard": i,
                    "routed": self.routed[i],
                    "batches": lane.stats["batches"],
                    "batched_requests": lane.stats["batched_requests"],
                    "inflight_peak": lane.stats["inflight_peak"],
                    "oracle_fallbacks": lane.stats["oracle_fallbacks"],
                    "batch_errors": lane.stats["batch_errors"],
                    "quarantined": lane.stats["quarantined"],
                    "breaker_state": health.state if health is not None else None,
                    "breaker_trips": health.stats["trips"] if health is not None else 0,
                    "occupancy": lane.m_occupancy.value,
                    "device_inputs": getattr(ev, "stats", {}).get("device_inputs", 0),
                    "device": str(getattr(ev, "device", None) or getattr(ev, "mesh", None) or ""),
                }
            )
        return out

    def routing_imbalance(self) -> float:
        """max/min over per-shard routed counts (1.0 = perfectly fair);
        counts of 0 make it infinity, reported as 0.0 before any traffic."""
        if not any(self.routed):
            return 0.0
        lo = min(self.routed)
        return float("inf") if lo == 0 else max(self.routed) / lo

    def health_state(self) -> str:
        """Aggregate breaker state for readiness: the pool is 'closed' while
        ANY lane takes device traffic (a sick shard degrades capacity, not
        availability), 'half_open' when the best lane is probing, and 'open'
        only when every lane refuses."""
        states = [
            lane.health.state for lane in self.shards if lane.health is not None
        ]
        if not states or STATE_CLOSED in states:
            return STATE_CLOSED
        if STATE_HALF_OPEN in states:
            return STATE_HALF_OPEN
        return STATE_OPEN


def build_shard_pool(
    base_evaluator: Any,
    *,
    n_shards: int = 0,
    per_shard_inflight: int = 0,
    routing: str = ROUTING_LEAST_LOADED,
    max_batch: int = 4096,
    max_wait_ms: float = 2.0,
    request_timeout_s: float = 30.0,
    inflight_depth: int = 3,
    quarantine_max: int = 128,
    breaker_conf: Optional[dict] = None,
    fault_spec: str = "",
) -> ShardedBatchingEvaluator:
    """Build the pool: clone the base evaluator once per shard placement,
    wrap each in its own fault domain (breaker + batcher lane), and front
    them with the router.

    ``fault_spec`` is the chaos grammar from ``engine/faults.py``; its
    ``shard:N`` knob scopes the injected faults to that one lane (the
    shard-kill chaos drill), otherwise every lane gets the wrapper.
    """
    from ..parallel.mesh import shard_devices
    from .faults import FaultInjector, parse_fault_spec
    from .health import DeviceHealth

    breaker_conf = breaker_conf or {}
    placements = shard_devices(n_shards or None)
    use_jax = bool(getattr(base_evaluator, "use_jax", False))
    if not use_jax:
        # numpy backend has no devices to spread over; still honor the
        # requested shard count so the fault-domain topology is testable
        n = max(1, int(n_shards)) if n_shards else len(placements)
        placements = [None] * n

    fault_shard: Optional[int] = None
    if fault_spec:
        knobs = parse_fault_spec(fault_spec)
        if knobs.get("shard") is not None:
            fault_shard = int(knobs["shard"])

    inflight = int(per_shard_inflight) or int(inflight_depth)
    lanes: list[BatchingEvaluator] = []
    for i, devices in enumerate(placements):
        ev = base_evaluator.shard_clone(devices, shard_id=i)
        dispatch: Any = ev
        if fault_spec and (fault_shard is None or fault_shard == i):
            dispatch = FaultInjector(ev, fault_spec)
        health = DeviceHealth(
            failure_threshold=int(breaker_conf.get("failureThreshold", 5)),
            timeout_rate_threshold=float(breaker_conf.get("timeoutRateThreshold", 0.5)),
            timeout_window_s=float(breaker_conf.get("timeoutWindowSeconds", 30)),
            timeout_min_samples=int(breaker_conf.get("timeoutMinSamples", 10)),
            probe_backoff_base_s=float(breaker_conf.get("probeBackoffBaseMs", 500)) / 1000.0,
            probe_backoff_cap_s=float(breaker_conf.get("probeBackoffCapMs", 30000)) / 1000.0,
            probe_timeout_s=float(breaker_conf.get("probeTimeoutMs", 5000)) / 1000.0,
            enabled=bool(breaker_conf.get("enabled", True)),
            shard_id=i,
        )
        lanes.append(
            BatchingEvaluator(
                dispatch,
                max_batch=max_batch,
                max_wait_ms=max_wait_ms,
                request_timeout_s=request_timeout_s,
                max_inflight=inflight,
                health=health,
                quarantine_max=quarantine_max,
                shard_id=i,
            )
        )
    _log.info(
        "sharded serving pool: %d shard(s), routing=%s, per-shard inflight=%d",
        len(lanes),
        routing,
        inflight,
    )
    return ShardedBatchingEvaluator(lanes, routing=routing)
