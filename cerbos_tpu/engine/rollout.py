"""Safe policy rollout: shadow-gated, epoch-versioned atomic cutover with a
live canary and automatic rollback (ROADMAP item 4's cutover substrate).

A policy reload used to be the least-defended moment in the serving path:
``RuleTableManager.on_storage_event`` rebuilt the table and then fired a
hand-chained stack of ``on_swap`` closures that mutated live engine state
one after another — a request in flight could evaluate half its inputs
against the old table and half against the new one, a pathological bundle
hit traffic with no gate beyond "build didn't throw", and there was no way
back. :class:`RolloutController` turns every swap into a staged, observable,
reversible rollout:

``build``
    the new :class:`RuleTable` is compiled off the serving path; failures
    keep the last valid epoch serving (the manager's historical contract).
``lower``
    the table is lowered off the serving path, proving the device lowering
    before any traffic can see it; the shadow lowering also feeds the gate.
``gate``
    the static analyzer (PR 14) runs against the shadow lowering —
    ``engine.tpu.rollout.failOn`` rejects e.g. ``oracle-only`` bundles
    outright — and the parity corpus plus a bounded sample of recently
    served inputs is differentially replayed old-vs-new. Effect diffs are
    summarized in the rollout report (an expected policy change is news,
    not an error) unless ``requireAck`` is set, in which case any diff
    rejects the swap.
``cutover``
    the new epoch — ``(rule_table, lowered tables, analyzer report, bundle
    hash, epoch N+1)`` — commits atomically: every batcher lane parks at a
    flight boundary (no device batch in flight), the named subscribers run
    while the world is stopped, lanes stamp the new epoch and resume. No
    request spans two tables; in-flight work keeps the epoch it started on.
``canary``
    for ``canarySec`` after cutover the parity sentinel samples at an
    elevated rate; a parity divergence / storm, a recompile storm (PR 5
    detector), or a pressure-score spike sustained above ``rollbackAt`` for
    ``holdSec`` rolls back to the still-resident epoch N automatically.
    ``cerbos-tpuctl store rollback`` gives operators the same lever.

Epoch numbers are never reused: a rollback reinstates epoch N (same number,
same table object) and the next successful rollout takes the next unused
number, so every decision's ``policyEpoch`` stamp maps to exactly one table
ever committed. The current epoch rides readiness snapshots and therefore
IPC STATUS frames, which is how ``--frontends`` processes observe cutovers
within a bounded, measured skew window
(``cerbos_tpu_policy_epoch_skew_seconds``).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..ruletable import check_input
from . import flight
from . import types as T

log = logging.getLogger("cerbos_tpu.rollout")

STAGE_BUILD = "build"
STAGE_LOWER = "lower"
STAGE_GATE = "gate"
STAGE_CUTOVER = "cutover"
STAGE_CANARY = "canary"
STAGES = (STAGE_BUILD, STAGE_LOWER, STAGE_GATE, STAGE_CUTOVER, STAGE_CANARY)

OUTCOME_SERVING = "serving"
OUTCOME_REJECTED = "rejected"
OUTCOME_FAILED = "failed"
OUTCOME_ROLLED_BACK = "rolled_back"
OUTCOME_IN_PROGRESS = "in_progress"
TERMINAL_OUTCOMES = (OUTCOME_SERVING, OUTCOME_REJECTED, OUTCOME_FAILED, OUTCOME_ROLLED_BACK)

# attribute stamped onto committed RuleTable objects; oracle paths that only
# hold a table reference (serial engine, batcher fallback) resolve their
# decision's epoch through it with no extra synchronization — the table was
# read once per request, so the (table, epoch) pair is consistent by design
EPOCH_ATTR = "policy_epoch"

_GATE_FINDINGS_MAX = 20
_DIFF_SAMPLES_MAX = 5


def epoch_of(rule_table: Any) -> Optional[int]:
    """The epoch a table was committed as, or None for never-committed
    tables (direct construction in tests, frontend-local rebuilds)."""
    return getattr(rule_table, EPOCH_ATTR, None)


def bundle_hash_of(rule_table: Any) -> str:
    """Stable content hash over the rule rows — the identity printed in
    rollout reports and flight events so operators can tie an epoch back to
    the bundle that produced it."""
    try:
        h = hashlib.sha256()
        rows = sorted(
            rule_table.idx.get_all_rows(), key=lambda r: (r.origin_fqn, r.id)
        )
        for r in rows:
            cond = r.condition
            cond_src = ""
            if cond is not None:
                cond_src = getattr(getattr(cond, "expr", None), "original", "") or cond.kind
            actions = r.action or "|".join(sorted(r.allow_actions or ()))
            h.update(
                f"{r.origin_fqn}|{r.id}|{r.evaluation_key}|{r.name}"
                f"|{r.effect}|{r.role}|{actions}|{cond_src}\n".encode()
            )
        return h.hexdigest()[:16]
    except Exception:  # noqa: BLE001 — identity is advisory, never fatal
        return ""


class RolloutFault(RuntimeError):
    """Raised by the ``swap_fail:STAGE`` fault knob (engine/faults.py)."""


@dataclass
class Epoch:
    """One immutable committed policy generation. Everything a cutover (or
    rollback) needs travels together: the table, its shadow lowering, the
    analyzer verdict, and the bundle identity."""

    number: Optional[int]
    rule_table: Any
    bundle_hash: str = ""
    committed_at: float = 0.0  # wall clock at commit (skew reference)
    analysis: Optional[dict] = None  # analyzer summary captured at the gate
    source: str = "rollout"  # boot | rollout | rollback | local
    # full AnalysisReport for the analysis subscriber to republish without
    # re-running the analyzer at commit time; not serialized
    analysis_report: Any = field(default=None, repr=False)
    lowered: Any = field(default=None, repr=False)

    def describe(self) -> dict:
        return {
            "epoch": self.number,
            "bundle_hash": self.bundle_hash,
            "committed_at": self.committed_at,
            "source": self.source,
            "analysis": self.analysis,
        }


class SwapBarrier:
    """Flight-boundary stop-the-world across batcher lanes.

    The controller hands the barrier to every lane via
    ``BatchingEvaluator.request_swap``; each drain loop finishes its current
    flights, submits nothing new, and calls :meth:`park`. Once every live
    lane is parked (or the bounded drain timeout expires — a wedged device
    must not hold a cutover hostage forever), the controller mutates the
    shared state and :meth:`release` resumes everyone."""

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = max(0.05, float(timeout_s))
        self._release = threading.Event()
        self._parked = threading.Semaphore(0)
        self.expected = 0
        self.timed_out = False

    def start(self, lanes: list) -> bool:
        """Request a park from every lane and wait for all of them to reach
        a flight boundary. Returns False when the drain timeout expired with
        lanes still in flight (the cutover proceeds anyway, recorded)."""
        self.expected = 0
        for lane in lanes:
            try:
                if lane.request_swap(self):
                    self.expected += 1
            except Exception:  # noqa: BLE001 — a dying lane never blocks cutover
                log.exception("rollout: lane refused swap barrier")
        deadline = time.monotonic() + self.timeout_s
        for _ in range(self.expected):
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self._parked.acquire(timeout=remaining):
                self.timed_out = True
                return False
        return True

    def park(self, lane: Any) -> None:
        """Called on a lane's drain thread at a flight boundary: report in,
        then hold position until the controller finishes the swap. The wait
        is bounded so a crashed controller can never wedge serving."""
        self._parked.release()
        self._release.wait(self.timeout_s * 2 + 1.0)

    def release(self) -> None:
        self._release.set()


class RolloutRun:
    """One staged rollout attempt: the stage ladder, the gate verdict, the
    canary result, and the terminal outcome — the report ``store reload
    --wait`` renders and ``/_cerbos/debug/rollout`` serves."""

    def __init__(self, generation: int, trigger: str, from_epoch: Optional[int]):
        self.generation = generation
        self.trigger = trigger
        self.from_epoch = from_epoch
        self.to_epoch: Optional[int] = None
        self.bundle_hash = ""
        self.outcome = OUTCOME_IN_PROGRESS
        self.stages: list[dict] = []
        self.gate: dict = {}
        self.canary: dict = {}
        self.error = ""
        self.started_at = time.time()
        self.finished_at: Optional[float] = None
        self.cancelled = False  # a newer rollout superseded the canary hold
        self._done = threading.Event()

    @property
    def terminal(self) -> bool:
        return self.outcome in TERMINAL_OUTCOMES

    @property
    def current_stage(self) -> str:
        return self.stages[-1]["stage"] if self.stages else ""

    def stage(self, name: str, status: str, seconds: float, **detail: Any) -> None:
        entry = {"stage": name, "status": status, "seconds": round(seconds, 6)}
        if detail:
            entry.update(detail)
        self.stages.append(entry)

    def finish(self, outcome: str, error: str = "") -> None:
        if self.terminal:
            return
        self.outcome = outcome
        self.error = error or self.error
        self.finished_at = time.time()
        self._done.set()

    def wait(self, timeout: float) -> bool:
        return self._done.wait(timeout)

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "trigger": self.trigger,
            "outcome": self.outcome,
            "from_epoch": self.from_epoch,
            "to_epoch": self.to_epoch,
            "bundle_hash": self.bundle_hash,
            "stages": list(self.stages),
            # underscore keys carry live objects (the AnalysisReport) for
            # the cutover path, not for serialization
            "gate": {k: v for k, v in self.gate.items() if not k.startswith("_")},
            "canary": dict(self.canary),
            "error": self.error,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
        }


class RolloutController:
    """Owns the swap path end to end: named subscribers replace the hand-
    chained ``on_swap`` closures, commits are epoch-versioned and atomic
    behind a lane drain barrier, and every attempt leaves a report.

    ``mode="full"`` gates, versions, and canaries (device-owning roles);
    ``mode="passive"`` only runs the subscriber registry on each rebuild
    (front ends — their epoch authority is the batcher's STATUS frames)."""

    def __init__(
        self,
        manager: Any,
        *,
        conf: Optional[dict] = None,
        mode: str = "full",
        globals_: Optional[dict] = None,
        schema_mgr: Any = None,
        sentinel: Any = None,
        faults: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        conf = dict(conf or {})
        self.manager = manager
        self.mode = mode
        self.globals_ = globals_ or {}
        self.schema_mgr = schema_mgr
        self.sentinel = sentinel
        self.faults = faults
        self._clock = clock

        self.enabled = bool(conf.get("enabled", True))
        self.fail_on = str(conf.get("failOn", "") or "")
        self.require_ack = bool(conf.get("requireAck", False))
        self.replay_max = max(0, int(conf.get("replayMax", 128)))
        self.canary_sec = max(0.0, float(conf.get("canarySec", 0.0)))
        self.canary_boost = float(conf.get("canaryBoost", 1.0))
        self.hold_sec = max(0.0, float(conf.get("holdSec", 5.0)))
        self.rollback_at = float(conf.get("rollbackAt", 0.9))
        self.canary_divergences = max(1, int(conf.get("canaryDivergences", 1)))
        self.drain_timeout_s = max(0.05, float(conf.get("drainTimeoutMs", 5000)) / 1000.0)
        self.poll_s = max(0.01, float(conf.get("canaryPollMs", 100)) / 1000.0)
        self.history_max = max(1, int(conf.get("epochHistory", 2)))
        self.runs_max = max(1, int(conf.get("runHistory", 8)))

        self._subs: list[tuple[str, Callable[[Epoch], None]]] = []
        self._lanes: list[Any] = []
        self._lock = threading.RLock()  # epoch / history / runs bookkeeping
        self._run_lock = threading.Lock()  # one rollout (or rollback) at a time
        self.epoch: Optional[Epoch] = None
        self.history: deque[Epoch] = deque(maxlen=self.history_max)
        self.runs: deque[RolloutRun] = deque(maxlen=self.runs_max)
        self.generation = 0
        self._max_number = 0
        self._canary_thread: Optional[threading.Thread] = None
        self._canary_run: Optional[RolloutRun] = None
        self._init_metrics()

    def _init_metrics(self) -> None:
        from ..observability import metrics

        reg = metrics()
        self.m_total = reg.counter_vec(
            "cerbos_tpu_rollout_total",
            "rollout stage transitions by outcome (ok/failed/rejected/rolled_back/pass)",
            label=("stage", "outcome"),
        )
        self.m_duration = reg.histogram_vec(
            "cerbos_tpu_rollout_duration_seconds",
            "wall time spent per rollout stage",
            label="stage",
            buckets=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0],
        )
        self.m_epoch = reg.gauge(
            "cerbos_tpu_policy_epoch",
            "policy epoch currently serving (monotone except across a rollback)",
        )

    # -- wiring ---------------------------------------------------------------

    def subscribe(self, name: str, fn: Callable[[Epoch], None]) -> None:
        """Register a named cutover subscriber. Subscribers run in
        registration order inside the stopped-world window; a failing
        subscriber is logged and skipped, never aborts a commit midway."""
        self._subs.append((name, fn))

    @property
    def subscribers(self) -> list[str]:
        return [name for name, _ in self._subs]

    def bind_lanes(self, lanes: list) -> None:
        """The batcher lanes that must park at a flight boundary before the
        shared lowered tables mutate (one BatchingEvaluator per shard, or a
        single-element list for the unsharded batcher)."""
        self._lanes = [lane for lane in lanes if lane is not None]

    def seed(self, rule_table: Any, source: str = "boot") -> Epoch:
        """Adopt the boot-time table as epoch 1 without gating (it is
        already serving — there is nothing to cut over from)."""
        ep = Epoch(
            number=1,
            rule_table=rule_table,
            bundle_hash=bundle_hash_of(rule_table),
            committed_at=time.time(),
            source=source,
        )
        with self._lock:
            self.epoch = ep
            self._max_number = max(self._max_number, 1)
        try:
            setattr(rule_table, EPOCH_ATTR, 1)
        except Exception:  # noqa: BLE001 — slots-style tables stay unstamped
            pass
        for lane in self._lanes:
            lane.epoch = 1
        self.m_epoch.set(1)
        return ep

    # -- fault hooks ----------------------------------------------------------

    def _fault_stage(self) -> str:
        spec = self.faults
        if not spec:
            return ""
        return str(spec.get("swap_fail", "") or "")

    def _fault_check(self, stage: str) -> None:
        if self._fault_stage() == stage:
            shard = self.faults.get("shard") if self.faults else None
            scope = f" (shard {shard})" if shard is not None else ""
            raise RolloutFault(f"injected swap_fail:{stage}{scope}")

    # -- the staged rollout ----------------------------------------------------

    def on_storage_event(self, events: Any = None) -> Optional[RolloutRun]:
        """The manager's storage-event delegate. Never raises: the store's
        notify path treats subscriber exceptions as lost, so every failure
        is captured in the run report instead."""
        try:
            if self.mode == "passive":
                return self._run_passive()
            return self.run_rollout(trigger="storage")
        except Exception:  # noqa: BLE001
            log.exception("rollout: unhandled failure; last valid epoch kept")
            return None

    def _run_passive(self) -> Optional[RolloutRun]:
        """Front-end rebuild: no gate, no epoch authority — just the named
        subscriber registry over the fresh local table."""
        try:
            rt = self.manager.build_table()
        except Exception:  # noqa: BLE001
            log.exception("policy reload failed; keeping last valid state")
            return None
        self.manager.commit_table(rt)
        self._notify_subscribers(Epoch(number=None, rule_table=rt, source="local"))
        return None

    def run_rollout(self, trigger: str = "storage") -> RolloutRun:
        self._cancel_canary()
        with self._run_lock:
            with self._lock:
                self.generation += 1
                old = self.epoch
                run = RolloutRun(
                    self.generation, trigger, old.number if old else None
                )
                self.runs.append(run)

            # build ----------------------------------------------------------
            try:
                rt = self._timed(run, STAGE_BUILD, self._stage_build)
            except Exception as e:  # noqa: BLE001 — keep last valid state
                log.error("policy reload failed; keeping last valid state: %s", e)
                run.finish(OUTCOME_FAILED, error=str(e))
                return run
            run.bundle_hash = bundle_hash_of(rt)

            if not self.enabled:
                run.stage(STAGE_LOWER, "skipped", 0.0)
                run.stage(STAGE_GATE, "skipped", 0.0)
                epoch = self._make_epoch(rt, None, None)
                self._timed(run, STAGE_CUTOVER, lambda: self._commit(epoch))
                run.to_epoch = epoch.number
                run.stage(STAGE_CANARY, "skipped", 0.0)
                run.finish(OUTCOME_SERVING)
                self.m_total.inc((STAGE_CUTOVER, "ok"))
                return run

            # lower ----------------------------------------------------------
            try:
                lowered = self._timed(run, STAGE_LOWER, lambda: self._stage_lower(rt))
            except Exception as e:  # noqa: BLE001
                log.error("rollout: lowering failed; keeping last valid state: %s", e)
                run.finish(OUTCOME_FAILED, error=str(e))
                return run

            # gate -----------------------------------------------------------
            t0 = self._clock()
            try:
                verdict = self._stage_gate(run, rt, lowered, old)
            except Exception as e:  # noqa: BLE001
                dt = self._clock() - t0
                run.stage(STAGE_GATE, "failed", dt, error=str(e))
                self.m_duration.observe(STAGE_GATE, dt)
                self.m_total.inc((STAGE_GATE, OUTCOME_FAILED))
                log.error("rollout: gate errored; keeping last valid state: %s", e)
                run.finish(OUTCOME_FAILED, error=str(e))
                return run
            dt = self._clock() - t0
            self.m_duration.observe(STAGE_GATE, dt)
            if verdict is not None:
                run.stage(STAGE_GATE, "rejected", dt, reason=verdict)
                self.m_total.inc((STAGE_GATE, OUTCOME_REJECTED))
                flight.recorder().record_event(
                    "rollout_rejected",
                    generation=run.generation,
                    reason=verdict,
                    bundle_hash=run.bundle_hash,
                )
                log.warning("rollout: bundle rejected at gate (%s); not serving it", verdict)
                run.finish(OUTCOME_REJECTED, error=verdict)
                return run
            run.stage(STAGE_GATE, "ok", dt, fail_on=self.fail_on or None)
            self.m_total.inc((STAGE_GATE, "ok"))

            # cutover --------------------------------------------------------
            report = run.gate.get("_analysis_report")
            run.gate.pop("_analysis_report", None)
            epoch = self._make_epoch(rt, lowered, report)
            self._timed(run, STAGE_CUTOVER, lambda: self._commit(epoch))
            run.to_epoch = epoch.number
            self.m_total.inc((STAGE_CUTOVER, "ok"))

            # canary ---------------------------------------------------------
            if self.canary_sec <= 0:
                run.stage(STAGE_CANARY, "skipped", 0.0)
                run.finish(OUTCOME_SERVING)
                return run
            self._start_canary(run, epoch)
            return run

    def _timed(self, run: RolloutRun, name: str, fn: Callable[[], Any]) -> Any:
        t0 = self._clock()
        try:
            out = fn()
        except Exception as e:
            dt = self._clock() - t0
            run.stage(name, "failed", dt, error=str(e))
            self.m_duration.observe(name, dt)
            self.m_total.inc((name, OUTCOME_FAILED))
            raise
        dt = self._clock() - t0
        run.stage(name, "ok", dt)
        self.m_duration.observe(name, dt)
        if name != STAGE_CUTOVER:  # cutover's ok is counted by the caller
            self.m_total.inc((name, "ok"))
        return out

    def _stage_build(self) -> Any:
        self._fault_check(STAGE_BUILD)
        return self.manager.build_table()

    def _stage_lower(self, rt: Any) -> Any:
        self._fault_check(STAGE_LOWER)
        from ..tpu.lowering import lower_table

        return lower_table(rt, self.globals_)

    def _stage_gate(
        self, run: RolloutRun, rt: Any, lowered: Any, old: Optional[Epoch]
    ) -> Optional[str]:
        """Run the analyzer and the differential replay. Returns a rejection
        reason, or None when the bundle may serve."""
        self._fault_check(STAGE_GATE)
        from ..tpu import analyze as _analyze

        report = _analyze.analyze_table(rt, self.globals_, lowered=lowered)
        run.gate["analysis"] = report.summary()
        run.gate["fail_on"] = self.fail_on
        run.gate["_analysis_report"] = report

        if self.fail_on:
            try:
                gate_failed = report.failed(self.fail_on)
            except ValueError as e:
                log.warning("rollout: unknown failOn %r ignored: %s", self.fail_on, e)
                gate_failed = False
            if gate_failed:
                run.gate["findings"] = [
                    {
                        "kind": f.kind,
                        "code": f.code,
                        "severity": f.severity,
                        "policy": f.policy,
                        "rule": f.rule_name,
                        "message": f.message,
                    }
                    for f in report.findings[:_GATE_FINDINGS_MAX]
                ]
                return f"analyzer:{self.fail_on}"

        replay = self._differential_replay(old.rule_table if old else None, rt)
        run.gate["replay"] = replay
        if self.require_ack and replay.get("diffs", 0) > 0:
            return f"diffs_require_ack:{replay['diffs']}"
        return None

    # -- differential replay ---------------------------------------------------

    def _replay_inputs(self) -> list:
        """Parity-corpus inputs plus the sentinel's bounded ring of recently
        sampled live inputs — the traffic the old table actually served."""
        inputs: list = []
        sent = self.sentinel
        if sent is None or self.replay_max == 0:
            return inputs
        corpus = getattr(sent, "corpus", None)
        corpus_dir = getattr(corpus, "dir", "") if corpus is not None else ""
        if corpus_dir:
            from .sentinel import DivergenceCorpus, input_from_json

            for _path, rec in DivergenceCorpus.load(corpus_dir):
                for ij in rec.get("inputs") or []:
                    try:
                        inputs.append(input_from_json(ij))
                    except Exception:  # noqa: BLE001 — a stale record never gates
                        pass
        recent = getattr(sent, "recent_inputs", None)
        if callable(recent):
            inputs.extend(recent())
        return inputs[-self.replay_max :]

    def _differential_replay(self, old_rt: Any, new_rt: Any) -> dict:
        from .sentinel import effect_rows

        inputs = self._replay_inputs()
        if old_rt is None or not inputs:
            return {"replayed": 0, "diffs": 0, "errors": 0, "samples": []}
        params = T.EvalParams()
        diffs: list[dict] = []
        errors = 0
        for inp in inputs:
            try:
                before = effect_rows([check_input(old_rt, inp, params, self.schema_mgr)])[0]
                after = effect_rows([check_input(new_rt, inp, params, self.schema_mgr)])[0]
            except Exception:  # noqa: BLE001 — replay is advisory
                errors += 1
                continue
            if before != after:
                diffs.append(
                    {
                        "principal": inp.principal.id,
                        "resource": f"{inp.resource.kind}:{inp.resource.id}",
                        "old": before,
                        "new": after,
                    }
                )
        return {
            "replayed": len(inputs),
            "diffs": len(diffs),
            "errors": errors,
            "samples": diffs[:_DIFF_SAMPLES_MAX],
        }

    # -- commit / rollback -----------------------------------------------------

    def _make_epoch(self, rt: Any, lowered: Any, report: Any) -> Epoch:
        with self._lock:
            number = self._max_number + 1
        return Epoch(
            number=number,
            rule_table=rt,
            bundle_hash=bundle_hash_of(rt),
            analysis=report.summary() if report is not None else None,
            analysis_report=report,
            lowered=lowered,
            source="rollout",
        )

    def _notify_subscribers(self, epoch: Epoch) -> None:
        for name, fn in self._subs:
            try:
                fn(epoch)
            except Exception:  # noqa: BLE001 — one bad subscriber, not a torn commit
                log.exception("rollout: subscriber %r failed during cutover", name)

    def _commit(self, epoch: Epoch, rollback: bool = False) -> None:
        """The atomic cutover: park every lane at a flight boundary, swap
        the world under the barrier, stamp lane epochs, resume."""
        epoch.committed_at = time.time()
        if epoch.number is not None:
            try:
                setattr(epoch.rule_table, EPOCH_ATTR, epoch.number)
            except Exception:  # noqa: BLE001
                pass
        barrier = SwapBarrier(timeout_s=self.drain_timeout_s)
        parked = barrier.start(self._lanes)
        if not parked:
            flight.recorder().record_event(
                "rollout_barrier_timeout",
                epoch=epoch.number,
                lanes=barrier.expected,
                timeout_s=self.drain_timeout_s,
            )
            log.warning(
                "rollout: %d lane(s) missed the %.2fs drain barrier; cutting over anyway",
                barrier.expected,
                self.drain_timeout_s,
            )
        try:
            self.manager.commit_table(epoch.rule_table)
            self._notify_subscribers(epoch)
            for lane in self._lanes:
                lane.epoch = epoch.number
        finally:
            barrier.release()
        with self._lock:
            prev = self.epoch
            if rollback:
                # reinstating history[-1]: remove it from history (it is
                # current again); the rolled-back epoch's table is dropped
                if self.history and self.history[-1] is not prev and self.history[-1].number == epoch.number:
                    self.history.pop()
            elif prev is not None:
                self.history.append(prev)
            self.epoch = epoch
            if epoch.number is not None:
                self._max_number = max(self._max_number, epoch.number)
        self.m_epoch.set(epoch.number or 0)
        flight.recorder().record_event(
            "rollout_cutover",
            epoch=epoch.number,
            from_epoch=prev.number if prev else None,
            bundle_hash=epoch.bundle_hash,
            source=epoch.source,
            barrier_parked=parked,
        )

    def rollback(self, reason: str = "operator", run: Optional[RolloutRun] = None) -> Optional[dict]:
        """Reinstate the still-resident previous epoch. Used by the canary
        (``run`` is the rollout being reverted) and by operators via
        ``cerbos-tpuctl store rollback`` (a synthetic run is recorded)."""
        if run is None:
            # operator-triggered: an active canary hold is watching the epoch
            # this rollback removes — stand it down before reverting
            self._cancel_canary()
        with self._run_lock:
            with self._lock:
                if not self.history:
                    return None
                prev = self.history[-1]
                bad = self.epoch
                if run is None:
                    self.generation += 1
                    run = RolloutRun(
                        self.generation, f"rollback:{reason}", bad.number if bad else None
                    )
                    self.runs.append(run)
            restored = Epoch(
                number=prev.number,
                rule_table=prev.rule_table,
                bundle_hash=prev.bundle_hash,
                analysis=prev.analysis,
                analysis_report=prev.analysis_report,
                lowered=prev.lowered,
                source="rollback",
            )
            t0 = self._clock()
            self._commit(restored, rollback=True)
            dt = self._clock() - t0
            run.stage("rollback", "ok", dt, reason=reason, restored_epoch=prev.number)
            self.m_duration.observe("rollback", dt)
            self.m_total.inc(("rollback", OUTCOME_ROLLED_BACK))
            flight.recorder().record_event(
                "rollout_rollback",
                reason=reason,
                from_epoch=bad.number if bad else None,
                to_epoch=prev.number,
            )
            log.warning(
                "rollout: rolled back epoch %s -> %s (%s)",
                bad.number if bad else None,
                prev.number,
                reason,
            )
            run.finish(OUTCOME_ROLLED_BACK, error=reason)
            return run.to_dict()

    # -- canary ----------------------------------------------------------------

    def _start_canary(self, run: RolloutRun, epoch: Epoch) -> None:
        sent = self.sentinel
        if sent is not None and self.canary_boost > 0:
            boost = getattr(sent, "set_boost", None)
            if callable(boost):
                boost(self.canary_boost, self.canary_sec)
        # baseline on THIS thread, at cutover: a divergence landing before
        # the watcher thread gets scheduled must count against the canary,
        # not silently fold into its baseline
        baseline = self._canary_baseline(sent)
        t = threading.Thread(
            target=self._canary_watch,
            args=(run, epoch, baseline),
            daemon=True,
            name=f"rollout-canary-{epoch.number}",
        )
        with self._lock:
            self._canary_thread = t
            self._canary_run = run
        t.start()

    def _cancel_canary(self) -> None:
        """A newer rollout supersedes an active canary hold: the held epoch
        is declared serving (the new rollout replaces it anyway)."""
        with self._lock:
            run, t = self._canary_run, self._canary_thread
            self._canary_run, self._canary_thread = None, None
        if run is not None and not run.terminal:
            run.cancelled = True
        if t is not None and t.is_alive() and t is not threading.current_thread():
            t.join(timeout=self.poll_s * 4 + 1.0)

    def _canary_baseline(self, sent: Any) -> tuple[int, int, int]:
        from ..tpu import compilestats

        base_div = base_storms = 0
        if sent is not None:
            st = sent.stats
            base_div = int(st.get("divergences", 0))
            base_storms = int(st.get("storms", 0))
        return base_div, base_storms, compilestats.stats().detector.storms

    def _canary_watch(
        self, run: RolloutRun, epoch: Epoch, baseline: tuple[int, int, int]
    ) -> None:
        from . import pressure
        from ..tpu import compilestats

        sent = self.sentinel
        base_div, base_storms, base_compile = baseline
        mon = pressure.monitor()

        t0 = self._clock()
        deadline = t0 + self.canary_sec
        hard_deadline = deadline + self.hold_sec
        over_since: Optional[float] = None
        trigger = ""
        while True:
            now = self._clock()
            if now >= deadline and (over_since is None or now >= hard_deadline):
                break
            if run.cancelled:
                run.canary["result"] = "superseded"
                run.finish(OUTCOME_SERVING)
                return
            time.sleep(self.poll_s)
            if self._fault_stage() == STAGE_CANARY:
                trigger = "fault:swap_fail:canary"
                break
            if sent is not None:
                st = sent.stats
                if int(st.get("storms", 0)) - base_storms > 0:
                    trigger = "parity_storm"
                    break
                div = int(st.get("divergences", 0)) - base_div
                run.canary["divergences"] = div
                if div >= self.canary_divergences:
                    trigger = f"parity_divergence:{div}"
                    break
            if compilestats.stats().detector.storms - base_compile > 0:
                trigger = "recompile_storm"
                break
            score = float(getattr(mon, "last_score", 0.0))
            run.canary["pressure"] = score
            if score > self.rollback_at:
                over_since = over_since if over_since is not None else self._clock()
                if self._clock() - over_since >= self.hold_sec:
                    trigger = f"pressure:{score:.2f}"
                    break
            else:
                over_since = None

        dt = self._clock() - t0
        self.m_duration.observe(STAGE_CANARY, dt)
        with self._lock:
            if self._canary_run is run:
                self._canary_run, self._canary_thread = None, None
        if trigger:
            run.canary["trigger"] = trigger
            run.stage(STAGE_CANARY, "rolled_back", dt, trigger=trigger)
            self.m_total.inc((STAGE_CANARY, OUTCOME_ROLLED_BACK))
            self.rollback(reason=trigger, run=run)
        else:
            run.canary["result"] = "pass"
            run.stage(STAGE_CANARY, "ok", dt)
            self.m_total.inc((STAGE_CANARY, "pass"))
            run.finish(OUTCOME_SERVING)

    # -- introspection ---------------------------------------------------------

    def epoch_info(self) -> dict:
        """The epoch block merged into readiness snapshots — and therefore
        into IPC STATUS frames, which is how front ends learn about
        cutovers (``committed_at`` is the skew reference)."""
        with self._lock:
            ep = self.epoch
            run = self.runs[-1] if self.runs else None
        if ep is None or ep.number is None:
            return {}
        out: dict = {
            "policy_epoch": ep.number,
            "policy_epoch_committed_at": ep.committed_at,
        }
        if run is not None and not run.terminal:
            out["rollout_stage"] = run.current_stage or OUTCOME_IN_PROGRESS
        return out

    def snapshot(self) -> dict:
        """The ``/_cerbos/debug/rollout`` payload."""
        with self._lock:
            ep = self.epoch
            history = [e.describe() for e in self.history]
            runs = [r.to_dict() for r in self.runs]
            lanes = [
                {"epoch": getattr(lane, "epoch", None)} for lane in self._lanes
            ]
        return {
            "mode": self.mode,
            "epoch": ep.describe() if ep is not None else None,
            "history": history,
            "lanes": lanes,
            "runs": runs,
            "config": {
                "enabled": self.enabled,
                "failOn": self.fail_on,
                "requireAck": self.require_ack,
                "replayMax": self.replay_max,
                "canarySec": self.canary_sec,
                "canaryBoost": self.canary_boost,
                "holdSec": self.hold_sec,
                "rollbackAt": self.rollback_at,
                "canaryDivergences": self.canary_divergences,
                "drainTimeoutMs": self.drain_timeout_s * 1000.0,
                "epochHistory": self.history_max,
            },
        }

    def wait_report(self, after_generation: int, timeout: float = 60.0) -> Optional[dict]:
        """Block until a run newer than ``after_generation`` reaches a
        terminal stage and return its report (``store reload --wait``)."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._lock:
                candidates = [r for r in self.runs if r.generation > after_generation]
            for r in candidates:
                if r.terminal:
                    return r.to_dict()
            waiter = candidates[0] if candidates else None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if waiter is not None:
                waiter.wait(min(remaining, 0.25))
            else:
                time.sleep(min(remaining, 0.05))

    def close(self) -> None:
        self._cancel_canary()


# -- process-wide handle ------------------------------------------------------

# the debug endpoint and admin handlers reach the controller through the
# Core; the module-level handle mirrors analyze.publish()'s semantics for
# surfaces with no Core reference (last bootstrap wins — fine in a process
# that serves one engine, which is every production topology)
_active: Optional[RolloutController] = None


def install(controller: Optional[RolloutController]) -> None:
    global _active
    _active = controller


def active() -> Optional[RolloutController]:
    return _active
