from .types import (  # noqa: F401
    ActionEffect,
    AuxData,
    CheckInput,
    CheckOutput,
    EvalParams,
    OutputEntry,
    Principal,
    Resource,
    ValidationError,
)
from .engine import Engine  # noqa: F401
