"""Staged brownout: shed optional work as pressure rises, restore as it falls.

Driven by ``PressureMonitor.sample()`` (an observer registered at
bootstrap), the controller walks a declared ladder of stages as the
pressure score crosses each stage's threshold — one stage per observation,
with hysteresis and a hold time so breaker blips and scrape jitter cannot
flap the ladder:

- ``shed_audit``         — stop audit log writes (cheapest loss first:
                           the decision still happens, only its record is
                           dropped);
- ``shed_parity``        — pause parity-sentinel shadow sampling (frees
                           the CPU oracle for degraded-path traffic);
- ``shed_plan``          — refuse plan queries (analytical traffic yields
                           to interactive checks);
- ``shed_low_priority``  — refuse sheddable admission classes outright.

A stage ENGAGES after the score holds at/above its ``enterAbove`` for
``holdSeconds``; it DISENGAGES after the score holds below
``enterAbove - hysteresis`` for the same hold. Every transition is
edge-logged, flight-recorded (``brownout_enter`` / ``brownout_exit``),
counted, and surfaced in readiness (``reason: "brownout"`` + the deepest
engaged stage) so operators see shed state where they already look.

Effects are applied two ways: push appliers bound at bootstrap (the audit
log's and parity sentinel's shed flags — restored to their configured
behavior on exit) and pull checks (``active("shed_plan")`` from the plan
handlers, the admission controller's low-priority shed flag). Each process
in a ``--frontends`` topology runs its own controller on its own pressure
monitor — sheds happen where the work lives (audit/plan at the front ends,
parity in the batcher), and the batcher's stage reaches front-end readiness
through the existing status-poll snapshot. One process-global instance
(the flight-recorder pattern); ``clock`` injectable for tests.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..observability import metrics
from . import flight

_log = logging.getLogger("cerbos_tpu.engine.brownout")

# the default ladder: cheapest loss first, refusals last
DEFAULT_STAGES = [
    {"name": "shed_audit", "enterAbove": 0.85},
    {"name": "shed_parity", "enterAbove": 0.90},
    {"name": "shed_plan", "enterAbove": 0.95},
    {"name": "shed_low_priority", "enterAbove": 0.98},
]
DEFAULT_HYSTERESIS = 0.05
DEFAULT_HOLD_S = 2.0


class BrownoutStage:
    __slots__ = ("name", "enter", "exit")

    def __init__(self, name: str, enter: float, hysteresis: float):
        self.name = str(name)
        self.enter = max(0.0, min(1.0, float(enter)))
        self.exit = max(0.0, self.enter - max(0.0, float(hysteresis)))


class BrownoutController:
    """Walks the stage ladder one step per pressure observation."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        reg = metrics()
        self.m_stage = reg.gauge(
            "cerbos_tpu_brownout_stage",
            "engaged brownout stages (0 = none, N = the first N stages of the declared ladder)",
        )
        self.m_transitions = reg.counter_vec(
            "cerbos_tpu_brownout_transitions_total",
            "brownout stage transitions by stage and direction (enter/exit)",
            label=("stage", "direction"),
        )
        self.m_shed = reg.counter_vec(
            "cerbos_tpu_brownout_shed_total",
            "work shed while a brownout stage was engaged, by target "
            "(audit / parity / plan / class)",
            label="target",
        )
        self._clock = clock
        self._lock = threading.Lock()
        self.enabled = False
        self.hold_s = DEFAULT_HOLD_S
        self.stages: list[BrownoutStage] = []
        self._level = 0
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        # stage name -> applier(engaged: bool); bound by bootstrap
        self._appliers: dict[str, Callable[[bool], None]] = {}

    # -- configuration (bootstrap, once) ------------------------------------

    def configure(self, conf: Optional[dict]) -> None:
        """Compile the ``overload.brownout`` block; resets to level 0 (any
        engaged appliers are released first so a reload never leaves work
        shed)."""
        conf = conf or {}
        hysteresis = float(conf.get("hysteresis", DEFAULT_HYSTERESIS))
        raw = conf.get("stages")
        if raw is None:
            raw = DEFAULT_STAGES
        stages = [
            BrownoutStage(s.get("name", ""), s.get("enterAbove", 1.0), hysteresis)
            for s in raw
            if s.get("name")
        ]
        with self._lock:
            self._disengage_all_locked()
            self.enabled = bool(conf.get("enabled", True)) and bool(stages)
            self.hold_s = max(0.0, float(conf.get("holdSeconds", DEFAULT_HOLD_S)))
            self.stages = stages
            self._above_since = self._below_since = None

    def bind_applier(self, stage_name: str, fn: Callable[[bool], None]) -> None:
        """Register the side effect of one stage (e.g. the audit log's shed
        flag). Called with True on enter, False on exit; exceptions are
        swallowed — a broken applier must not wedge the control loop."""
        self._appliers[str(stage_name)] = fn

    def reset(self) -> None:
        """Release every engaged stage (tests, re-initialization)."""
        with self._lock:
            self._disengage_all_locked()
            self._above_since = self._below_since = None

    # -- control loop (pressure observer) -----------------------------------

    def observe(
        self,
        score: float,
        components: Optional[dict] = None,
        now: Optional[float] = None,
    ) -> None:
        """One pressure observation. Never raises: this runs inside the
        pressure monitor's sampling path."""
        try:
            self._observe(float(score), now)
        except Exception:  # noqa: BLE001
            _log.exception("brownout controller observation failed")

    def _observe(self, score: float, now: Optional[float]) -> None:
        with self._lock:
            if not self.enabled or not self.stages:
                return
            now = self._clock() if now is None else now
            entered = exited = None
            # ascend: next stage's enter threshold held for hold_s
            if self._level < len(self.stages) and score >= self.stages[self._level].enter:
                if self._above_since is None:
                    self._above_since = now
                if now - self._above_since >= self.hold_s:
                    entered = self.stages[self._level]
                    self._level += 1
                    # a deeper stage needs a fresh hold of ITS threshold
                    self._above_since = None
            else:
                self._above_since = None
            # descend: current stage's exit threshold held for hold_s
            if (
                entered is None
                and self._level > 0
                and score < self.stages[self._level - 1].exit
            ):
                if self._below_since is None:
                    self._below_since = now
                if now - self._below_since >= self.hold_s:
                    self._level -= 1
                    exited = self.stages[self._level]
                    self._below_since = None
            else:
                self._below_since = None
            level = self._level
        if entered is not None:
            self._transition(entered, True, score, level)
        if exited is not None:
            self._transition(exited, False, score, level)

    def _transition(self, stage: BrownoutStage, engaged: bool, score: float, level: int) -> None:
        direction = "enter" if engaged else "exit"
        self.m_stage.set(float(level))
        self.m_transitions.inc((stage.name, direction))
        flight.recorder().record_event(
            f"brownout_{direction}",
            stage=stage.name,
            score=round(score, 4),
            level=level,
        )
        log = _log.warning if engaged else _log.info
        log(
            "brownout %s: %s (pressure %.3f, %d/%d stages engaged)",
            direction,
            stage.name,
            score,
            level,
            len(self.stages),
        )
        self._apply(stage.name, engaged)

    def _apply(self, stage_name: str, engaged: bool) -> None:
        fn = self._appliers.get(stage_name)
        if fn is None:
            return
        try:
            fn(engaged)
        except Exception:  # noqa: BLE001
            _log.exception("brownout applier for %s failed", stage_name)

    def _disengage_all_locked(self) -> None:
        while self._level > 0:
            self._level -= 1
            stage = self.stages[self._level]
            self.m_transitions.inc((stage.name, "exit"))
            self._apply(stage.name, False)
        self.m_stage.set(0.0)

    # -- reads (servers, readiness, admission) ------------------------------

    def level(self) -> int:
        return self._level

    def active(self, stage_name: str) -> bool:
        """Is the named stage currently engaged? (pull-side shed checks)"""
        with self._lock:
            for i in range(self._level):
                if self.stages[i].name == stage_name:
                    return True
        return False

    def stage_name(self) -> str:
        """Deepest engaged stage name, or '' — the readiness provider."""
        with self._lock:
            return self.stages[self._level - 1].name if self._level > 0 else ""

    def note_shed(self, target: str) -> None:
        """Count one unit of shed work (an audit entry dropped, a plan
        refused, ...) against the brownout evidence trail."""
        self.m_shed.inc(target)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "level": self._level,
                "stage": self.stages[self._level - 1].name if self._level > 0 else "",
                "hold_seconds": self.hold_s,
                "stages": [
                    {
                        "name": s.name,
                        "enter": s.enter,
                        "exit": s.exit,
                        "engaged": i < self._level,
                    }
                    for i, s in enumerate(self.stages)
                ],
            }


_controller = BrownoutController()


def controller() -> BrownoutController:
    return _controller
