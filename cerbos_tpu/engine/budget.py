"""Per-request latency-budget waterfall and goodput accounting.

PR 4 instrumented *batches* (``cerbos_tpu_batch_stage_seconds``); this
module instruments *requests*: a compact stage-timestamp record created at
ingress (before the request body is even decoded, so parse cost is visible)
and carried with the request through admission, the IPC hop, the batcher
queue, the device window, settlement, and reply encoding. Each stage is the
delta between consecutive marks, so the stage durations tile the request's
wall clock by construction — the reconciliation property bench/loadtest
assert (≥95% of p99 wall attributed to named stages).

Cross-process carriage reuses ``engine/ipc.py``'s deadline idiom: monotonic
clocks are process-local, so only RELATIVE values cross the socket. The
front end ships ``(age, attributed)`` — how old the request is and how much
of that age its stages already explain — and the batcher re-anchors
``t0 = now - age`` on its own clock, booking the unexplained remainder as
the ``transit`` stage. The reply carries the batcher-side stages plus its
final age back, and the front end books the residual as ``ipc_return``.
Clock skew between the processes cancels exactly the way it does for
deadlines.

On top of the waterfall:

- **goodput accounting** — ``cerbos_tpu_decisions_total{outcome=...}``
  splits throughput from goodput: ``deadline_met`` (served by the device
  path inside its budget), ``oracle_fallback`` (served correctly, but by
  the CPU oracle after a device-path degradation), ``expired`` (deadline
  blown — a 504 the caller already gave up on), ``refused`` (rejected at
  admission, e.g. request limits).
- **slow-request ring** — a bounded ring (the ``engine/flight.py``
  pattern) of the waterfalls of requests slower than a threshold, served
  at ``/_cerbos/debug/slow`` with the flight recorder's ``?shard=``
  filter; each entry carries the trace id so an operator can pivot to the
  trace and the flight-recorder batch.
- ``cerbos_tpu_deadline_budget_remaining_seconds{point,shard}`` — the
  remaining deadline budget sampled at enqueue and at device-submit, so
  requests that reach the device already near-expired are visible before
  ROADMAP item 5 adds early refusal.

One process-global tracker (the flight-recorder pattern): bootstrap
configures it from ``engine.tpu.latencyBudget.*``, every layer marks
through it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..observability import metrics

# stage glossary, in waterfall order (docs/OBSERVABILITY.md "Latency
# budget & pressure" documents the boundaries)
STAGE_INGRESS_PARSE = "ingress_parse"    # raw bytes on the wire → request decoded
STAGE_ADMISSION = "admission"            # decoded → accepted into the engine (validate, convert, span setup)
STAGE_IPC_ENCODE = "ipc_encode"          # ticket encoded for the shared batcher (front-end topology)
STAGE_TRANSIT = "transit"                # front-end send → batcher receipt (cross-process)
STAGE_QUEUE_WAIT = "queue_wait"          # batcher enqueue → drain-loop pickup
STAGE_PACK = "pack"                      # host staging + device dispatch of the batch
STAGE_DEVICE = "device"                  # device in-flight window (submit return → collect return)
STAGE_COLLECT = "collect"                # device readback + row decode
STAGE_SETTLE = "settle"                  # result slicing + future settlement (includes in-flight slot waits)
STAGE_IPC_RETURN = "ipc_return"          # batcher settle → response frame on the front end
STAGE_REPLY_ENCODE = "reply_encode"      # engine result → response bytes
STAGE_EVALUATE = "evaluate"              # non-batched evaluation (serial path / direct device call)
STAGE_ORACLE = "oracle"                  # CPU-oracle evaluation after a device-path degradation

STAGES = (
    STAGE_INGRESS_PARSE,
    STAGE_ADMISSION,
    STAGE_IPC_ENCODE,
    STAGE_TRANSIT,
    STAGE_QUEUE_WAIT,
    STAGE_PACK,
    STAGE_DEVICE,
    STAGE_COLLECT,
    STAGE_SETTLE,
    STAGE_IPC_RETURN,
    STAGE_REPLY_ENCODE,
    STAGE_EVALUATE,
    STAGE_ORACLE,
)

OUTCOME_MET = "deadline_met"
OUTCOME_EXPIRED = "expired"
OUTCOME_ORACLE = "oracle_fallback"
OUTCOME_REFUSED = "refused"
OUTCOMES = (OUTCOME_MET, OUTCOME_EXPIRED, OUTCOME_ORACLE, OUTCOME_REFUSED)

POINT_ENQUEUE = "enqueue"
POINT_DEVICE_SUBMIT = "device_submit"

# request stages span ~100µs (reply encode) to seconds (queue under
# overload); the default registry buckets bottom out at 1ms and would
# blur every fast stage into one bucket
_STAGE_BUCKETS = [
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
]
# budget remaining is read against deadlines of ~10ms..30s
_BUDGET_BUCKETS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0]


class Waterfall:
    """One request's stage-timestamp record.

    Owned by exactly one thread at a time (it migrates with the request:
    request thread → drain thread → writer thread), so marks are
    lock-free. ``mark`` books the delta since the previous mark; ``add``
    books an externally measured duration and advances the cursor by it,
    so a later ``mark`` only books the residual — the invariant throughout
    is that the recorded stages tile ``[t0, _last]`` exactly.
    """

    __slots__ = (
        "t0", "wall_ns", "stages", "_last", "trace_id", "deadline",
        "shard", "served_by", "fallback_reason",
    )

    def __init__(
        self,
        t0: Optional[float] = None,
        wall_ns: Optional[int] = None,
        trace_id: str = "",
        deadline: Optional[float] = None,
    ):
        now = time.monotonic() if t0 is None else t0
        self.t0 = now
        self._last = now
        self.wall_ns = time.time_ns() if wall_ns is None else wall_ns
        self.stages: list[tuple[str, float]] = []
        self.trace_id = trace_id
        self.deadline = deadline
        self.shard: Optional[int] = None
        self.served_by = "device"
        self.fallback_reason = ""

    def mark(self, stage: str, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        dur = max(0.0, now - self._last)
        self.stages.append((stage, dur))
        self._last = now
        return dur

    def add(self, stage: str, dur: float) -> None:
        dur = max(0.0, float(dur))
        self.stages.append((stage, dur))
        self._last += dur

    def age(self, now: Optional[float] = None) -> float:
        return max(0.0, (time.monotonic() if now is None else now) - self.t0)

    def attributed(self) -> float:
        return sum(d for _, d in self.stages)

    def note_fallback(self, reason: str) -> None:
        self.served_by = "oracle"
        self.fallback_reason = reason or ""

    # -- cross-process carriage (relative values only; see module doc) ------

    def carry(self, now: Optional[float] = None) -> tuple[float, float]:
        """Ship over IPC: (age of the request, seconds already attributed)."""
        return (self.age(now), self.attributed())

    @classmethod
    def from_carry(
        cls,
        spec,
        trace_id: str = "",
        deadline: Optional[float] = None,
    ) -> "Waterfall":
        """Batcher side: re-anchor ``t0`` on the local monotonic clock from
        the carried age (the deadline re-anchoring idiom) and book the
        unattributed remainder — encode, socket, frame decode — as
        ``transit``."""
        # index reads, not unpacking: a newer front end may append carry
        # elements (the admission class rides as spec[2]) that this record
        # does not consume
        age, attributed = spec[0], spec[1]
        now = time.monotonic()
        wf = cls(t0=now - max(0.0, float(age)), trace_id=trace_id, deadline=deadline)
        wf._last = wf.t0 + min(max(0.0, float(attributed)), wf.age(now))
        wf.mark(STAGE_TRANSIT, now=now)
        return wf

    def reply_spec(self, now: Optional[float] = None):
        """Batcher side: everything the front end needs to splice the
        batcher-visible stages back into its own record."""
        return (
            self.age(now),
            list(self.stages),
            self.served_by,
            self.fallback_reason,
            self.shard,
        )

    def splice_reply(self, spec, now: Optional[float] = None) -> None:
        """Front-end side: append the batcher's stages and book the
        residual — writer-thread encode, socket, response decode — as
        ``ipc_return``."""
        now = time.monotonic() if now is None else now
        _age_b, stages_b, served_by, reason, shard = spec
        self.stages.extend((str(s), max(0.0, float(d))) for s, d in stages_b)
        if served_by == "oracle":
            self.note_fallback(str(reason))
        if shard is not None:
            self.shard = int(shard)
        ret = (now - self.t0) - self.attributed()
        self.stages.append((STAGE_IPC_RETURN, max(0.0, ret)))
        self._last = now

    def snapshot(self) -> dict:
        """Slow-ring / debug-endpoint entry (milliseconds for humans)."""
        total = self.attributed()
        out = {
            "trace_id": self.trace_id,
            "total_ms": round(total * 1000, 3),
            "stages": [(s, round(d * 1000, 3)) for s, d in self.stages],
            "served_by": self.served_by,
            "wall_time_ns": self.wall_ns,
        }
        if self.shard is not None:
            out["shard"] = self.shard
        if self.fallback_reason:
            out["fallback_reason"] = self.fallback_reason
        if self.deadline is not None:
            out["budget_remaining_ms"] = round((self.deadline - self._last) * 1000, 3)
        return out


class BudgetTracker:
    """Process-global waterfall config, metric families, and slow ring."""

    def __init__(self, slow_capacity: int = 64, slow_threshold_s: float = 0.25):
        reg = metrics()
        self.m_stage = reg.histogram_vec(
            "cerbos_tpu_request_stage_seconds",
            "Per-request latency-budget waterfall: seconds spent in each named stage",
            label=("stage", "shard"),
            buckets=_STAGE_BUCKETS,
        )
        self.m_total = reg.histogram(
            "cerbos_tpu_request_total_seconds",
            "Per-request wall clock from ingress to reply encode (the waterfall total)",
            buckets=_STAGE_BUCKETS,
        )
        self.m_budget = reg.histogram_vec(
            "cerbos_tpu_deadline_budget_remaining_seconds",
            "Deadline budget remaining at the sampled point (enqueue, device_submit); 0 = already expired",
            label=("point", "shard"),
            buckets=_BUDGET_BUCKETS,
        )
        self.m_decisions = reg.counter_vec(
            "cerbos_tpu_decisions_total",
            "Decisions by API and outcome: deadline_met, oracle_fallback, expired, "
            "refused (goodput = met + fallback); api=plan books PlanResources "
            "traffic so shed_plan brownouts show as refused instead of vanishing",
            label=("api", "outcome"),
        )
        self.m_slow = reg.counter(
            "cerbos_tpu_slow_requests_total",
            "Requests slower than latencyBudget.slowThresholdMs (captured in the slow ring)",
        )
        self.enabled = True
        self.slow_threshold_s = float(slow_threshold_s)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(slow_capacity))
        # (stage, shard) → child Histogram, bypassing the vec-level lock on
        # the per-request flush; the key space is small (13 stages × shards)
        # and plain-dict reads are GIL-atomic, so misses just fall through
        # to the locked labels() path once
        self._stage_children: dict = {}
        self._budget_children: dict = {}

    def configure(
        self,
        enabled: Optional[bool] = None,
        slow_capacity: Optional[int] = None,
        slow_threshold_ms: Optional[float] = None,
    ) -> None:
        """Mutate in place (the flight-recorder pattern) so references held
        by already-wired layers stay valid."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if slow_threshold_ms is not None:
                self.slow_threshold_s = float(slow_threshold_ms) / 1000.0
            if slow_capacity is not None and slow_capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, int(slow_capacity)))

    # -- record lifecycle ---------------------------------------------------

    def start(
        self,
        trace_id: str = "",
        deadline: Optional[float] = None,
        t0: Optional[float] = None,
        wall_ns: Optional[int] = None,
    ) -> Optional[Waterfall]:
        if not self.enabled:
            return None
        return Waterfall(t0=t0, wall_ns=wall_ns, trace_id=trace_id, deadline=deadline)

    def resume(self, spec, trace_id: str = "", deadline: Optional[float] = None) -> Optional[Waterfall]:
        """Batcher side of the IPC hop: rebuild the record from the carried
        relative spec (None when the front end runs with the budget off)."""
        if not self.enabled or spec is None:
            return None
        try:
            return Waterfall.from_carry(spec, trace_id=trace_id, deadline=deadline)
        except Exception:  # noqa: BLE001 — a malformed carry must not fail the request
            return None

    def observe_budget(self, point: str, remaining: float, shard: Optional[int] = None) -> None:
        if not self.enabled:
            return
        key = (point, str(shard if shard is not None else 0))
        child = self._budget_children.get(key)
        if child is None:
            child = self.m_budget.labels(key)
            self._budget_children[key] = child
        child.observe(max(0.0, remaining))

    def finish(
        self,
        wf: Optional[Waterfall],
        outcome: str,
        final_stage: Optional[str] = None,
        api: str = "check",
    ) -> None:
        """Count the decision and flush the waterfall's stages to the
        histograms; slower-than-threshold requests land in the slow ring."""
        self.m_decisions.inc((api, outcome))
        if wf is None:
            return
        now = time.monotonic()
        if final_stage is not None:
            wf.mark(final_stage, now=now)
        shard = str(wf.shard if wf.shard is not None else 0)
        children = self._stage_children
        for stage, dur in wf.stages:
            child = children.get((stage, shard))
            if child is None:
                child = self.m_stage.labels((stage, shard))
                children[(stage, shard)] = child
            child.observe(dur)
        total = wf.attributed()
        self.m_total.observe(total)
        if total >= self.slow_threshold_s:
            self.m_slow.inc()
            entry = wf.snapshot()
            entry["outcome"] = outcome
            with self._lock:
                self._ring.append(entry)

    def count(self, outcome: str, api: str = "check") -> None:
        """Goodput accounting for the waterfall-disabled path."""
        self.m_decisions.inc((api, outcome))

    # -- slow ring ----------------------------------------------------------

    def slow_dump(self, shard: Optional[int] = None, top: int = 0) -> dict:
        with self._lock:
            entries = list(self._ring)
            capacity = self._ring.maxlen
        if shard is not None:
            entries = [e for e in entries if e.get("shard", 0) == shard]
        entries.sort(key=lambda e: e.get("total_ms", 0.0), reverse=True)
        if top > 0:
            entries = entries[:top]
        return {
            "capacity": capacity,
            "threshold_ms": round(self.slow_threshold_s * 1000, 3),
            "enabled": self.enabled,
            "requests": entries,
        }

    def reset(self) -> None:
        """Test hook: drop captured slow requests."""
        with self._lock:
            self._ring.clear()


_tracker = BudgetTracker()


def tracker() -> BudgetTracker:
    return _tracker
